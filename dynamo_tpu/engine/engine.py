"""JaxEngine: the first-party TPU engine behind the AsyncEngine interface.

This is the component the reference delegates to vLLM/SGLang/TRT-LLM
subprocesses (launch/dynamo-run/src/subprocess/vllm_inc.py:53-120); here it
is first-party: ``generate(Context[PreprocessedRequest]) ->
AsyncIterator[Annotated[LLMEngineOutput-dict]]`` -- the token-level
``ExecutionContext`` shape of the reference (lib/llm/src/backend.rs:60).

Threading model: one asyncio task drives ticks; device dispatches run in a
single-worker executor thread so the event loop keeps serving I/O while XLA
executes.  All scheduler state is touched either inside an executor call or
between them (the tick awaits each call), so no locks are needed.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime.engine import Annotated, Context, ResponseStream
from ..protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from ..tokens.sequence import TokenBlock
from .config import ModelConfig
from .kv_cache import PagedKVCache
from .model import Params, init_params
from .sampling import SamplingParams
from .scheduler import Scheduler, SchedulerConfig, SeqState, StepEvent
from .step import decode_step, pick_bucket, prefill_buckets, prefill_step, sample_step

logger = logging.getLogger("dynamo.engine")


@dataclass
class EngineConfig:
    max_batch_size: int = 8
    max_seq_len: int = 2048
    page_size: int = 16
    num_pages: int = 512
    block_size: Optional[int] = None  # router-visible KV block size
    seed: int = 0
    dtype: Optional[str] = None


@dataclass
class ForwardPassMetrics:
    """Worker load metrics published to the KV router
    (reference kv_router/protocols.rs:43-62; 'gpu_*' names kept for parity)."""

    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0
    request_active_slots: int = 0
    request_total_slots: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return self.__dict__.copy()


class JaxEngine:
    """Continuous-batching JAX engine over a paged KV cache."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        params: Params,
        cfg: Optional[EngineConfig] = None,
        kv_sharding: Optional[jax.sharding.Sharding] = None,
    ) -> None:
        self.model_cfg = model_cfg
        self.cfg = cfg or EngineConfig()
        self.params = params
        self.kv = PagedKVCache(
            model_cfg,
            num_pages=self.cfg.num_pages,
            page_size=self.cfg.page_size,
            dtype=self.cfg.dtype,
            sharding=kv_sharding,
        )
        self.sched = Scheduler(
            SchedulerConfig(
                max_batch_size=self.cfg.max_batch_size,
                max_seq_len=self.cfg.max_seq_len,
                page_size=self.cfg.page_size,
                block_size=self.cfg.block_size,
            ),
            self.kv.allocator,
        )
        self.buckets = prefill_buckets(self.cfg.page_size, self.cfg.max_seq_len)
        self._rng = jax.random.PRNGKey(self.cfg.seed)
        self._queues: Dict[str, asyncio.Queue] = {}
        self._cancelled: set = set()
        self._wake: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._ex = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="jax-engine"
        )
        self._running = False
        # KV event sink: fn(event_dict) -- wired to the router event publisher
        self.kv_event_sink: Optional[Callable[[Dict[str, Any]], None]] = None
        self._prefix_hits = 0
        self._prefix_lookups = 0
        self._steps = 0
        self._tokens_generated = 0

    # -- lifecycle ----------------------------------------------------------

    @classmethod
    def random_init(
        cls,
        model_cfg: ModelConfig,
        cfg: Optional[EngineConfig] = None,
        seed: int = 0,
    ) -> "JaxEngine":
        params = init_params(model_cfg, jax.random.PRNGKey(seed))
        return cls(model_cfg, params, cfg)

    @classmethod
    def from_pretrained(
        cls, model_path: str, cfg: Optional[EngineConfig] = None
    ) -> "JaxEngine":
        from .weights import load_safetensors_params

        model_cfg = ModelConfig.from_pretrained(model_path)
        params = load_safetensors_params(model_path, model_cfg)
        return cls(model_cfg, params, cfg)

    async def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._wake = asyncio.Event()
        self._task = asyncio.create_task(self._run(), name="jax-engine-loop")

    async def stop(self) -> None:
        self._running = False
        if self._wake is not None:
            self._wake.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        self._ex.shutdown(wait=False)

    # -- AsyncEngine --------------------------------------------------------

    async def generate(self, request: Context[Any]) -> AsyncIterator[Annotated]:
        """Token-level generate; yields Annotated[LLMEngineOutput-dict]."""
        if not self._running:
            await self.start()
        data = request.data
        if isinstance(data, dict):
            req = PreprocessedRequest.from_dict(data)
        else:
            req = data
        seq = SeqState.from_request(request.id, req, self.sched.block_size)
        ctx = request.ctx
        try:
            self.sched.enqueue(seq)
        except ValueError as e:
            # surface as an error item, matching the remote prologue-error path
            message = str(e)

            async def err_stream() -> AsyncIterator[Annotated]:
                yield Annotated.from_error(message)

            return ResponseStream(ctx, err_stream())
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[request.id] = queue
        assert self._wake is not None
        self._wake.set()

        async def stream() -> AsyncIterator[Annotated]:
            try:
                while True:
                    get = asyncio.ensure_future(queue.get())
                    stop_waiter = asyncio.ensure_future(ctx.stopped())
                    done, _ = await asyncio.wait(
                        {get, stop_waiter}, return_when=asyncio.FIRST_COMPLETED
                    )
                    if get not in done:
                        get.cancel()
                        stop_waiter.cancel()
                        self._cancelled.add(request.id)
                        self._wake.set()
                        yield Annotated.from_data(
                            LLMEngineOutput.finished(FinishReason.CANCELLED).to_dict()
                        )
                        return
                    stop_waiter.cancel()
                    item = get.result()
                    if item is None:
                        return
                    yield item
            finally:
                self._queues.pop(request.id, None)

        return ResponseStream(ctx, stream())

    # -- metrics ------------------------------------------------------------

    def metrics(self) -> ForwardPassMetrics:
        alloc = self.kv.allocator
        hit_rate = (
            self._prefix_hits / self._prefix_lookups if self._prefix_lookups else 0.0
        )
        return ForwardPassMetrics(
            kv_active_blocks=alloc.used_pages,
            kv_total_blocks=alloc.num_pages - 1,
            num_requests_waiting=self.sched.num_waiting,
            gpu_cache_usage_perc=self.kv.usage,
            gpu_prefix_cache_hit_rate=hit_rate,
            request_active_slots=self.sched.num_active,
            request_total_slots=self.cfg.max_batch_size,
        )

    @property
    def tokens_generated(self) -> int:
        return self._tokens_generated

    # -- the tick loop ------------------------------------------------------

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        assert self._wake is not None
        while self._running:
            try:
                self._process_cancellations()
                if not self.sched.has_work:
                    self._wake.clear()
                    await self._wake.wait()
                    continue
                plan = self.sched.plan()
                for seq, prompt_len in plan.prefills:
                    ev = await loop.run_in_executor(
                        self._ex, self._do_prefill, seq, prompt_len
                    )
                    self._dispatch([ev])
                if plan.run_decode and self.sched.num_active > 0:
                    events = await loop.run_in_executor(self._ex, self._do_decode)
                    self._dispatch(events)
                if not plan.prefills and not plan.run_decode:
                    self._handle_stalled_admission()
                # yield so enqueue/cancel callbacks interleave
                await asyncio.sleep(0)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # engine must never die silently
                logger.exception("engine tick failed")
                self._fail_all(f"engine error: {e}")
                await asyncio.sleep(0.01)

    def _handle_stalled_admission(self) -> None:
        """Nothing running, nothing admitted: requests whose prompts can never
        fit the page pool must fail instead of spinning the loop forever."""
        sched = self.sched
        if sched.num_active > 0 or not sched.waiting:
            return
        head = sched.waiting[0]
        reason = (
            f"request needs more KV pages than the pool holds "
            f"({len(head.prompt)} prompt tokens, "
            f"{sched.allocator.num_pages - 1} pages of {sched.cfg.page_size})"
        )
        # With no active sequences, no pages will ever free up -- anything
        # unadmittable now is unadmittable forever.
        sched.waiting.popleft()
        self._fail_seq(head, reason)

    def _fail_seq(self, seq: SeqState, message: str) -> None:
        queue = self._queues.get(seq.request_id)
        if queue is not None:
            queue.put_nowait(Annotated.from_error(message))
            queue.put_nowait(None)

    def _fail_all(self, message: str) -> None:
        for seq in list(self.sched.waiting) + [
            s for s in self.sched.slots if s is not None
        ]:
            self._fail_seq(seq, message)
            self.sched.cancel(seq)

    def _process_cancellations(self) -> None:
        if not self._cancelled:
            return
        by_id = {}
        for s in self.sched.slots:
            if s is not None:
                by_id[s.request_id] = s
        for s in self.sched.waiting:
            by_id[s.request_id] = s
        for rid in list(self._cancelled):
            self._cancelled.discard(rid)
            seq = by_id.get(rid)
            if seq is not None:
                self._publish_removed(seq)
                self.sched.cancel(seq)

    # -- device work (executor thread) --------------------------------------

    def _sampling_arrays(self, seqs: List[Optional[SeqState]]) -> SamplingParams:
        n = len(seqs)
        temp = np.zeros((n,), np.float32)
        top_p = np.ones((n,), np.float32)
        top_k = np.zeros((n,), np.int32)
        for i, s in enumerate(seqs):
            if s is None:
                continue
            so = s.sampling
            if so.temperature is not None:
                temp[i] = so.temperature
            elif so.top_p is not None or so.top_k is not None:
                # unset temperature with explicit top_p/top_k means "sample":
                # default temperature 1.0, not greedy
                temp[i] = 1.0
            top_p[i] = so.top_p if so.top_p is not None else 1.0
            top_k[i] = so.top_k or 0
        return SamplingParams(
            temperature=jnp.asarray(temp),
            top_p=jnp.asarray(top_p),
            top_k=jnp.asarray(top_k),
        )

    def _next_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _do_prefill(self, seq: SeqState, prompt_len: int) -> StepEvent:
        # Prefix-cache reuse lands with the block-manager integration; until
        # then every lookup is an honest miss (hit counter stays 0).
        self._prefix_lookups += 1
        self._prefix_hits += 1 if seq.cached_prompt_tokens else 0
        bucket = pick_bucket(self.buckets, prompt_len)
        n_pages = bucket // self.cfg.page_size
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :prompt_len] = seq.prompt
        page_table = np.zeros((1, n_pages), np.int32)
        page_table[0, : len(seq.pages)] = seq.pages
        seq_lens = np.asarray([prompt_len], np.int32)

        t0 = time.monotonic()
        logits, self.kv.pages = prefill_step(
            self.params,
            self.model_cfg,
            self.kv.pages,
            jnp.asarray(tokens),
            jnp.asarray(seq_lens),
            jnp.asarray(page_table),
        )
        sp = self._sampling_arrays([seq])
        sampled = sample_step(logits, self._next_rng(), sp)
        token = int(np.asarray(sampled)[0])
        logger.debug(
            "prefill id=%s len=%d bucket=%d %.1fms",
            seq.request_id, prompt_len, bucket, (time.monotonic() - t0) * 1e3,
        )
        self._steps += 1
        return self.sched.commit_prefill_token(seq, token)

    def _do_decode(self) -> List[StepEvent]:
        self.sched.ensure_decode_capacity()
        logits, self.kv.pages = decode_step(
            self.params,
            self.model_cfg,
            self.kv.pages,
            jnp.asarray(self.sched.tokens),
            jnp.asarray(self.sched.seq_lens),
            jnp.asarray(self.sched.page_table),
        )
        sp = self._sampling_arrays(list(self.sched.slots))
        sampled = sample_step(logits, self._next_rng(), sp)
        self._steps += 1
        return self.sched.commit_tokens(np.asarray(sampled))

    # -- event/output dispatch (loop thread) --------------------------------

    def _dispatch(self, events: List[StepEvent]) -> None:
        for ev in events:
            queue = self._queues.get(ev.seq.request_id)
            if ev.token is not None:
                self._tokens_generated += 1
            if ev.completed_blocks:
                self._publish_stored(ev.seq, ev.completed_blocks)
            if queue is None:
                continue
            if ev.token is not None:
                out = LLMEngineOutput(token_ids=[ev.token])
                queue.put_nowait(Annotated.from_data(out.to_dict()))
            if ev.finished is not None:
                out = LLMEngineOutput.finished(ev.finished)
                queue.put_nowait(Annotated.from_data(out.to_dict()))
                queue.put_nowait(None)
                self._publish_removed(ev.seq)

    def _publish_stored(self, seq: SeqState, blocks: List[TokenBlock]) -> None:
        if self.kv_event_sink is None:
            return
        self.kv_event_sink(
            {
                "type": "stored",
                "blocks": [
                    {
                        "block_hash": b.block_hash,
                        "sequence_hash": b.sequence_hash,
                        "parent_sequence_hash": b.parent_sequence_hash,
                        "position": b.position,
                    }
                    for b in blocks
                ],
            }
        )

    def _publish_removed(self, seq: SeqState) -> None:
        if self.kv_event_sink is None or seq.blocks is None:
            return
        hashes = seq.blocks.sequence_hashes()
        if hashes:
            self.kv_event_sink({"type": "removed", "sequence_hashes": hashes})
