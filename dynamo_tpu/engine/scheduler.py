"""Continuous-batching scheduler: host-side state feeding fixed-shape device
steps.

Behavioral spec comes from the reference mocker scheduler / KV manager split
(lib/llm/src/mocker/scheduler.rs:185, kv_manager.rs:55) and vLLM-style
continuous batching, re-shaped for XLA: the device sees a fixed-capacity
decode batch (``max_batch_size`` lanes) and bucket-padded prefill shapes;
all variability -- admission, slot assignment, page growth, stop conditions,
preemption -- lives here on the host.

The scheduler is sans-IO: it owns numpy mirrors of the device-side batch
arrays (tokens / seq_lens / page_table) and pure-Python bookkeeping; the
engine drives it and runs the actual jitted steps.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from ..block_manager import PagePool
from ..spec.drafter import spec_live
from ..protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from ..tokens.sequence import TokenBlock, TokenBlockSequence
from .kv_cache import OutOfPages, PageAllocator


@dataclass
class KVAdmitConfig:
    """KV-budget admission model (ROADMAP item 5 / FlowKV): admit against
    a *predicted KV-page* commitment instead of slot count, so one 128k
    prompt neither grabs a slot it cannot feed nor blocks the queue while
    short traffic could still fit.

    The predictor charges each request its peak pages -- sequence length
    plus decode headroom (``remaining_budget``, optionally capped by
    ``headroom_tokens``) -- against ``util * pool - reserve_pages``.  A
    head that does not fit is *skipped over* (short traffic keeps
    admitting, up to ``max_skips`` per pass) until it has aged past
    ``floor_s`` seconds; from then on no request passes it, so freed
    pages accumulate for the head instead of feeding newcomers -- the
    fairness floor in both directions.  Admission order changes; token
    streams never do.

    Armed via ``SchedulerConfig.kv_admit`` (engine:
    ``EngineConfig.kv_admit_budget`` / ``DYN_KV_ADMIT_BUDGET``)."""

    # fraction of the (trash-page-excluded) pool the predictor may commit
    util: float = 0.9
    # cap on the predicted decode headroom per request, tokens; None =
    # the request's full remaining token budget (max_tokens-capped)
    headroom_tokens: Optional[int] = None
    # pages withheld from the predictor (swap-restore / onboard slack)
    reserve_pages: int = 0
    # fairness floor: once the queue head has waited this long, nothing
    # skips past it
    floor_s: float = 2.0
    # max requests admitted past a blocked head per planning pass
    max_skips: int = 4


def parse_kv_admit_spec(spec: Any) -> Optional[KVAdmitConfig]:
    """Parse a ``DYN_KV_ADMIT_BUDGET`` value into a :class:`KVAdmitConfig`
    (None = slot-count admission).

    Grammar: ``0``/``off`` disarms, ``1``/``on`` arms the defaults, or a
    comma-separated ``k=v`` list::

        DYN_KV_ADMIT_BUDGET=util=0.9,headroom=256,reserve=16,floor_s=2,skips=4
    """
    if spec is None:
        return None
    if isinstance(spec, KVAdmitConfig):
        return spec
    if isinstance(spec, bool):
        return KVAdmitConfig() if spec else None
    s = str(spec).strip()
    if not s or s.lower() in ("0", "off", "false", "no"):
        return None
    out = KVAdmitConfig()
    if s.lower() in ("1", "on", "true", "yes"):
        return out
    for clause in filter(None, (c.strip() for c in s.split(","))):
        k, sep, v = clause.partition("=")
        k = k.strip().lower()
        if not sep:
            raise ValueError(f"malformed DYN_KV_ADMIT_BUDGET clause {clause!r}")
        if k not in ("util", "headroom", "reserve", "floor_s", "skips"):
            raise ValueError(f"unknown DYN_KV_ADMIT_BUDGET key {k!r}")
        try:
            if k == "util":
                out.util = float(v)
            elif k == "headroom":
                out.headroom_tokens = int(v)
            elif k == "reserve":
                out.reserve_pages = int(v)
            elif k == "floor_s":
                out.floor_s = float(v)
            elif k == "skips":
                out.max_skips = int(v)
        except ValueError as e:
            raise ValueError(f"bad DYN_KV_ADMIT_BUDGET value {clause!r}") from e
    return out


@dataclass
class SchedulerConfig:
    max_batch_size: int = 8
    max_seq_len: int = 2048
    page_size: int = 16
    # max prompts prefilled per tick (each prefill is one async device
    # dispatch); None = as many as there are free slots.  Uncapped admission
    # fills the decode batch in one tick, so a burst of N prompts costs one
    # partially-occupied decode block instead of N
    max_prefill_per_tick: Optional[int] = None
    # KV block size for router-visible block identity (token hashing); usually
    # equals page_size but decoupled (reference recommends 128 for routing).
    block_size: Optional[int] = None
    # data-parallel groups of the serving mesh: slot b belongs to dp group
    # b // (max_batch_size / dp_groups), because the engine's decode-state
    # arrays shard batch-major over ``dp``.  Admission balances lanes
    # across groups (see _free_slot) so one dp shard never carries the
    # whole batch while its peers idle -- per-chip throughput under
    # partial load depends on it.  1 = no mesh, first-free admission.
    dp_groups: int = 1
    # KV-budget admission (None = legacy slot-count admission); see
    # KVAdmitConfig.  Changes which tick a request admits on, never its
    # tokens.
    kv_admit: Optional[KVAdmitConfig] = None


@dataclass
class SeqState:
    """One in-flight request."""

    request_id: str
    prompt: List[int]
    stop: StopConditions
    sampling: SamplingOptions
    eos_ids: List[int]
    arrival_s: float = field(default_factory=time.monotonic)
    slot: int = -1
    # page_table view: shared (reused) pages first, then owned pages
    pages: List[int] = field(default_factory=list)
    blocks: Optional[TokenBlockSequence] = None  # router-visible block identity
    # llava-style soft prompt: [T_img, hidden] f32 rows injected over the
    # first T_img prompt positions at prefill (None = text-only)
    mm_embeds: Optional[Any] = None
    num_generated: int = 0
    # tokens generated before the last preemption (already streamed to the
    # client); stop-condition accounting uses prior_generated + num_generated
    prior_generated: int = 0
    finish: Optional[FinishReason] = None
    # number of prompt tokens whose KV was reused from a prefix-cache match
    cached_prompt_tokens: int = 0
    # registry refs this sequence holds (reused prefix + own registered blocks)
    held_blocks: List[int] = field(default_factory=list)
    # pages allocated to (and freed by) this sequence alone
    owned_pages: List[int] = field(default_factory=list)
    # completed blocks whose final token's KV is not yet written (it lands
    # with the next decode step); registered once the cache catches up
    pending_register: List[TokenBlock] = field(default_factory=list)
    # offload-tier hits awaiting their device scatter: (seq_hash, pages,
    # blob, meta) -- the engine scatters + registers them at prefill time
    pending_onboard: List[Any] = field(default_factory=list)
    # prefix-cache stats are counted once per request (first admission)
    stats_counted: bool = False
    # disaggregation: prompt KV arrives from a remote prefill worker; the
    # lane holds pages but stays inactive until delivery
    awaiting_kv: bool = False
    # chunked prefill: prompt tokens whose KV has been dispatched so far;
    # the lane stays decode-inactive while prefilling is True
    prefilled_tokens: int = 0
    prefilling: bool = False
    # speculative decoding: the request's knobs (SpeculationOptions | None)
    # and, once the engine arms the lane, its live spec.SpecState.  A lane
    # with spec armed is DEVICE-inactive for the decode scan -- it advances
    # through the engine's batched verify dispatches instead, driven from
    # the host mirrors.
    speculation: Optional[Any] = None
    spec: Optional[Any] = None
    # echo+logprobs: top-N prompt logprobs to compute at first prefill
    prompt_logprobs: Optional[int] = None
    prompt_lp_sent: bool = False
    # queue-side prefetch accounting: offloaded prefix blocks found
    # host-staged at admission because the prefetch walk promoted them
    # during queue wait (engine._note_prefetch_admission; span attr +
    # dynamo_kv_prefetch_hits)
    prefetch_hits: int = 0
    # SLO attainment plane (runtime/slo.py): admission stamp closing the
    # queue-wait leg, and a once-only latch for the first-token
    # queue/service decomposition note
    admitted_s: float = 0.0
    slo_noted: bool = False

    @property
    def seq_len(self) -> int:
        return len(self.prompt) + self.num_generated

    @classmethod
    def from_request(cls, request_id: str, req: PreprocessedRequest, block_size: int) -> "SeqState":
        import numpy as np

        mm = None
        if req.mm_embeds:
            mm = np.asarray(req.mm_embeds, np.float32)
        return cls(
            request_id=request_id,
            prompt=list(req.token_ids),
            stop=req.stop_conditions,
            sampling=req.sampling_options,
            eos_ids=list(req.eos_token_ids),
            # multimodal prompts opt out of prefix caching: the block hash
            # chain is computed over token ids, and the placeholder ids for
            # embedding positions would alias across different images
            blocks=(
                None
                if mm is not None
                else TokenBlockSequence(req.token_ids, block_size=block_size)
            ),
            mm_embeds=mm,
            speculation=req.speculation,
            prompt_logprobs=req.prompt_logprobs,
        )


@dataclass
class TickPlan:
    """What the engine must execute this tick."""

    # prompts to prefill: (seq, bucket_len) -- each is one prefill dispatch
    prefills: List[Tuple[SeqState, int]] = field(default_factory=list)


@dataclass
class MixedChunk:
    """One lane's contribution of prompt tokens to a unified mixed-batch
    dispatch: ``final`` means the chunk completes the prompt, so the
    dispatch samples the lane's first token."""

    seq: SeqState
    start: int  # first prompt position this chunk covers
    length: int  # tokens in the chunk
    final: bool


@dataclass
class StepEvent:
    """Per-request outcome of a tick (tokens emitted and/or finished).

    ``tokens`` carries every token the tick emitted for the request -- a
    whole decode block's worth coalesces into ONE event (commit_block), so
    downstream per-event costs (queue put, consumer wakeup, SSE frame build)
    are paid per block, not per token.  Order within the list is emission
    order."""

    seq: SeqState
    tokens: List[int] = field(default_factory=list)
    finished: Optional[FinishReason] = None
    completed_blocks: List[TokenBlock] = field(default_factory=list)
    # aligned with ``tokens`` when the dispatch carried logprob data:
    # chosen-token logprobs, and per-token top-N alternatives as
    # [[token_id, logprob], ...] (None when the dispatch ran without tops)
    logprobs: List[float] = field(default_factory=list)
    top_logprobs: Optional[List[List[List[float]]]] = None
    # echo+logprobs: per-prompt-position [token_id, logprob|None, top|None]
    # entries, attached by the engine to the request's first event
    prompt_logprobs: Optional[List[Any]] = None

    @property
    def token(self) -> Optional[int]:
        """Single-token view for the prefill/first-token paths (and tests)."""
        return self.tokens[0] if self.tokens else None


class Scheduler:
    def __init__(self, cfg: SchedulerConfig, allocator: PageAllocator) -> None:
        self.cfg = cfg
        self.allocator = allocator
        self.block_size = cfg.block_size or cfg.page_size
        # prefix-cache reuse runs when the allocator is a PagePool (has a
        # sequence-hash registry) and router blocks align to whole pages
        self.pool: Optional[PagePool] = (
            allocator
            if isinstance(allocator, PagePool)
            and self.block_size % cfg.page_size == 0
            else None
        )
        self.pages_per_block = self.block_size // cfg.page_size
        # G2/G3 offload lookup: fn(seq_hash) -> (blob, meta) | None, wired
        # by the engine when offload tiers are configured
        self.offload_lookup: Optional[Any] = None
        # swap-based preemption hook: fn(seq) -> bool, wired by the engine
        # when the offload plane is armed.  Called with the victim still
        # slotted (pages intact) so the engine can dispatch the device
        # snapshot before the slot release frees them; True parks the
        # sequence for a KV restore instead of a re-prefill.
        self.swap_out: Optional[Any] = None
        self.preempt_swap = 0
        self.preempt_recompute = 0
        # KV-budget admission (None = slot-count): counters back the
        # long-context bench and the starvation tests
        self.kv_admit = cfg.kv_admit
        self.admit_skips = 0  # admissions that passed a blocked head
        self.admit_blocked = 0  # passes whose head did not fit the budget
        # K-granular admission (ISSUE 16): tokens a decode lane may grow
        # by before the scheduler can react again -- the engine sets this
        # to its multi-step K x pipeline depth each tick, so the budget
        # planner charges every decode-phase lane at least that much
        # uncommitted in-flight growth and an admission decision can never
        # be invalidated by a block that was already dispatched
        self.decode_inflight_tokens = 0
        # observability hook (engine/metrics.EngineMetrics): the scheduler
        # stays sans-IO -- it only pokes gauges the engine wired in
        self.metrics: Optional[Any] = None
        B = cfg.max_batch_size
        self.max_pages = cfg.max_seq_len // cfg.page_size
        self.waiting: Deque[SeqState] = collections.deque()
        self.slots: List[Optional[SeqState]] = [None] * B
        # slotted lanes whose prompt KV the mixed-batch plane still owes
        # (unified ragged dispatches pack their chunks; see
        # form_mixed_chunks)
        self.mix_pending: List[SeqState] = []
        # numpy mirrors of the device batch arrays
        self.tokens = np.zeros((B,), np.int32)
        self.seq_lens = np.zeros((B,), np.int32)
        self.page_table = np.zeros((B, self.max_pages), np.int32)
        # layout_version: slot membership changed (admission / release /
        # preemption).  growth_version: pages were appended to live lanes --
        # the engine refreshes the device page table and limits, keeping the
        # decode pipeline running.  dirty_slots: lanes whose mirrors changed
        # (admission/release); the engine folds them into the device-resident
        # decode state with per-row scatters instead of a full rebuild, so
        # the decode pipeline never drains for batch-membership changes.
        self.layout_version = 0
        self.growth_version = 0
        self.dirty_slots: set = set()

    # -- queue/observability -------------------------------------------------

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    @property
    def num_runnable(self) -> int:
        """Slotted lanes the device can actually step (parked awaiting_kv /
        mid-chunked-prefill lanes hold a slot + pages but must not spin
        decode blocks)."""
        return sum(
            1
            for s in self.slots
            if s is not None and not s.awaiting_kv and not s.prefilling
        )

    @property
    def num_decode_runnable(self) -> int:
        """Runnable lanes the decode SCAN should step: actively
        speculating lanes are excluded -- they advance via the engine's
        verify columns (host-mirror driven), and a decode block over
        only-spec lanes would burn a dispatch on dead rows.  A lane whose
        speculation auto-disabled is a plain decode lane again and counts
        (``spec.drafter.spec_live``: the same predicate the engine's
        eligibility sites consult)."""
        return sum(
            1
            for s in self.slots
            if s is not None
            and not s.awaiting_kv
            and not s.prefilling
            and not spec_live(s.spec)
        )

    @property
    def has_work(self) -> bool:
        return self.num_active > 0 or len(self.waiting) > 0

    @property
    def has_runnable_work(self) -> bool:
        """Work the tick loop can make progress on *right now*; a batch of
        only parked lanes sleeps until a delivery (or timeout) wakes it."""
        return self.num_runnable > 0 or len(self.waiting) > 0

    def enqueue(self, seq: SeqState) -> None:
        if not seq.prompt:
            raise ValueError("empty prompt (zero tokens after preprocessing)")
        if len(seq.prompt) > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt of {len(seq.prompt)} tokens exceeds max_seq_len "
                f"{self.cfg.max_seq_len}"
            )
        self.waiting.append(seq)

    # -- admission -----------------------------------------------------------

    def remaining_budget(self, seq: SeqState) -> int:
        """Tokens the sequence may still emit (max_tokens / max_seq_len caps)."""
        produced = seq.prior_generated + seq.num_generated
        by_max = (
            seq.stop.max_tokens - produced
            if seq.stop.max_tokens is not None
            else self.cfg.max_seq_len
        )
        by_len = self.cfg.max_seq_len - seq.seq_len
        return max(0, min(by_max, by_len))

    def min_total_pages(self, seq: SeqState) -> int:
        """Smallest page count that lets the sequence make forward progress:
        the prompt KV plus, when at least one decode step must run, the write
        slot for the next token.  (A single-token request samples its only
        token from the prefill logits and never decodes.)"""
        n = len(seq.prompt)
        if self.remaining_budget(seq) >= 2:
            n += 1
        return -(-n // self.cfg.page_size)

    def plan(self) -> TickPlan:
        """Admit waiting requests into free slots (page permitting), then
        decide whether a decode step runs.

        With ``kv_admit`` unset the queue admits strictly FIFO against
        slot count + the physical page floor.  With it set, admission
        runs the KV-budget model (:class:`KVAdmitConfig`): predicted
        peak pages gate each candidate, and a head that does not fit is
        skipped over -- bounded by the fairness floor -- so short
        traffic and one long prompt make progress together."""
        plan = TickPlan()
        cap = self.cfg.max_prefill_per_tick
        if self.kv_admit is not None:
            self._plan_budget(plan, cap)
        else:
            while self.waiting and (cap is None or len(plan.prefills) < cap):
                slot = self._free_slot()
                if slot is None:
                    break
                if not self._try_admit(self.waiting[0], plan, slot):
                    break
                self.waiting.popleft()
        # decode dispatch gating lives in the engine tick loop, keyed on
        # num_decode_runnable AFTER this tick's lane parking: a tick whose
        # slots hold only parked / mid-prefill / speculating lanes must
        # not pay a device dispatch for dead rows
        if self.metrics is not None:
            self.metrics.observe_sched(len(self.waiting), self.num_active)
        return plan

    def _try_admit(self, seq: SeqState, plan: TickPlan, slot: int) -> bool:
        """Admit one request into ``slot`` if the physical page floor
        allows; returns False (state untouched) otherwise.  The one
        admission body both planners share."""
        # remote-prefilled prompts arrive as one full-prompt KV blob; a
        # shared reused prefix would be overwritten by the scatter, so
        # external admissions take fresh pages only (reuse is the local
        # prefill path's optimization)
        cached_pages = [] if seq.awaiting_kv else self._match_prefix(seq)
        if seq.awaiting_kv:
            seq.cached_prompt_tokens = 0
        n_pages = -(-len(seq.prompt) // self.cfg.page_size)
        # admission needs room for the prompt *and* the first decode
        # write, with one page of headroom per active seq for growth;
        # reused prefix pages are already resident and cost nothing
        need = self.min_total_pages(seq) - len(cached_pages)
        if self.allocator.free_pages < need + self.num_active:
            self._unmatch_prefix(seq)
            return False
        fresh = self.allocator.alloc(n_pages - len(cached_pages))
        # onboard pages were allocated inside _match_prefix and stay
        # plain-owned until the engine registers them post-scatter
        onboard = [
            p for _h, pgs, _b, _m in seq.pending_onboard for p in pgs
        ]
        seq.owned_pages = onboard + fresh
        seq.pages = cached_pages + fresh
        seq.slot = slot
        # SLO queue-wait/service decomposition stamp (runtime/slo.py):
        # admission ends the queue-wait leg; re-admissions after
        # preemption re-stamp (the first-token note fires only once)
        seq.admitted_s = time.monotonic()
        self.slots[slot] = seq
        self._write_slot_arrays(seq)
        self._queue_prompt_registrations(seq)
        if not seq.awaiting_kv:
            plan.prefills.append((seq, len(seq.prompt)))
        # awaiting_kv lanes hold their pages and stay device-inactive
        # until the remote prefill delivers (engine.deliver_external)
        return True

    def predicted_pages(self, seq: SeqState) -> int:
        """Predicted peak KV pages for a request under the budget model:
        current sequence length (the prompt, for a queued request) plus
        decode headroom -- the remaining token budget, optionally capped
        by ``headroom_tokens``.  Never below what the sequence already
        holds, never above the per-lane page ceiling."""
        adm = self.kv_admit
        remaining = self.remaining_budget(seq)
        head = remaining
        if adm is not None and adm.headroom_tokens is not None:
            head = min(head, adm.headroom_tokens)
        if (
            seq.slot is not None
            and not seq.prefilling
            and not seq.awaiting_kv
        ):
            # a decode-phase lane has up to decode_inflight_tokens of
            # uncommitted multi-step growth in flight: charge at least
            # that (still capped by what it may legally emit), even when
            # headroom_tokens clamps tighter
            head = max(head, min(self.decode_inflight_tokens, remaining))
        n = min(seq.seq_len + head, self.cfg.max_seq_len)
        pages = -(-n // self.cfg.page_size)
        return max(min(pages, self.max_pages), len(seq.pages))

    def _plan_budget(self, plan: TickPlan, cap: Optional[int]) -> None:
        """KV-budget admission pass (see :class:`KVAdmitConfig`)."""
        adm = self.kv_admit
        now = time.monotonic()
        usable = self.allocator.num_pages - 1  # trash page excluded
        budget = max(int(usable * adm.util) - adm.reserve_pages, 1)
        committed = sum(
            self.predicted_pages(s) for s in self.slots if s is not None
        )

        # fairness floor: an aged head stops all skip-ahead, so pages
        # freed by completions accumulate for it instead of feeding
        # newcomers behind it.  Evaluated against the CURRENT head at
        # each gating point -- an aged head that admits mid-pass must
        # not leave its stale flag gating the requests behind it.
        def head_aged() -> bool:
            return (
                bool(self.waiting)
                and now - self.waiting[0].arrival_s > adm.floor_s
            )

        skips = 0
        i = 0
        while i < len(self.waiting) and (
            cap is None or len(plan.prefills) < cap
        ):
            slot = self._free_slot()
            if slot is None:
                break
            seq = self.waiting[i]
            need = self.predicted_pages(seq)
            # an empty batch always admits its head: a request whose
            # prediction exceeds the whole budget must still run alone
            # (the engine fails truly-impossible prompts separately)
            fits = committed + need <= budget or (
                self.num_active == 0 and i == 0
            )
            if fits and self._try_admit(seq, plan, slot):
                del self.waiting[i]
                committed += need
                continue
            if i == 0:
                self.admit_blocked += 1
            if head_aged() or skips >= adm.max_skips:
                break
            skips += 1
            self.admit_skips += 1
            i += 1

    # -- mixed-batch formation (unified ragged dispatch) ---------------------

    def queue_mixed_prefill(self, seq: SeqState, start: int) -> None:
        """Hand an admitted (slotted) prompt to the mixed-batch plane: the
        lane parks ``prefilling`` (decode-inactive) and its prompt tokens
        are packed into unified dispatches chunk by chunk, FIFO across
        lanes, under the per-dispatch token budget."""
        seq.prefilling = True
        seq.prefilled_tokens = start
        # a re-admitted (preemption-recomputed) lane may still have a stale
        # entry from its previous life; one entry per seq keeps one chunk
        # per lane per dispatch
        if seq not in self.mix_pending:
            self.mix_pending.append(seq)

    def form_mixed_chunks(
        self, budget: int, chunk_cap: Optional[int] = None,
        reserve_tokens: int = 0,
    ) -> List[MixedChunk]:
        """Pack pending prefill work into this tick's unified dispatch.

        ``budget`` is the dispatch's total fresh-token budget
        (``DYN_MIXED_TOKEN_BUDGET``): every decode-runnable lane costs one
        token, ``reserve_tokens`` rows are withheld for the tick's folded
        speculative-verify segments (the engine's spec-fold reserve -- a
        verify column is a fresh row like any other under the packed
        layout), the remainder goes to prefill chunks in arrival order.  At
        least one prompt token always packs when prefill work is pending,
        so a decode batch as wide as the budget can never starve
        admission.  ``chunk_cap`` bounds one lane's chunk (the
        ``prefill_chunk_tokens`` knob); chunk lengths are otherwise ragged
        -- the dispatch pads the query axis to a pow2 bucket, so the
        executable-shape set stays O(log(budget)) no matter the arrival
        pattern (tested in test_mixed_batching).

        Non-final chunk boundaries are rounded DOWN to a page multiple:
        a drained lane (``_drain_mixed_to_classic``) resumes through the
        classic suffix machinery, whose prefix page table covers whole
        pages only -- a mid-page boundary would leave the partial page's
        keys unreachable on restart.  Starts stay aligned by induction
        (admission starts at the page-aligned prefix-cache boundary).
        When alignment rounds the head lane's chunk to zero, one full
        page packs anyway (slight budget overshoot beats starvation).
        """
        ps = self.cfg.page_size
        left = max(budget - self.num_decode_runnable - reserve_tokens, 1)
        chunks: List[MixedChunk] = []
        still: List[SeqState] = []
        seen: set = set()
        for seq in self.mix_pending:
            if (
                seq.finish is not None
                or seq.slot < 0
                or self.slots[seq.slot] is not seq
                or not seq.prefilling
                or id(seq) in seen
            ):
                continue  # cancelled / preempted mid-prefill / dup: drop
            seen.add(id(seq))
            remaining = len(seq.prompt) - seq.prefilled_tokens
            if remaining <= 0:  # defensive; final chunk clears prefilling
                seq.prefilling = False
                self.dirty_slots.add(seq.slot)
                continue
            take = min(remaining, left) if left > 0 else 0
            if chunk_cap is not None:
                take = min(take, chunk_cap)
            if take < remaining:
                # non-final: keep the boundary page-aligned for the
                # classic-path handoff (start is aligned by induction)
                take = (seq.prefilled_tokens + take) // ps * ps \
                    - seq.prefilled_tokens
                if take <= 0 and not chunks:
                    take = min(ps, remaining)
            if take > 0:
                chunks.append(
                    MixedChunk(
                        seq=seq,
                        start=seq.prefilled_tokens,
                        length=take,
                        final=(take == remaining),
                    )
                )
                left -= take
                if take < remaining:
                    still.append(seq)
            else:
                still.append(seq)
        self.mix_pending = still
        return chunks

    def _match_prefix(self, seq: SeqState) -> List[int]:
        """Acquire the longest resident prefix of the prompt's blocks; returns
        the reused pages (front of the page table).  Reuse is capped below the
        full prompt so prefill always has at least one token to process.

        After the G1 (HBM) match ends, the chain continues into the offload
        tiers: a G2/G3 hit allocates fresh pages now and defers the device
        scatter + registration to the engine (``seq.pending_onboard``) --
        those pages stay plain-owned until the scatter is dispatched, so no
        other request can match a block whose contents haven't landed."""
        seq.cached_prompt_tokens = 0
        if self.pool is None or seq.blocks is None:
            return []
        max_blocks = max(0, (len(seq.prompt) - 1) // self.block_size)
        hashes = seq.blocks.sequence_hashes()[:max_blocks]
        matched = self.pool.match(hashes)
        pages: List[int] = []
        for blk in matched:
            got = self.pool.acquire(blk.sequence_hash)
            if got is None:  # raced away (defensive; single-threaded today)
                break
            seq.held_blocks.append(blk.sequence_hash)
            pages.extend(blk.pages)
        n_matched = len(seq.held_blocks)
        if self.offload_lookup is not None:
            for h in hashes[n_matched:]:
                if self.pool.is_registered(h):
                    break  # re-resident meanwhile; stop the offload chain
                hit = self.offload_lookup(h)
                if hit is None:
                    break
                blob, meta = hit
                try:
                    got_pages = self.allocator.alloc(self.pages_per_block)
                except OutOfPages:
                    break
                seq.pending_onboard.append((h, got_pages, blob, meta))
                pages.extend(got_pages)
        seq.cached_prompt_tokens = (
            n_matched + len(seq.pending_onboard)
        ) * self.block_size
        return pages

    def _unmatch_prefix(self, seq: SeqState) -> None:
        for h in seq.held_blocks:
            self.pool.release(h)
        seq.held_blocks = []
        for _h, pages, _blob, _meta in seq.pending_onboard:
            self.allocator.free(pages)
        seq.pending_onboard = []
        seq.cached_prompt_tokens = 0

    def _queue_prompt_registrations(self, seq: SeqState) -> None:
        """Prompt blocks beyond the reused prefix register once prefill's KV
        writes are committed (the catch-up in ``_register_ready``)."""
        if self.pool is None or seq.blocks is None:
            return
        n_reused = seq.cached_prompt_tokens // self.block_size
        n_prompt_blocks = len(seq.prompt) // self.block_size
        seq.pending_register = list(seq.blocks.blocks[n_reused:n_prompt_blocks])

    def _free_slot(self) -> Optional[int]:
        dp = self.cfg.dp_groups
        B = self.cfg.max_batch_size
        if dp <= 1 or B % dp:
            for i, s in enumerate(self.slots):
                if s is None:
                    return i
            return None
        # dp-balanced admission: pick the first free slot of the
        # least-occupied dp group (ties -> lowest group, preserving the
        # deterministic first-free order within a group).  The decode batch
        # shards batch-major over dp, so an unbalanced fill would leave
        # whole chips stepping empty lanes while one group saturates.
        per = B // dp
        best: Optional[int] = None
        best_load = per + 1
        for g in range(dp):
            lanes = self.slots[g * per : (g + 1) * per]
            load = sum(1 for s in lanes if s is not None)
            if load >= per or load >= best_load:
                continue
            best = g * per + next(
                i for i, s in enumerate(lanes) if s is None
            )
            best_load = load
        return best

    def _write_slot_arrays(self, seq: SeqState) -> None:
        b = seq.slot
        self.page_table[b, :] = 0
        self.page_table[b, : len(seq.pages)] = seq.pages
        self.seq_lens[b] = len(seq.prompt)
        self.tokens[b] = seq.prompt[-1] if seq.prompt else 0
        self.layout_version += 1
        self.dirty_slots.add(b)

    # -- decode bookkeeping --------------------------------------------------

    def ensure_decode_capacity(
        self, lookahead: int = 1, chunk_pages: int = 0
    ) -> List[SeqState]:
        """Grow page tables so each active sequence can absorb up to
        ``lookahead`` more tokens, never growing past the lane's remaining
        token budget (max_tokens / max_seq_len).  When growth is needed,
        over-allocate by ``chunk_pages`` so the page table (and the device
        copy of it) changes every few blocks instead of every block.

        Growth is best-effort: a lane that cannot reach the full lookahead
        pauses at its allocated capacity (the device-side ``limit_lens`` cap
        keeps it from writing past its pages) and retries next tick.
        Preemption only triggers when a lane lacks room for even one more
        token -- then the youngest lane is evicted (possibly the lane
        itself).  Returns the preempted sequences (moved back to the head of
        the waiting queue, pages freed)."""
        ps = self.cfg.page_size
        preempted: List[SeqState] = []
        for seq in [s for s in self.slots if s is not None]:
            if seq.slot < 0:
                continue  # became a preemption victim earlier this pass
            cache_len = int(self.seq_lens[seq.slot])
            budget = max(self.remaining_budget(seq), 1)
            # max cache length the lane can ever use (limit_lens semantics:
            # the final token's KV is never read, and position max_seq_len-1
            # is the last writable slot)
            useful = min(cache_len + budget, self.cfg.max_seq_len - 1)
            want_tokens = min(cache_len + lookahead, useful)
            need_tokens = min(cache_len + 1, useful)
            want = min(-(-want_tokens // ps), self.max_pages)
            need = min(-(-need_tokens // ps), self.max_pages)
            if len(seq.pages) < want:
                want = min(want + chunk_pages, -(-useful // ps), self.max_pages)
            while len(seq.pages) < want:
                try:
                    page = self.allocator.alloc(1)[0]
                except OutOfPages:
                    if len(seq.pages) >= need:
                        break  # best effort met; lane pauses at capacity
                    victim = self._pick_preemption_victim()
                    if victim is None or victim is seq:
                        # cannot make room; preempt this one
                        self._preempt(seq)
                        preempted.append(seq)
                        break
                    self._preempt(victim)
                    preempted.append(victim)
                    continue
                seq.pages.append(page)
                seq.owned_pages.append(page)
                self.page_table[seq.slot, len(seq.pages) - 1] = page
                self.growth_version += 1
        return preempted

    def _pick_preemption_victim(self) -> Optional[SeqState]:
        """Preempt the most recently arrived active sequence (reference
        vLLM-style recompute preemption favors older requests)."""
        active = [s for s in self.slots if s is not None]
        if not active:
            return None
        return max(active, key=lambda s: s.arrival_s)

    def _preempt(self, seq: SeqState) -> None:
        # swap-based preemption: snapshot the lane's KV (engine hook, must
        # run while the pages are still allocated so the device read is
        # ordered before any reuse) and park the sequence for a restore;
        # recompute -- fold + re-prefill -- remains the fallback whenever
        # the hook declines (tiers full, lane mid-prefill, chaos)
        swapped = False
        if self.swap_out is not None and seq.finish is None:
            try:
                swapped = bool(self.swap_out(seq))
            except Exception:
                import logging

                logging.getLogger("dynamo.offload").exception(
                    "swap-out hook failed for %s; recomputing", seq.request_id
                )
        self._release_slot(seq)
        # fold generated tokens into the prompt so the resume -- whether a
        # KV restore or a re-prefill -- reproduces the full sequence
        # deterministically (stop/penalty accounting shares this bookkeeping)
        seq.prompt = seq.prompt + self._generated_tokens(seq)
        seq.prior_generated += seq.num_generated
        seq.num_generated = 0
        seq.slot = -1
        if swapped:
            # parked exactly like a disagg external lane: holds pages at
            # admission, stays device-inactive until the engine's swap-in
            # delivery clears the barrier (an external lane keeps its own
            # pre-existing awaiting_kv)
            seq.awaiting_kv = True
            self.preempt_swap += 1
        else:
            self.preempt_recompute += 1
        self.waiting.appendleft(seq)

    def _generated_tokens(self, seq: SeqState) -> List[int]:
        if seq.blocks is None:
            return []
        all_tokens = seq.blocks.tokens
        return list(all_tokens[len(seq.prompt) :])

    def _release_slot(self, seq: SeqState) -> None:
        seq.prefilling = False
        seq.prefilled_tokens = 0
        if seq.slot >= 0:
            b = seq.slot
            self.slots[b] = None
            self.page_table[b, :] = 0
            self.seq_lens[b] = 0
            self.tokens[b] = 0
            self.layout_version += 1
            self.dirty_slots.add(b)
        # registered blocks outlive the sequence (refcount drops; the block
        # turns inactive-reusable at zero); only exclusively-owned pages and
        # never-registered completions return to the free list
        if self.pool is not None:
            self.allocator.free(seq.owned_pages)
            for h in seq.held_blocks:
                self.pool.release(h)
            seq.held_blocks = []
            seq.pending_register = []
            seq.pending_onboard = []  # pages were owned; freed above
            seq.pages = []
            seq.owned_pages = []
        elif seq.pages:
            self.allocator.free(seq.pages)
            seq.pages = []
            seq.owned_pages = []

    # -- per-token postprocessing -------------------------------------------

    def commit_tokens(self, sampled: np.ndarray) -> List[StepEvent]:
        """Apply one decode step's sampled tokens [B]; returns per-seq events.

        Stop-condition semantics follow the reference backend jail
        (lib/llm/src/backend.rs): eos finishes unless ignore_eos; hidden stop
        token ids finish without emitting the token.
        """
        events: List[StepEvent] = []
        for b, seq in enumerate(self.slots):
            if seq is None:
                continue
            token = int(sampled[b])
            ev = self._commit_token(seq, token)
            events.append(ev)
            if ev.finished is not None:
                seq.finish = ev.finished
                self._release_slot(seq)
        return events

    def _commit_lane_column(
        self,
        seq: SeqState,
        column: np.ndarray,
        lps: Optional[np.ndarray] = None,  # [K] chosen-token logprobs
        top_ids: Optional[np.ndarray] = None,  # [K, N]
        top_lps: Optional[np.ndarray] = None,  # [K, N]
    ) -> StepEvent:
        """Commit one lane's K sampled tokens as a single coalesced event.

        Host-side replay of the device loop for one lane: per token the
        exact stop-condition rules run (``_commit_token``); ``-1`` marks a
        step the device already knew was dead.  Once the lane finishes, the
        rest of the column was speculative decode and is discarded."""
        tokens: List[int] = []
        blocks: List[TokenBlock] = []
        logprobs: List[float] = []
        tops: Optional[List[List[List[float]]]] = (
            [] if top_ids is not None else None
        )
        finished: Optional[FinishReason] = None
        for k, raw in enumerate(column.tolist()):
            if raw < 0:
                continue
            ev = self._commit_token(seq, raw)
            if ev.tokens:
                tokens.extend(ev.tokens)
                if lps is not None:
                    logprobs.append(float(lps[k]))
                if tops is not None:
                    tops.append(
                        [
                            [int(i), float(l)]
                            for i, l in zip(top_ids[k], top_lps[k])
                        ]
                    )
            blocks.extend(ev.completed_blocks)
            if ev.finished is not None:
                finished = ev.finished
                break
        return StepEvent(
            seq=seq, tokens=tokens, finished=finished, completed_blocks=blocks,
            logprobs=logprobs, top_logprobs=tops,
        )

    def commit_block(
        self,
        sampled: np.ndarray,
        slot_snapshot: Optional[List[Optional[SeqState]]] = None,
        lps: Optional[np.ndarray] = None,  # [B, K] chosen-token logprobs
        top_ids: Optional[np.ndarray] = None,  # [B, K, N]
        top_lps: Optional[np.ndarray] = None,  # [B, K, N]
    ) -> List[StepEvent]:
        """Apply a device-decoded block of raw sampled tokens [B, K].

        Each live lane's column commits through ``_commit_lane_column``,
        which replays the device stop rules token by token but returns ONE
        coalesced event for the block -- the per-event downstream cost
        (queue put, consumer wakeup, SSE frame) is paid per block per lane,
        not per token, which is what keeps large-batch decode off the host's
        critical path.

        ``slot_snapshot`` is the slot list captured when the block was
        dispatched -- with pipelined blocks a slot may have been released (or
        even re-assigned) since, and those lanes' tokens must not be
        attributed to the new occupant.
        """
        events: List[StepEvent] = []
        B, K = sampled.shape
        slots_at_entry = (
            list(slot_snapshot) if slot_snapshot is not None else list(self.slots)
        )
        for b in range(B):
            seq = slots_at_entry[b]
            if seq is None or seq.finish is not None or seq.slot != b:
                continue
            if seq.prefilling or seq.awaiting_kv:
                # a parked lane's column is placeholder garbage by
                # construction (the lane is device-inactive, rows are -1);
                # a lane re-parked since the dispatch (preempt + re-admit
                # into the same slot) must not have stale columns
                # attributed to its new life
                continue
            ev = self._commit_lane_column(
                seq, sampled[b],
                lps[b] if lps is not None else None,
                top_ids[b] if top_ids is not None else None,
                top_lps[b] if top_lps is not None else None,
            )
            if ev.finished is not None:
                seq.finish = ev.finished
                self._release_slot(seq)
            if ev.tokens or ev.finished is not None:
                events.append(ev)
        return events

    def commit_prefill_token(
        self,
        seq: SeqState,
        token: int,
        logprob: Optional[float] = None,
        top: Optional[List[List[float]]] = None,
    ) -> StepEvent:
        """Apply the first token sampled from prefill logits."""
        ev = self._commit_token(seq, token)
        if ev.tokens:
            if logprob is not None:
                ev.logprobs = [logprob]
            if top is not None:
                ev.top_logprobs = [top]
        if ev.finished is not None:
            seq.finish = ev.finished
            self._release_slot(seq)
        return ev

    def _commit_token(self, seq: SeqState, token: int) -> StepEvent:
        stop = seq.stop
        # total tokens streamed to the client, across preemptions
        n_gen = seq.prior_generated + seq.num_generated + 1

        hidden_stop = stop.stop_token_ids_hidden or []
        is_eos = token in seq.eos_ids
        min_ok = stop.min_tokens is None or n_gen >= stop.min_tokens

        if token in hidden_stop and min_ok:
            return StepEvent(seq=seq, finished=FinishReason.STOP)
        if is_eos and not stop.ignore_eos and min_ok:
            return StepEvent(seq=seq, finished=FinishReason.EOS)

        seq.num_generated += 1
        completed: List[TokenBlock] = []
        if seq.blocks is not None:
            blk = seq.blocks.append(token)
            if blk is not None:
                completed.append(blk)
        b = seq.slot
        self.tokens[b] = token
        # seq_lens mirrors the *cache* length: the KV of the newest token is
        # written by the upcoming decode step at exactly this position
        # (decode_step positions = seq_lens).
        self.seq_lens[b] = seq.seq_len - 1
        if self.pool is not None:
            seq.pending_register.extend(completed)
            self._register_ready(seq)

        finished: Optional[FinishReason] = None
        if stop.max_tokens is not None and n_gen >= stop.max_tokens:
            finished = FinishReason.LENGTH
        elif seq.seq_len >= self.cfg.max_seq_len:
            finished = FinishReason.LENGTH
        return StepEvent(
            seq=seq, tokens=[token], finished=finished, completed_blocks=completed
        )

    def _register_ready(self, seq: SeqState) -> None:
        """Register completed blocks whose KV is fully written.

        A block ending at token position ``end`` is committable once the
        cache length reaches ``end``: the decode step that consumed the
        block's final token wrote its KV (commit implies the write was
        dispatched, and the device executes dispatches in order, so any
        later prefill that reuses the block reads it complete).
        """
        cache_len = int(self.seq_lens[seq.slot])
        ppb = self.pages_per_block
        while seq.pending_register:
            blk = seq.pending_register[0]
            end = (blk.position + 1) * self.block_size
            if end > cache_len:
                break
            seq.pending_register.pop(0)
            start = blk.position * ppb
            pages = seq.pages[start : start + ppb]
            if len(pages) < ppb:
                break  # table shorter than the block span (defensive)
            if self.pool.register(
                blk.sequence_hash,
                pages,
                block_hash=blk.block_hash,
                parent_sequence_hash=blk.parent_sequence_hash,
                position=blk.position,
            ):
                # ownership moves to the registry; this seq keeps a ref
                seq.held_blocks.append(blk.sequence_hash)
                for p in pages:
                    seq.owned_pages.remove(p)
            # register() == False: identical block already registered by a
            # concurrent twin; keep plain ownership of our duplicate pages

    def cancel(self, seq: SeqState) -> None:
        if seq.slot >= 0:
            self._release_slot(seq)
        elif seq in self.waiting:
            self.waiting.remove(seq)
        seq.finish = FinishReason.CANCELLED
