"""Attention ops over the paged KV cache (reference-free JAX implementations).

Layout: the KV cache is one stacked buffer ``[layers, 2, num_pages,
page_size, kv_heads, head_dim]``; readers/writers take a scalar layer index
and scatter/gather in place, so the layer scan carries a single buffer that
XLA updates without copying.  A request owns a list of pages recorded in
its row of the page table ``[batch, pages_per_seq]``.  Page 0 is reserved
as the trash page:
inactive batch slots scatter their writes there, so dead lanes never corrupt
live state and every step runs with fully static shapes (XLA requirement).

These are the XLA-composed implementations (gather + einsum; XLA fuses the
mask/softmax chain).  On TPU the decode hot loop routes through the Pallas
kernel in dynamo_tpu.ops.paged_attention instead (see
``decode_attention_dispatch``): the XLA gather materializes
[B, P*page, Hkv, D] per step, the kernel streams pages HBM->VMEM once.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..analysis.hotpath import hot_path
from .kv_cache import (
    QuantKV,
    gather_layer_kv,
    index_kv_layer,
    kv_data,
    kv_is_quantized,
    quantize_kv_rows,
)

_NEG_INF = -1e30


# -- int8 pool plumbing (kv_cache.QuantKV) ----------------------------------
#
# Every reader/writer below takes the pool as one opaque value: a dense
# array for bf16/f32 pools, a QuantKV (int8 data + per-row scales) pytree
# for quantized ones.  Reads gather data+scales and dequantize after the
# page gather (kv_cache.gather_layer_kv -- XLA fuses the convert+scale
# into the consuming einsum); writes route through the shared
# quantize_kv_rows rule below and scatter both arrays.  The branch
# resolves at trace time (pytree structure is static), so each compiled
# executable embeds exactly one layout.


def _kv_write(kv_pages, kv_idx, layer, ids, k_rows, *, slot=None):
    """Scatter one side's rows into the pool at (layer, ids[, slot]):
    quantizes on write for int8 pools.  ``ids`` (page ids) and ``slot``
    (within-page row) are pre-flattened index arrays; ``k_rows`` is
    ``[..., Hkv, D]`` aligned with them."""
    if isinstance(kv_pages, QuantKV):
        q, s = quantize_kv_rows(k_rows)
        if slot is None:
            new_q = kv_pages.q.at[layer, kv_idx, ids].set(q)
            new_s = kv_pages.s.at[layer, kv_idx, ids].set(
                s.astype(kv_pages.s.dtype)
            )
        else:
            new_q = kv_pages.q.at[layer, kv_idx, ids, slot].set(q)
            new_s = kv_pages.s.at[layer, kv_idx, ids, slot].set(
                s.astype(kv_pages.s.dtype)
            )
        return QuantKV(q=new_q, s=new_s)
    if slot is None:
        return kv_pages.at[layer, kv_idx, ids].set(
            k_rows.astype(kv_pages.dtype)
        )
    return kv_pages.at[layer, kv_idx, ids, slot].set(
        k_rows.astype(kv_pages.dtype)
    )


def _env_flag(name: str):
    """Tri-state env override shared by every Pallas dispatch gate:
    True/False when the variable is set, None for auto."""
    env = os.environ.get(name)
    if env is None:
        return None
    return env not in ("0", "false", "")


def _on_tpu() -> bool:
    try:
        return any("TPU" in d.device_kind for d in jax.devices())
    # dynalint: disable=DT003 -- platform probe: "no backend" simply means not-TPU
    except Exception:
        return False


def _pallas_decode_enabled(page_size: int) -> bool:
    """Trace-time choice of the decode-attention backend.

    ``DYN_PALLAS_DECODE=1/0`` forces it; default is auto -- on when the
    backend is a TPU and the page size meets the kernel's sublane tiling
    (>= 8).  The XLA path stays as the universal fallback (CPU tests, tiny
    page sizes)."""
    forced = _env_flag("DYN_PALLAS_DECODE")
    if forced is not None:
        return forced
    return page_size >= 8 and _on_tpu()


@hot_path
def decode_attention_dispatch(
    q: jax.Array,  # [B, Hq, D]
    kv_pages: jax.Array,  # [L, 2, num_pages, page_size, Hkv, D]
    page_table: jax.Array,  # [B, P]
    kv_lens: jax.Array,  # [B]
    layer: jax.Array,  # scalar i32
    window: int = 0,  # sliding-window width; 0 = full attention
) -> jax.Array:
    """Decode attention: Pallas page-streaming kernel on TPU, XLA gather
    elsewhere.  Resolved at trace time (static), so each compiled executable
    embeds exactly one backend.  Quantized pools take the XLA gather on
    this CLASSIC path only (penalized/multimodal fallback lanes) -- the
    serving hot path under ``--kv-dtype int8`` is the unified ragged
    dispatch, whose Pallas kernels fuse the dequant."""
    if (
        not kv_is_quantized(kv_pages)
        # the classic Pallas kernels compute directly on the pool tiles:
        # a dense pool dtype that differs from the query/compute dtype
        # (explicit --kv-dtype float32 under a bf16 model) takes the XLA
        # gather, whose dequant/cast normalizes operands
        and kv_pages.dtype == q.dtype
        and _pallas_decode_enabled(kv_pages.shape[3])
    ):
        from ..ops.paged_attention import paged_decode_attention_v2

        # group-of-8 fetches: grid-step overhead dominates per-page v1 at
        # serving shapes (v2 internally falls back to v1 for table widths
        # the group doesn't divide)
        return paged_decode_attention_v2(
            q, kv_pages, page_table, kv_lens, layer, window, group=8
        )
    layer_kv = index_kv_layer(kv_pages, layer)
    return paged_decode_attention(q, layer_kv, page_table, kv_lens, window)


def _pallas_ragged_enabled(page_size: int, Hq: int, Hkv: int, D: int) -> bool:
    """Trace-time choice of the ragged mixed-batch attention backend.

    ``DYN_PALLAS_RAGGED=1/0`` forces it; default is auto -- on when the
    backend is a TPU, the page size meets the kernel's sublane tiling
    (>= 8), and the GQA group divides cleanly.  The XLA composition
    (ops.ragged_attention.ragged_paged_attention_xla) stays as the
    universal fallback and the tier-1 (CPU) code path."""
    forced = _env_flag("DYN_PALLAS_RAGGED")
    if forced is not None:
        return forced
    if page_size < 8 or Hq % Hkv or D % 8:
        return False
    return _on_tpu()


@hot_path
def ragged_attention_dispatch(
    q: jax.Array,  # [B, S, Hq, D] ragged queries (lane b row i at base[b]+i)
    k: jax.Array,  # [B, S, Hkv, D] fresh keys for the same columns
    v: jax.Array,  # [B, S, Hkv, D]
    kv_pages: jax.Array,  # [L, 2, num_pages, page, Hkv, D]
    layer: jax.Array,  # scalar i32
    page_table: jax.Array,  # [B, P] (bucketed)
    base: jax.Array,  # [B] committed cache length per lane
    q_lens: jax.Array,  # [B] valid query rows (0 = inactive lane)
    window: int = 0,
) -> jax.Array:
    """Ragged mixed prefill+decode attention over the paged pool: Pallas
    page-streaming kernel on TPU, XLA gather + einsum elsewhere.  Resolved
    at trace time (static), so each compiled executable embeds exactly one
    backend -- the pattern every other dispatch gate here follows.  This
    is the ONE attention call of ``step.unified_step``: a decode lane is a
    1-row query, a chunked-prefill lane its chunk's rows, all causal at
    token granularity against the resident prefix plus the dispatch's own
    fresh columns.  Quantized pools pass their row scales as extra kernel
    operands; the dequant fuses into the page-group stream (VMEM multiply
    per fetched group, never a full-width pool materialization)."""
    Hq, D = q.shape[2], q.shape[3]
    Hkv = k.shape[2]
    data = kv_data(kv_pages)
    scales = kv_pages.s if kv_is_quantized(kv_pages) else None
    if _pallas_ragged_enabled(data.shape[3], Hq, Hkv, D):
        from ..ops.ragged_attention import ragged_paged_attention

        return ragged_paged_attention(
            q, k, v, data, page_table, base, q_lens, layer, window,
            group=4, kv_scales=scales,
        )
    from ..ops.ragged_attention import ragged_paged_attention_xla

    return ragged_paged_attention_xla(
        q, k, v, kv_pages, page_table, base, q_lens, layer, window
    )


@hot_path
def packed_ragged_attention_dispatch(
    q: jax.Array,  # [Np, Hq, D] packed queries (lane's row i at base+i)
    k: jax.Array,  # [Np, Hkv, D] packed fresh keys
    v: jax.Array,  # [Np, Hkv, D]
    kv_pages: jax.Array,  # [L, 2, num_pages, page, Hkv, D]
    layer: jax.Array,  # scalar i32
    page_table: jax.Array,  # [B, P] (bucketed)
    base: jax.Array,  # [B] committed cache length per lane
    seg_off: jax.Array,  # [B] lane's segment offset into the packed axis
    q_lens: jax.Array,  # [B] fresh rows per lane (0 = no segment)
    lane: jax.Array,  # [Np] lane per packed token (B = padding)
    rel: jax.Array,  # [Np] row index within the lane's segment
    s_max: int,  # static per-lane window capacity
    window: int = 0,
) -> jax.Array:
    """Fully-packed ragged mixed-batch attention: the flat-token-axis
    layout of ``step.packed_unified_step`` (ISSUE 10).  Pallas
    packed-operand kernel on TPU, XLA unpack-rectangle-repack reference
    elsewhere -- resolved at trace time like every other dispatch gate,
    and gated by the same ``DYN_PALLAS_RAGGED`` knob as the rectangle
    kernel (the two are the same algorithm over different operand
    layouts).  Quantized pools fuse the row-scale dequant exactly like
    the rectangle dispatch above."""
    Hq, D = q.shape[1], q.shape[2]
    Hkv = k.shape[1]
    data = kv_data(kv_pages)
    scales = kv_pages.s if kv_is_quantized(kv_pages) else None
    if _pallas_ragged_enabled(data.shape[3], Hq, Hkv, D):
        from ..ops.ragged_attention import packed_ragged_attention

        return packed_ragged_attention(
            q, k, v, data, page_table, base, seg_off, q_lens, s_max,
            layer, window, group=4, kv_scales=scales,
        )
    from ..ops.ragged_attention import packed_ragged_attention_xla

    return packed_ragged_attention_xla(
        q, k, v, kv_pages, page_table, base, seg_off, q_lens, lane, rel,
        s_max, layer, window,
    )


def _pallas_prefill_enabled(T: int, Hq: int, Hkv: int, D: int) -> bool:
    """Trace-time choice of the prefill-attention backend.

    ``DYN_PALLAS_PREFILL=1/0`` forces it; default is auto -- on when the
    backend is a TPU, the GQA group divides cleanly, and the sequence is
    long enough that score materialization dominates.  Measured on v5e
    (bench heads, 256-token tiles): T=512 XLA's fused chain still matches;
    T=1024 flash wins 102 vs 109 ms; T=2048 it wins 86 vs 117 ms (-26%);
    T=4096 106 vs 108 ms -- so auto engages at T >= 1024.  The XLA path
    stays as the universal fallback."""
    forced = _env_flag("DYN_PALLAS_PREFILL")
    if forced is not None:
        return forced
    if T < 1024 or Hq % Hkv or D % 8:
        return False
    return _on_tpu()


@hot_path
def prefill_attention_dispatch(
    q: jax.Array,  # [B, T, Hq, D]
    k: jax.Array,  # [B, T, Hkv, D]
    v: jax.Array,  # [B, T, Hkv, D]
    seq_lens: jax.Array,  # [B]
    window: int = 0,
) -> jax.Array:
    """Prefill attention: Pallas flash kernel on TPU, XLA einsum elsewhere.
    Resolved at trace time, so each compiled executable embeds exactly one
    backend (same pattern as decode_attention_dispatch)."""
    B, T, Hq, D = q.shape
    if _pallas_prefill_enabled(T, Hq, k.shape[2], D):
        from ..ops.flash_prefill import flash_prefill_attention

        return flash_prefill_attention(q, k, v, seq_lens, window)
    return prefill_attention(q, k, v, seq_lens, window)


def _pallas_prefix_prefill_enabled(
    T: int, Kp: int, Hq: int, Hkv: int, D: int
) -> bool:
    """Trace-time choice for the prefix-suffix prefill backend.

    Same knob as the full-prefill dispatch (``DYN_PALLAS_PREFILL``); the
    auto threshold engages earlier than plain prefill because the score
    tensor the kernel avoids is ``[B, Hq, T, Kp+T]`` -- the resident
    prefix widens the key axis beyond what T alone suggests."""
    forced = _env_flag("DYN_PALLAS_PREFILL")
    if forced is not None:
        return forced
    if Hq % Hkv or D % 8:
        return False
    if T < 1024 and (T < 512 or Kp < 512):
        return False
    return _on_tpu()


@hot_path
def prefill_prefix_attention_dispatch(
    q: jax.Array,  # [B, T, Hq, D] suffix queries
    k: jax.Array,  # [B, T, Hkv, D] suffix keys (being prefilled)
    v: jax.Array,  # [B, T, Hkv, D]
    kv_pages: jax.Array,  # [L, 2, num_pages, page, Hkv, D]
    layer: jax.Array,  # scalar i32
    prefix_table: jax.Array,  # [B, Pp] reused-prefix page ids (0-padded)
    offset: jax.Array,  # [B] cached prefix length in tokens
    suffix_lens: jax.Array,  # [B] valid suffix length
    window: int = 0,
) -> jax.Array:
    """Prefix-suffix prefill attention: flash-tiled on TPU, XLA gather +
    einsum elsewhere.  Resolved at trace time (same pattern as the other
    dispatches).  The flash path pre-gathers the prefix pages into
    contiguous K/V (a few MB, XLA-fused) and never materializes the
    ``[B, Hq, T, Kp+T]`` score tensor -- this is the common path under KV
    routing, where most admissions restart on a cached prefix."""
    B, T, Hq, D = q.shape
    Hkv = k.shape[2]
    page_size = kv_data(kv_pages).shape[3]
    Kp = prefix_table.shape[1] * page_size
    if _pallas_prefix_prefill_enabled(T, Kp, Hq, Hkv, D):
        import math

        from ..ops.flash_prefill import flash_prefix_prefill_attention

        layer_kv = index_kv_layer(kv_pages, layer)
        kp = gather_layer_kv(layer_kv, 0, prefix_table, q.dtype).reshape(
            B, Kp, Hkv, D
        )
        vp = gather_layer_kv(layer_kv, 1, prefix_table, q.dtype).reshape(
            B, Kp, Hkv, D
        )
        # pad the prefix span to a key-tile multiple (BK = gcd(T, 256),
        # mirroring the kernel's tile choice): a tiny cached prefix must
        # not collapse the whole key axis to its width, and non-pow2 top
        # buckets must still tile exactly.  Pad keys are masked by
        # ``kpos < offset`` (offset <= Kp <= padded span).
        BK = math.gcd(T, 256)
        pad = (-Kp) % BK
        if pad:
            widths = [(0, 0)] * 4
            widths[1] = (0, pad)
            kp = jnp.pad(kp, widths)
            vp = jnp.pad(vp, widths)
        return flash_prefix_prefill_attention(
            q,
            jnp.concatenate([kp, k], axis=1),
            jnp.concatenate([vp, v], axis=1),
            offset,
            suffix_lens,
            window,
        )
    return prefill_prefix_attention(
        q, k, v, kv_pages, layer, prefix_table, offset, suffix_lens, window
    )


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[.., kv_heads, d] -> [.., kv_heads * n_rep, d] (GQA expansion)."""
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=-2)


@hot_path
def prefill_attention(
    q: jax.Array,  # [B, T, Hq, D]
    k: jax.Array,  # [B, T, Hkv, D]
    v: jax.Array,  # [B, T, Hkv, D]
    seq_lens: jax.Array,  # [B] valid prompt length per slot
    window: int = 0,  # sliding-window width; 0 = full attention
) -> jax.Array:
    """Causal self-attention over the prompt being prefilled.

    Assumes the prompt starts at position 0 (no prior cache); prefix-cache
    restarts gather reused pages through the decode path instead.
    ``window`` > 0 masks keys more than ``window - 1`` positions behind the
    query (Mistral/Phi3 sliding-window semantics: the query position itself
    counts toward the window)."""
    B, T, Hq, D = q.shape
    n_rep = Hq // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, q.dtype))
    # [B, H, T, T]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    pos = jnp.arange(T)
    causal = pos[None, :] <= pos[:, None]  # [Tq, Tk] keys <= query
    if window > 0:
        causal = causal & (pos[:, None] - pos[None, :] < window)
    valid = pos[None, :] < seq_lens[:, None]  # [B, Tk]
    mask = causal[None, None, :, :] & valid[:, None, None, :]
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@hot_path
def paged_decode_attention(
    q: jax.Array,  # [B, Hq, D] one new query token per slot
    kv_pages: jax.Array,  # [2, num_pages, page_size, Hkv, D]
    page_table: jax.Array,  # [B, P] int32 page ids
    kv_lens: jax.Array,  # [B] tokens in cache (incl. the one just written)
    window: int = 0,  # sliding-window width; 0 = full attention
) -> jax.Array:
    """Decode-step attention: gather each slot's pages, mask, softmax.

    The gather materializes ``[B, P*page_size, Hkv, D]`` -- the classic
    paged-attention v1 shape.  P (pages per sequence) is static; kv_lens
    masks the tail (and, with ``window``, the head beyond the window).
    """
    B, Hq, D = q.shape
    _, _, page_size, Hkv, _ = kv_data(kv_pages).shape
    P = page_table.shape[1]
    n_rep = Hq // Hkv

    k = gather_layer_kv(kv_pages, 0, page_table, q.dtype)  # [B, P, page, Hkv, D]
    v = gather_layer_kv(kv_pages, 1, page_table, q.dtype)
    k = k.reshape(B, P * page_size, Hkv, D)
    v = v.reshape(B, P * page_size, Hkv, D)
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)

    scale = 1.0 / jnp.sqrt(jnp.asarray(D, q.dtype))
    scores = jnp.einsum("bhd,bkhd->bhk", q, k) * scale  # [B, Hq, P*page]
    idx = jnp.arange(P * page_size)
    mask = idx[None, :] < kv_lens[:, None]  # [B, P*page]
    if window > 0:
        mask = mask & (idx[None, :] >= kv_lens[:, None] - window)
    scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhk,bkhd->bhd", probs, v)


@hot_path
def prefill_prefix_attention(
    q: jax.Array,  # [B, T, Hq, D] suffix queries
    k: jax.Array,  # [B, T, Hkv, D] suffix keys (being prefilled)
    v: jax.Array,  # [B, T, Hkv, D]
    kv_pages: jax.Array,  # [L, 2, num_pages, page, Hkv, D]
    layer: jax.Array,  # scalar i32
    prefix_table: jax.Array,  # [B, Pp] reused-prefix page ids (0-padded)
    offset: jax.Array,  # [B] cached prefix length in tokens
    suffix_lens: jax.Array,  # [B] valid suffix length
    window: int = 0,  # sliding-window width; 0 = full attention
) -> jax.Array:
    """Suffix prefill attention with a resident prefix (prefix-cache restart).

    Queries live at absolute positions ``offset + local``; keys are the
    gathered prefix pages (positions ``0..offset``) concatenated with the
    suffix K/V computed this dispatch.  ``Pp`` is a static page-count bucket;
    pad slots point at trash page 0 and are masked by ``kpos < offset``.
    """
    B, T, Hq, D = q.shape
    page_size = kv_data(kv_pages).shape[3]
    Pp = prefix_table.shape[1]
    Hkv = k.shape[2]
    n_rep = Hq // Hkv

    layer_kv = index_kv_layer(kv_pages, layer)
    kp = gather_layer_kv(layer_kv, 0, prefix_table, q.dtype).reshape(
        B, Pp * page_size, Hkv, D
    )
    vp = gather_layer_kv(layer_kv, 1, prefix_table, q.dtype).reshape(
        B, Pp * page_size, Hkv, D
    )
    keys = repeat_kv(jnp.concatenate([kp, k], axis=1), n_rep)
    vals = repeat_kv(jnp.concatenate([vp, v], axis=1), n_rep)

    scale = 1.0 / jnp.sqrt(jnp.asarray(D, q.dtype))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, keys) * scale

    local = jnp.arange(T)
    prefix_valid = jnp.arange(Pp * page_size)[None, :] < offset[:, None]  # [B, Kp]
    suffix_valid = local[None, :] < suffix_lens[:, None]  # [B, T]
    causal = local[None, :] <= local[:, None]  # [Tq, Tk]
    if window > 0:
        # absolute positions: query = offset + local_q, prefix key = kpos,
        # suffix key = offset + local_k; keep keys within the window
        q_abs = offset[:, None] + local[None, :]  # [B, Tq]
        kpos = jnp.arange(Pp * page_size)
        prefix_win = (
            kpos[None, None, :] > q_abs[:, :, None] - window
        )  # [B, Tq, Kp]
        mask_prefix = jnp.broadcast_to(
            (prefix_valid[:, None, :] & prefix_win)[:, None],
            (B, 1, T, Pp * page_size),
        )
        suffix_win = local[:, None] - local[None, :] < window  # [Tq, Tk]
        causal = causal & suffix_win
    else:
        mask_prefix = jnp.broadcast_to(
            prefix_valid[:, None, None, :], (B, 1, T, Pp * page_size)
        )
    mask_suffix = jnp.broadcast_to(
        causal[None, None, :, :] & suffix_valid[:, None, None, :], (B, 1, T, T)
    )
    mask = jnp.concatenate([mask_prefix, mask_suffix], axis=-1)
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, vals)


@hot_path
def write_prefill_kv(
    kv_pages: jax.Array,  # [L, 2, num_pages, page, Hkv, D]
    k: jax.Array,  # [B, T, Hkv, D]
    v: jax.Array,  # [B, T, Hkv, D]
    page_table: jax.Array,  # [B, P]
    layer: jax.Array,  # scalar i32
) -> jax.Array:
    """Scatter a full prompt's K/V into its pages (in place -- kv_pages is
    the scan carry).  T must be a multiple of page_size (prompts are
    bucket-padded); pad lanes land on trash page 0.  Quantized pools
    quantize on write (per-row scales scatter alongside)."""
    B, T, Hkv, D = k.shape
    page_size = kv_data(kv_pages).shape[3]
    n_pages = T // page_size
    ids = page_table[:, :n_pages].reshape(-1)  # [B*n_pages]
    kp = k.reshape(B * n_pages, page_size, Hkv, D)
    vp = v.reshape(B * n_pages, page_size, Hkv, D)
    kv_pages = _kv_write(kv_pages, 0, layer, ids, kp)
    kv_pages = _kv_write(kv_pages, 1, layer, ids, vp)
    return kv_pages


@hot_path
def write_spec_kv(
    kv_pages: jax.Array,  # [L, 2, num_pages, page, Hkv, D]
    k: jax.Array,  # [B, S, Hkv, D] verify-column keys
    v: jax.Array,
    page_table: jax.Array,  # [B, P]
    base: jax.Array,  # [B] cache length; column j lands at base + j
    n_tokens: jax.Array,  # [B] valid columns per lane (0 = lane not verifying)
    layer: jax.Array,  # scalar i32
) -> jax.Array:
    """Scatter a speculative verify dispatch's K/V: column ``j`` of lane
    ``b`` lands at position ``base[b] + j``.  Columns past ``n_tokens``
    (rejected-draft padding, non-speculating lanes) and positions past the
    lane's page allocation route to trash page 0 -- the multi-token
    sibling of :func:`write_decode_kv`'s dead-lane handling.  Rejected
    columns' writes within a lane's pages are *garbage by design*: they
    sit beyond the committed cache length, are never attended (the read
    window is ``seq_lens``-bounded), and the next verify/decode step
    overwrites them in sequence order before the length passes them."""
    B, S, Hkv, D = k.shape
    page_size = kv_data(kv_pages).shape[3]
    P = page_table.shape[1]
    positions = base[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]  # [B, S]
    valid = jnp.arange(S)[None, :] < n_tokens[:, None]  # [B, S]
    page_idx = positions // page_size
    slot = jnp.where(valid, positions % page_size, 0)
    ids = jnp.take_along_axis(page_table, jnp.clip(page_idx, 0, P - 1), axis=1)
    ids = jnp.where(valid & (page_idx < P), ids, 0)
    flat_ids = ids.reshape(B * S)
    flat_slot = slot.reshape(B * S)
    kv_pages = _kv_write(
        kv_pages, 0, layer, flat_ids, k.reshape(B * S, Hkv, D),
        slot=flat_slot,
    )
    kv_pages = _kv_write(
        kv_pages, 1, layer, flat_ids, v.reshape(B * S, Hkv, D),
        slot=flat_slot,
    )
    return kv_pages


@hot_path
def write_packed_kv(
    kv_pages: jax.Array,  # [L, 2, num_pages, page, Hkv, D]
    k: jax.Array,  # [Np, Hkv, D] packed fresh keys
    v: jax.Array,  # [Np, Hkv, D]
    page_table: jax.Array,  # [B, P]
    lane: jax.Array,  # [Np] lane per packed token (B = padding)
    pos: jax.Array,  # [Np] absolute position per token
    valid: jax.Array,  # [Np] bool (False = pad / dead row -> trash page 0)
    layer: jax.Array,  # scalar i32
) -> jax.Array:
    """Scatter a packed unified dispatch's K/V: packed token ``n`` of
    lane ``lane[n]`` lands at position ``pos[n]`` through that lane's
    page table.  The flat-axis sibling of :func:`write_spec_kv` --
    invalid rows (packed-axis padding, device-dead decode lanes) and
    positions past the lane's allocation route to trash page 0."""
    Np = k.shape[0]
    page_size = kv_data(kv_pages).shape[3]
    B, P = page_table.shape
    lane_c = jnp.clip(lane.astype(jnp.int32), 0, B - 1)
    page_idx = pos // page_size
    ok = valid & (page_idx < P) & (lane.astype(jnp.int32) < B)
    slot = jnp.where(ok, pos % page_size, 0)
    ids = page_table[lane_c, jnp.clip(page_idx, 0, P - 1)]
    ids = jnp.where(ok, ids, 0)
    kv_pages = _kv_write(kv_pages, 0, layer, ids, k, slot=slot)
    kv_pages = _kv_write(kv_pages, 1, layer, ids, v, slot=slot)
    return kv_pages


@hot_path
def write_decode_kv(
    kv_pages: jax.Array,  # [L, 2, num_pages, page, Hkv, D]
    k: jax.Array,  # [B, Hkv, D] one token
    v: jax.Array,
    page_table: jax.Array,  # [B, P]
    positions: jax.Array,  # [B] position the token lands at
    layer: jax.Array,  # scalar i32
) -> jax.Array:
    page_size = kv_data(kv_pages).shape[3]
    P = page_table.shape[1]
    page_idx = positions // page_size
    slot = positions % page_size
    ids = jnp.take_along_axis(
        page_table, jnp.clip(page_idx, 0, P - 1)[:, None], axis=1
    )[:, 0]
    # a lane frozen at its capacity (page_idx == P) must land on trash page
    # 0, not clamp into its own last live page -- its stale write repeats
    # every step while other lanes decode
    ids = jnp.where(page_idx < P, ids, 0)
    kv_pages = _kv_write(kv_pages, 0, layer, ids, k, slot=slot)
    kv_pages = _kv_write(kv_pages, 1, layer, ids, v, slot=slot)
    return kv_pages
