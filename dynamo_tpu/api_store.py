"""api-store: the deployment-artifact registry behind ``dynamo deploy``.

Reference ``deploy/cloud/api-store`` (FastAPI + Postgres + S3, ~2.5k LoC):
a REST service where built graph components are registered, versioned,
uploaded, downloaded, and where deployment records live.  The TPU-native
rebuild keeps the same REST surface shape but stores everything in the
first-party hub -- component/version/deployment records in the KV space
(``apistore/…``), artifact blobs in the object store -- so the registry
shares the cluster's one control plane instead of dragging in a SQL
database and an S3 bucket.

Routes (`/api/v1`, mirroring the reference's dynamo_components API):

  POST /api/v1/components                     {"name", "description"?}
  GET  /api/v1/components
  GET  /api/v1/components/{name}
  POST /api/v1/components/{name}/versions     {"version", "manifest"?}
  GET  /api/v1/components/{name}/versions
  PUT  /api/v1/components/{name}/versions/{v}/artifact   (raw body)
  GET  /api/v1/components/{name}/versions/{v}/artifact
  POST /api/v1/deployments                    {"name", "spec"}
  GET  /api/v1/deployments
  GET  /health

Run: ``dynamo-tpu api-store --hub H:P [--port 8282]``.
"""

from __future__ import annotations

import json
import logging
import re
import time
from typing import Any, Dict, Optional

from .http.server import BadRequest, HttpServer, Request, Response

logger = logging.getLogger("dynamo.api_store")

KV_COMPONENT = "apistore/components/{name}"
KV_VERSION = "apistore/components/{name}/versions/{version}"
KV_DEPLOYMENT = "apistore/deployments/{name}"
OBJ_ARTIFACT = "apistore/artifacts/{name}/{version}"

_NAME_RE = re.compile(r"^[\w][\w.-]{0,127}$")


def _bad(msg: str, status: int = 400) -> Response:
    return Response.json({"error": msg}, status)


class ApiStoreService:
    """REST registry over the hub (see module docstring)."""

    def __init__(self, hub, host: str = "0.0.0.0", port: int = 8282) -> None:
        self.hub = hub
        self.server = HttpServer(host=host, port=port)
        self.server.fallback = self._dispatch

    @property
    def address(self):
        return self.server.address

    async def start(self) -> None:
        await self.server.start()
        logger.info("api-store listening on %s:%d", *self.server.address)

    async def stop(self) -> None:
        await self.server.stop()

    # -- routing (path-parameterized, so the fallback handler does it) ------

    async def _dispatch(self, req: Request) -> Response:
        try:
            parts = [p for p in req.path.split("?")[0].split("/") if p]
            m = req.method.upper()
            if parts == ["health"]:
                return Response.json({"status": "ok"})
            if len(parts) < 2 or parts[0] != "api" or parts[1] != "v1":
                return _bad("not found", 404)
            rest = parts[2:]
            if rest == ["components"]:
                if m == "POST":
                    return await self._create_component(req)
                if m == "GET":
                    return await self._list(KV_COMPONENT.format(name=""))
            elif len(rest) == 2 and rest[0] == "components":
                if m == "GET":
                    return await self._get(KV_COMPONENT.format(name=rest[1]))
            elif len(rest) == 3 and rest[0] == "components" and rest[2] == "versions":
                if m == "POST":
                    return await self._create_version(req, rest[1])
                if m == "GET":
                    return await self._list(
                        KV_VERSION.format(name=rest[1], version="")
                    )
            elif (
                len(rest) == 5
                and rest[0] == "components"
                and rest[2] == "versions"
                and rest[4] == "artifact"
            ):
                if m == "PUT":
                    return await self._put_artifact(req, rest[1], rest[3])
                if m == "GET":
                    return await self._get_artifact(rest[1], rest[3])
            elif rest == ["deployments"]:
                if m == "POST":
                    return await self._create_deployment(req)
                if m == "GET":
                    return await self._list(KV_DEPLOYMENT.format(name=""))
            elif len(rest) == 2 and rest[0] == "deployments":
                if m == "GET":
                    return await self._get_deployment(rest[1])
            return _bad("not found", 404)
        except BadRequest as e:
            # malformed client input is a 400, same as the server's own
            # registered routes -- not a logged server fault
            return _bad(str(e), 400)
        except Exception as e:  # noqa: BLE001 - REST boundary
            logger.exception("api-store request failed")
            return _bad(f"internal error: {e}", 500)

    # -- records -------------------------------------------------------------

    async def _create_component(self, req: Request) -> Response:
        body = req.json() or {}
        name = body.get("name") or ""
        if not _NAME_RE.match(name):
            return _bad("'name' must match [A-Za-z0-9_.-]{1,128}")
        record = {
            "name": name,
            "description": body.get("description") or "",
            "created_at": time.time(),
        }
        created = await self.hub.kv_create(
            KV_COMPONENT.format(name=name), json.dumps(record).encode()
        )
        if not created:
            return _bad(f"component {name!r} already exists", 409)
        return Response.json(record, 201)

    async def _create_version(self, req: Request, name: str) -> Response:
        if not await self._exists(KV_COMPONENT.format(name=name)):
            return _bad(f"component {name!r} not found", 404)
        body = req.json() or {}
        version = body.get("version") or ""
        if not _NAME_RE.match(version):
            return _bad("'version' must match [A-Za-z0-9_.-]{1,128}")
        record = {
            "name": name,
            "version": version,
            "manifest": body.get("manifest") or {},
            "upload_status": "pending",  # reference DynamoComponentUploadStatus
            "created_at": time.time(),
        }
        created = await self.hub.kv_create(
            KV_VERSION.format(name=name, version=version),
            json.dumps(record).encode(),
        )
        if not created:
            return _bad(f"version {name}:{version} already exists", 409)
        return Response.json(record, 201)

    async def _put_artifact(self, req: Request, name: str, version: str) -> Response:
        key = KV_VERSION.format(name=name, version=version)
        match = [
            v for k, v in await self.hub.kv_get_prefix(key) if k == key
        ]
        if not match:
            return _bad(f"version {name}:{version} not found", 404)
        await self.hub.obj_put(
            OBJ_ARTIFACT.format(name=name, version=version), req.body
        )
        record = json.loads(match[0])
        record["upload_status"] = "success"
        record["artifact_bytes"] = len(req.body)
        await self.hub.kv_put(key, json.dumps(record).encode())
        return Response.json(record)

    async def _get_artifact(self, name: str, version: str) -> Response:
        blob = await self.hub.obj_get(
            OBJ_ARTIFACT.format(name=name, version=version)
        )
        if blob is None:
            return _bad(f"artifact {name}:{version} not found", 404)
        return Response(
            status=200,
            headers={"Content-Type": "application/octet-stream"},
            body=blob,
        )

    async def _create_deployment(self, req: Request) -> Response:
        body = req.json() or {}
        name = body.get("name") or ""
        if not _NAME_RE.match(name):
            return _bad("'name' must match [A-Za-z0-9_.-]{1,128}")
        record = {
            "name": name,
            "spec": body.get("spec") or {},
            "created_at": time.time(),
        }
        # deployments are upserts: re-deploying a graph updates the record
        await self.hub.kv_put(
            KV_DEPLOYMENT.format(name=name), json.dumps(record).encode()
        )
        return Response.json(record, 201)

    async def _get_deployment(self, name: str) -> Response:
        """Record + operator status, merged on read.

        The operator writes status under ``{record}/status`` (its own key,
        so a concurrent re-deploy upsert can never be clobbered -- the k8s
        status-subresource isolation); the GET view presents them as one
        object, the CRD-with-status shape."""
        key = KV_DEPLOYMENT.format(name=name)
        record = None
        status = None
        for k, v in await self.hub.kv_get_prefix(key):
            try:
                if k == key:
                    record = json.loads(v)
                elif k == key + "/status":
                    status = json.loads(v)
            except Exception:
                logger.warning("skipping corrupt store record at %s", k)
                continue
        if record is None:
            return _bad("not found", 404)
        if status is not None:
            record["status"] = status
        return Response.json(record)

    # -- shared helpers ------------------------------------------------------

    async def _exists(self, key: str) -> bool:
        # exact-key check: a prefix hit on a sibling ("comp" vs "comp2")
        # must not count
        return any(k == key for k, _v in await self.hub.kv_get_prefix(key))

    async def _get(self, key: str) -> Response:
        entries = await self.hub.kv_get_prefix(key)
        for k, v in entries:
            if k == key:
                return Response.json(json.loads(v))
        return _bad("not found", 404)

    async def _list(self, prefix: str) -> Response:
        entries = await self.hub.kv_get_prefix(prefix)
        items = []
        for k, v in entries:
            # versions live UNDER component keys; a component listing must
            # not include them
            tail = k[len(prefix):]
            if "/" in tail:
                continue
            try:
                items.append(json.loads(v))
            except Exception:
                logger.warning("skipping corrupt store record at %s", k)
                continue
        return Response.json({"items": items, "total": len(items)})
