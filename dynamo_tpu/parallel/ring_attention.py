"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context strategy (SURVEY.md 5.7): prompts longer than one device's
memory/compute budget shard their *sequence* dimension across the ``sp``
axis.  Each device holds one contiguous chunk of Q and its local chunk of
K/V; K/V chunks rotate around the ring via ``jax.lax.ppermute`` (one ICI
hop per step) while each device accumulates flash-style online softmax
against every chunk it sees.  After ``sp`` steps every Q chunk has attended
to every K/V chunk; peak memory per device is O(T/sp) and the rotation
overlaps with the attention math of the previous chunk.

This is the TPU-native replacement for the reference's single-GPU long-
context ceiling (its engines cap at what one GPU's KV fits); capability
parity target, not a translation -- the reference has no CP implementation
to copy.

Causal masking uses global positions (device i covers positions
``[i*C, (i+1)*C)``), so chunks strictly in the future contribute nothing --
the plain ring wastes those steps' FLOPs (the classic load imbalance;
striped layouts fix it and can layer on later).  Numerics: f32 running
max/sum/accumulator, matching engine/attention.py and ops/paged_attention.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..engine import attention as att
from ..engine.config import ModelConfig
from ..engine.model import Params, lm_logits, transformer
from .mesh import shard_map_compat

_NEG_INF = -1e30


def ring_attention_chunk(
    q: jax.Array,  # [B, C, Hq, D] this device's query chunk
    k: jax.Array,  # [B, C, Hkv, D] this device's key chunk
    v: jax.Array,  # [B, C, Hkv, D]
    seq_lens: jax.Array,  # [B] global valid length (replicated)
    axis_name: str,
    axis_size: int,
    window: int = 0,  # sliding-window width; 0 = full attention
) -> jax.Array:
    """Per-shard body (run under shard_map over ``axis_name``).

    Returns the attention output for the local Q chunk [B, C, Hq, D].
    """
    B, C, Hq, D = q.shape
    Hkv = k.shape[2]
    n_rep = Hq // Hkv
    idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))

    qpos = idx * C + jnp.arange(C)  # [C] global positions of local queries
    qf = q.astype(jnp.float32)

    m = jnp.full((B, Hq, C, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, Hq, C, 1), jnp.float32)
    acc = jnp.zeros((B, Hq, C, D), jnp.float32)

    def one_chunk(m, l, acc, k, v, src):
        kpos = src * C + jnp.arange(C)  # [C] global positions of these keys
        kr = att.repeat_kv(k, n_rep).astype(jnp.float32)
        vr = att.repeat_kv(v, n_rep).astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kr) * scale  # [B, Hq, C, C]
        causal = kpos[None, :] <= qpos[:, None]
        if window > 0:
            # sliding window over GLOBAL positions: a key further than
            # window-1 behind the query contributes nothing regardless of
            # which shard holds it
            causal = causal & (qpos[:, None] - kpos[None, :] < window)
        mask = causal[None, None] & (
            kpos[None, None, None, :] < seq_lens[:, None, None, None]
        )
        s = jnp.where(mask, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        acc = acc * alpha + jnp.einsum("bhqk,bkhd->bhqd", p, vr)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        return m_new, l, acc

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    for step in range(axis_size):
        src = (idx - step) % axis_size
        m, l, acc = one_chunk(m, l, acc, k, v, src)
        if step != axis_size - 1:  # final rotation would be unused
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)

    safe = jnp.where(l > 0.0, l, 1.0)
    out = (acc / safe).transpose(0, 2, 1, 3)  # [B, C, Hq, D]
    return out.astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis_name: str = "sp", window: int = 0):
    """shard_map'ed causal attention over sequence-sharded [B, T, H, D]
    arrays; composes inside a jit whose other axes GSPMD shards."""
    axis_size = mesh.shape[axis_name]
    spec = P(None, axis_name, None, None)

    fn = shard_map_compat(
        partial(
            ring_attention_chunk, axis_name=axis_name, axis_size=axis_size,
            window=window,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, P(None)),
        out_specs=spec,
    )

    def ring_attn(q, k, v, seq_lens):
        return fn(q, k, v, seq_lens)

    return ring_attn


@partial(
    jax.jit,
    static_argnames=("cfg", "mesh", "axis_name"),
    donate_argnames=("kv_pages",),
)
def ring_prefill_step(
    params: Params,
    cfg: ModelConfig,
    kv_pages: jax.Array,  # [L, 2, num_pages, page, Hkv, D]
    tokens: jax.Array,  # [B, T] bucket-padded prompts, T % sp == 0
    seq_lens: jax.Array,  # [B] true prompt lengths
    page_table: jax.Array,  # [B, T // page_size]
    mesh: Mesh,
    axis_name: str = "sp",
) -> Tuple[jax.Array, jax.Array]:
    """Sequence-parallel prefill: engine/step.py prefill_step with the
    sequence dimension sharded over ``sp`` and attention running as a ring.

    Everything else (QKV projections, MLP, KV page writes) is plain GSPMD:
    the per-token ops shard trivially over T, and the page scatter's
    collectives are XLA's problem.  Returns (last-token logits [B, V] f32,
    updated kv_pages)."""
    B, T = tokens.shape
    if T % mesh.shape[axis_name]:
        raise ValueError(
            f"prefill bucket {T} not divisible by sp={mesh.shape[axis_name]}"
        )
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    ring = make_ring_attention(mesh, axis_name, cfg.sliding_window or 0)

    def attn_fn(q, k, v, kv, layer):
        out = ring(q, k, v, seq_lens)
        new_kv = att.write_prefill_kv(kv, k, v, page_table, layer)
        return out, new_kv

    hidden, kv_pages = transformer(params, cfg, tokens, positions, kv_pages, attn_fn)
    last = jnp.clip(seq_lens - 1, 0, T - 1)
    hidden_last = jnp.take_along_axis(hidden, last[:, None, None], axis=1)[:, 0]
    return lm_logits(params, cfg, hidden_last), kv_pages
