"""Mesh construction over TPU slices.

Axes (scaling-book conventions):

- ``dp``   -- data parallel: independent batch lanes (serving-layer worker
  replication maps here when one engine spans multiple hosts).
- ``tp``   -- tensor parallel: attention heads / MLP hidden sharded; the
  all-reduce rides ICI.
- ``pp``   -- pipeline parallel over layer groups (cross-host).
- ``sp``   -- sequence/context parallel (ring attention) for long context.
- ``ep``   -- expert parallel: MoE expert weights and dispatch buffers
  sharded over experts; the token shuffle rides ICI (GSPMD inserts the
  all_to_all from the sharding annotations).

``build_mesh`` lays axes out so that tp is innermost (fastest-varying
device order = closest ICI neighbors), matching how XLA enumerates cores in
a slice.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.tp * self.pp * self.sp * self.ep

    def axis_names(self) -> List[str]:
        return ["dp", "pp", "sp", "ep", "tp"]


def build_mesh(
    cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < cfg.num_devices:
        raise ValueError(
            f"mesh needs {cfg.num_devices} devices, have {len(devices)}"
        )
    devices = devices[: cfg.num_devices]
    arr = np.asarray(devices).reshape(cfg.dp, cfg.pp, cfg.sp, cfg.ep, cfg.tp)
    return Mesh(arr, axis_names=tuple(cfg.axis_names()))


def single_device_mesh() -> Mesh:
    return build_mesh(MeshConfig())


def shard_map_compat(f, *, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` moved out of experimental AND renamed its
    replication-check kwarg (``check_rep`` -> ``check_vma``) across the
    jax versions this repo must serve on (TPU driver vs CI container).
    Resolve whichever this runtime carries and disable the check under
    its local name (the per-shard bodies here return intentionally
    stage-local values that the checker would reject)."""
    import inspect

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    params = inspect.signature(fn).parameters
    if "check_vma" in params:
        kwargs["check_vma"] = False
    elif "check_rep" in params:
        kwargs["check_rep"] = False
    return fn(f, **kwargs)


def serving_mesh(
    tp: int = 1, dp: int = 1, devices: Optional[Sequence[jax.Device]] = None
) -> Optional[Mesh]:
    """The engine-startup mesh: dp x tp over the local devices, or None
    when both degrees are 1 (single-chip serving pays zero mesh
    machinery).  Raises when the process cannot see enough devices --
    a silently-shrunk mesh would serve with replicated params and report
    multi-chip throughput it is not getting."""
    tp, dp = max(int(tp), 1), max(int(dp), 1)
    if tp == 1 and dp == 1:
        return None
    return build_mesh(MeshConfig(dp=dp, tp=tp), devices)


def env_parallel_spec() -> dict:
    """``DYN_TP`` / ``DYN_DP`` -> {"tp": n | None, "dp": n | None}: the
    deployment-side override for engine-startup tensor/data parallelism
    (mirrors the DYN_KV_OFFLOAD pattern -- arm the plane without touching
    config).  None means the variable is unset and config decides; a set
    value wins outright, so ``DYN_TP=1`` disarms a config-armed tp.  An
    unparsable value raises: a typo silently falling back to config would
    serve single-chip while the operator believes TP is armed -- the
    worst kind of disarm, since the output is identical either way."""
    import os

    out = {}
    for key, name in (("tp", "DYN_TP"), ("dp", "DYN_DP")):
        raw = os.environ.get(name)
        if raw is None or raw.strip() == "":
            out[key] = None
            continue
        try:
            out[key] = max(int(raw), 1)
        except ValueError:
            raise ValueError(
                f"{name}={raw!r} is not an integer parallel degree"
            ) from None
    return out
