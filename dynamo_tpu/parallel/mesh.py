"""Mesh construction over TPU slices.

Axes (scaling-book conventions):

- ``dp``   -- data parallel: independent batch lanes (serving-layer worker
  replication maps here when one engine spans multiple hosts).
- ``tp``   -- tensor parallel: attention heads / MLP hidden sharded; the
  all-reduce rides ICI.
- ``pp``   -- pipeline parallel over layer groups (cross-host).
- ``sp``   -- sequence/context parallel (ring attention) for long context.
- ``ep``   -- expert parallel: MoE expert weights and dispatch buffers
  sharded over experts; the token shuffle rides ICI (GSPMD inserts the
  all_to_all from the sharding annotations).

``build_mesh`` lays axes out so that tp is innermost (fastest-varying
device order = closest ICI neighbors), matching how XLA enumerates cores in
a slice.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.tp * self.pp * self.sp * self.ep

    def axis_names(self) -> List[str]:
        return ["dp", "pp", "sp", "ep", "tp"]


def build_mesh(
    cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < cfg.num_devices:
        raise ValueError(
            f"mesh needs {cfg.num_devices} devices, have {len(devices)}"
        )
    devices = devices[: cfg.num_devices]
    arr = np.asarray(devices).reshape(cfg.dp, cfg.pp, cfg.sp, cfg.ep, cfg.tp)
    return Mesh(arr, axis_names=tuple(cfg.axis_names()))


def single_device_mesh() -> Mesh:
    return build_mesh(MeshConfig())
