"""Multi-host bootstrap: one engine spanning several TPU hosts.

Reference parity: the engines' ``MultiNodeConfig`` (lib/llm engines glue:
node_rank / num_nodes / leader address handed to vLLM's distributed
runtime).  The TPU-native equivalent is ``jax.distributed``: every host
runs the same program, the leader coordinates, and ``jax.devices()``
becomes the *global* device list -- after which the existing mesh/GSPMD
machinery (parallel.mesh, parallel.sharding) works unchanged across hosts
with XLA collectives riding ICI/DCN.

Usage (every host, same binary)::

    cfg = MultiNodeConfig.from_env()        # DYN_NUM_NODES / DYN_NODE_RANK /
    initialize_multihost(cfg)               # DYN_LEADER_ADDR
    mesh = build_mesh(MeshConfig(dp=..., tp=...))   # global devices

Single-node configs make ``initialize_multihost`` a no-op, so the same
launch path serves laptops and pods.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional

logger = logging.getLogger("dynamo.multihost")


@dataclass
class MultiNodeConfig:
    """Reference MultiNodeConfig shape: ranks + a leader address."""

    num_nodes: int = 1
    node_rank: int = 0
    # leader host:port for the jax.distributed coordinator
    leader_addr: str = ""

    @classmethod
    def from_env(cls) -> "MultiNodeConfig":
        return cls(
            num_nodes=int(os.environ.get("DYN_NUM_NODES", "1")),
            node_rank=int(os.environ.get("DYN_NODE_RANK", "0")),
            leader_addr=os.environ.get("DYN_LEADER_ADDR", ""),
        )

    def validate(self) -> None:
        if self.num_nodes < 1:
            raise ValueError(f"num_nodes must be >= 1, got {self.num_nodes}")
        if not (0 <= self.node_rank < self.num_nodes):
            raise ValueError(
                f"node_rank {self.node_rank} out of range for "
                f"{self.num_nodes} nodes"
            )
        if self.num_nodes > 1 and not self.leader_addr:
            raise ValueError("multi-node requires leader_addr (host:port)")

    @property
    def is_multi_node(self) -> bool:
        return self.num_nodes > 1

    @property
    def is_leader(self) -> bool:
        return self.node_rank == 0


def initialize_multihost(
    cfg: Optional[MultiNodeConfig] = None,
    local_device_ids: Optional[list] = None,
) -> MultiNodeConfig:
    """Join the multi-host world (must run before first backend touch).

    No-op for single-node configs.  After this returns, ``jax.devices()``
    lists every host's chips and sharded computations span them."""
    cfg = cfg or MultiNodeConfig.from_env()
    cfg.validate()
    if not cfg.is_multi_node:
        return cfg
    import jax

    logger.info(
        "joining multihost world: rank %d/%d, leader %s",
        cfg.node_rank, cfg.num_nodes, cfg.leader_addr,
    )
    jax.distributed.initialize(
        coordinator_address=cfg.leader_addr,
        num_processes=cfg.num_nodes,
        process_id=cfg.node_rank,
        local_device_ids=local_device_ids,
    )
    return cfg
