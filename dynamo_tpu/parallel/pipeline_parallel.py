"""Pipeline parallelism over the ``pp`` mesh axis (GPipe-style, inference).

Stage-partitions the stacked layer weights ``[L, ...]`` (and the KV pages,
which carry the same leading layer axis) across ``pp`` devices and streams
microbatches through the stages with ``ppermute`` handoffs: at tick ``t``
stage ``s`` runs microbatch ``t - s`` through its ``L/pp`` local layers,
then passes the activations one hop down the ring.  A full forward takes
``M + pp - 1`` ticks for ``M`` microbatches; the (pp-1)-tick bubble
amortizes as M grows.

TPU-native by construction: every stage executes the same SPMD program
under ``shard_map`` (no per-stage Python), handoffs are single ICI hops,
and the local layer loop is the same ``lax.scan`` over
``model.transformer_layer`` the single-device path uses -- the math cannot
diverge.  Bubble ticks compute garbage by design (SPMD cannot skip); their
KV writes are routed to trash page 0 so they cannot corrupt live pages.

Capability parity: the reference delegates PP to its engines (vLLM
--pipeline-parallel-size, SURVEY.md 2.8); here it is first-party.  Prefill
is the PP-relevant phase (compute-bound); decode stays dp/tp-sharded.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..engine import attention as att
from ..engine.config import ModelConfig
from ..engine.model import (
    Params,
    lm_logits,
    rms_norm,
    rope_cos_sin,
    scan_layers,
)
from .mesh import shard_map_compat


@partial(
    jax.jit,
    static_argnames=("cfg", "mesh", "axis_name", "num_microbatches"),
    donate_argnames=("kv_pages",),
)
def pp_prefill_step(
    params: Params,
    cfg: ModelConfig,
    kv_pages: jax.Array,  # [L, 2, num_pages, page, Hkv, D]
    tokens: jax.Array,  # [B, T] bucket-padded prompts
    seq_lens: jax.Array,  # [B] true prompt lengths
    page_table: jax.Array,  # [B, T // page_size]
    mesh: Mesh,
    axis_name: str = "pp",
    num_microbatches: int = 0,  # 0 = one per stage
) -> Tuple[jax.Array, jax.Array]:
    """Pipeline-parallel prefill; returns (last-token logits [B, V] f32,
    updated kv_pages).  Matches engine/step.py prefill_step numerically."""
    num_stages = mesh.shape[axis_name]
    M = num_microbatches or num_stages
    B, T = tokens.shape
    L = kv_pages.shape[0]
    if L % num_stages:
        raise ValueError(f"{L} layers not divisible by pp={num_stages}")
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    D = cfg.head_dim
    dtype = jnp.dtype(cfg.dtype)

    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    cos, sin = rope_cos_sin(positions, D, cfg.rope_theta, cfg.rope_scaling)  # [B, T, D]
    x = params["embed"][tokens].astype(dtype)  # [B, T, H]

    def split(a):  # [B, ...] -> [M, mb, ...]
        return a.reshape((M, mb) + a.shape[1:])

    x_mb, cos_mb, sin_mb = split(x), split(cos), split(sin)
    pt_mb, lens_mb = split(page_table), split(seq_lens)

    def stage(lp_local, kv_local, x_all, cos_a, sin_a, pt_a, lens_a):
        s = jax.lax.axis_index(axis_name)
        H = x_all.shape[-1]
        state = jnp.zeros((mb, T, H), dtype)
        out = jnp.zeros_like(x_all)
        kv = kv_local
        perm = [(i, i + 1) for i in range(num_stages - 1)]
        for t in range(M + num_stages - 1):
            feed = x_all[jnp.clip(t, 0, M - 1)]
            x_in = jnp.where(s == 0, feed, state)
            mbi = t - s  # microbatch this stage holds at tick t
            valid = (mbi >= 0) & (mbi < M)
            mbi_c = jnp.clip(mbi, 0, M - 1)
            cos_t, sin_t = cos_a[mbi_c], sin_a[mbi_c]
            lens_t = lens_a[mbi_c]
            # bubble ticks write their (garbage) KV to trash page 0
            pt_t = jnp.where(valid, pt_a[mbi_c], 0)

            def attn_fn(q, k, v, kv_buf, layer):
                o = att.prefill_attention(
                    q, k, v, lens_t, cfg.sliding_window or 0
                )
                return o, att.write_prefill_kv(kv_buf, k, v, pt_t, layer)

            x_out, kv = scan_layers(lp_local, kv, x_in, cos_t, sin_t, cfg, attn_fn)
            oi = t - (num_stages - 1)
            if oi >= 0:
                emit = jnp.where(s == num_stages - 1, x_out, 0)
                out = out.at[oi].set(emit.astype(out.dtype))
            if t != M + num_stages - 2:
                state = jax.lax.ppermute(x_out, axis_name, perm)
        # only the last stage wrote non-zeros; psum replicates the result
        return jax.lax.psum(out, axis_name), kv

    fn = shard_map_compat(
        stage,
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(), P(), P(), P(), P()),
        out_specs=(P(), P(axis_name)),
    )
    hidden_mb, kv_pages = fn(
        params["layers"], kv_pages, x_mb, cos_mb, sin_mb, pt_mb, lens_mb
    )
    hidden = hidden_mb.reshape(B, T, -1)
    hidden = rms_norm(hidden, params["final_norm"], cfg.rms_norm_eps)
    last = jnp.clip(seq_lens - 1, 0, T - 1)
    hidden_last = jnp.take_along_axis(hidden, last[:, None, None], axis=1)[:, 0]
    return lm_logits(params, cfg, hidden_last), kv_pages
