"""Device-mesh parallelism: mesh construction and sharding rules.

The reference delegates intra-model parallelism to its GPU engines (NCCL
inside vLLM/TRT-LLM -- SURVEY.md 2.8); here it is first-party: a
``jax.sharding.Mesh`` over ICI with named axes and ``NamedSharding``
annotations on the params/KV pytrees; XLA inserts the collectives.
"""

from .mesh import MeshConfig, build_mesh
from .sharding import kv_pspec, batch_pspecs, param_pspecs, shard_params

__all__ = [
    "MeshConfig",
    "build_mesh",
    "param_pspecs",
    "kv_pspec",
    "batch_pspecs",
    "shard_params",
]
