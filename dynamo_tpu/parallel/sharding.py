"""Sharding rules: PartitionSpecs for the model params, KV pages, and batch.

Megatron-style TP layout expressed as GSPMD annotations (XLA inserts the
collectives -- SURVEY.md 5.8 "engine-internal collectives -> XLA over ICI"):

- attention qkv projections column-parallel (heads sharded), output
  projection row-parallel -> one all-reduce per attention block;
- MLP gate/up column-parallel, down row-parallel -> one all-reduce per MLP;
- KV pages sharded over kv_heads so each tp shard attends its own heads
  with zero cross-chip traffic on the decode hot path;
- MoE expert weights sharded over the ``ep`` axis (experts per device
  group), with column/row TP inside each expert.

All specs carry the leading ``num_layers`` axis unsharded (layers are
scanned, not distributed; pipeline parallel splits the scan instead).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.config import ModelConfig
from ..engine.model import Params


def param_pspecs(cfg: ModelConfig) -> Dict[str, P]:
    """Pytree-path (``a/b``) -> PartitionSpec for every parameter."""
    specs: Dict[str, P] = {
        "embed": P(None, "tp"),
        "final_norm": P(None),
        "layers/wq": P(None, None, "tp"),
        "layers/wk": P(None, None, "tp"),
        "layers/wv": P(None, None, "tp"),
        "layers/wo": P(None, "tp", None),
        "layers/input_norm": P(None, None),
        "layers/post_norm": P(None, None),
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    if cfg.attention_bias:
        specs["layers/bq"] = P(None, "tp")
        specs["layers/bk"] = P(None, "tp")
        specs["layers/bv"] = P(None, "tp")
    if cfg.qk_norm:  # [L, D] per-head norms replicate (applied per head)
        specs["layers/q_norm"] = P(None, None)
        specs["layers/k_norm"] = P(None, None)
    if cfg.is_moe:
        # experts over ep; within an expert, classic column/row TP
        specs["layers/router"] = P(None, None, None)
        specs["layers/w_gate"] = P(None, "ep", None, "tp")
        specs["layers/w_up"] = P(None, "ep", None, "tp")
        specs["layers/w_down"] = P(None, "ep", "tp", None)
    else:
        specs["layers/w_gate"] = P(None, None, "tp")
        specs["layers/w_up"] = P(None, None, "tp")
        specs["layers/w_down"] = P(None, "tp", None)
    return specs


def kv_pspec(cfg: ModelConfig) -> P:
    """KV pages [L, 2, pages, page, Hkv, D]: shard kv heads over tp when
    divisible (GQA models with few kv heads and large tp replicate)."""
    return P(None, None, None, None, "tp", None)


def batch_pspecs() -> Dict[str, P]:
    """Decode batch arrays sharded over dp."""
    return {
        "tokens": P("dp"),
        "seq_lens": P("dp"),
        "page_table": P("dp", None),
        "prompt_tokens": P("dp", None),
    }


def _flatten_with_paths(tree: Any, prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_with_paths(v, f"{prefix}{k}/"))
    else:
        out[prefix[:-1]] = tree
    return out


def param_shardings(
    cfg: ModelConfig, mesh: Mesh
) -> Dict[str, NamedSharding]:
    """Path -> NamedSharding map (feeds the streaming safetensors loader)."""
    return {
        path: NamedSharding(mesh, spec) for path, spec in param_pspecs(cfg).items()
    }


def shard_params(params: Params, cfg: ModelConfig, mesh: Mesh) -> Params:
    """Device_put an assembled params pytree onto its TP layout.

    Axes that do not divide evenly (e.g. kv heads < tp) fall back to
    replication for that tensor.
    """
    flat = _flatten_with_paths(params)
    specs = param_pspecs(cfg)
    out_flat: Dict[str, jax.Array] = {}
    for path, leaf in flat.items():
        spec = specs.get(path, P())
        spec = _compatible_spec(spec, leaf.shape, mesh)
        out_flat[path] = jax.device_put(leaf, NamedSharding(mesh, spec))
    return _unflatten(out_flat)


def shard_kv(kv: jax.Array, cfg: ModelConfig, mesh: Mesh) -> jax.Array:
    spec = _compatible_spec(kv_pspec(cfg), kv.shape, mesh)
    return jax.device_put(kv, NamedSharding(mesh, spec))


def _compatible_spec(spec: P, shape, mesh: Mesh) -> P:
    """Drop mesh axes that do not divide the corresponding dim."""
    fixed = []
    for i, axis in enumerate(spec):
        if axis is None:
            fixed.append(None)
            continue
        size = mesh.shape.get(axis, 1)
        if i < len(shape) and shape[i] % size == 0:
            fixed.append(axis)
        else:
            fixed.append(None)
    return P(*fixed)


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for path, leaf in flat.items():
        parts = path.split("/")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return out
