"""Sharding rules: PartitionSpecs for the model params, KV pages, and batch.

Megatron-style TP layout expressed as GSPMD annotations (XLA inserts the
collectives -- SURVEY.md 5.8 "engine-internal collectives -> XLA over ICI"):

- attention qkv projections column-parallel (heads sharded), output
  projection row-parallel -> one all-reduce per attention block;
- MLP gate/up column-parallel, down row-parallel -> one all-reduce per MLP;
- KV pages sharded over kv_heads so each tp shard attends its own heads
  with zero cross-chip traffic on the decode hot path;
- MoE expert weights sharded over the ``ep`` axis (experts per device
  group), with column/row TP inside each expert.

All specs carry the leading ``num_layers`` axis unsharded (layers are
scanned, not distributed; pipeline parallel splits the scan instead).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.config import ModelConfig
from ..engine.model import Params

# Declared tick-role device-touch site (dynalint DT019): assemble_shards
# is the designed per-shard fetch behind the engine's commit/export sync
# points -- its device_get is the sync those sites already declare.
PACKED_DISPATCH_SITES = ("assemble_shards",)


def param_pspecs(cfg: ModelConfig) -> Dict[str, P]:
    """Pytree-path (``a/b``) -> PartitionSpec for every parameter."""
    specs: Dict[str, P] = {
        "embed": P(None, "tp"),
        "final_norm": P(None),
        "layers/wq": P(None, None, "tp"),
        "layers/wk": P(None, None, "tp"),
        "layers/wv": P(None, None, "tp"),
        "layers/wo": P(None, "tp", None),
        "layers/input_norm": P(None, None),
        "layers/post_norm": P(None, None),
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    if cfg.attention_bias:
        specs["layers/bq"] = P(None, "tp")
        specs["layers/bk"] = P(None, "tp")
        specs["layers/bv"] = P(None, "tp")
    if cfg.qk_norm:  # [L, D] per-head norms replicate (applied per head)
        specs["layers/q_norm"] = P(None, None)
        specs["layers/k_norm"] = P(None, None)
    if cfg.is_moe:
        # experts over ep; within an expert, classic column/row TP
        specs["layers/router"] = P(None, None, None)
        specs["layers/w_gate"] = P(None, "ep", None, "tp")
        specs["layers/w_up"] = P(None, "ep", None, "tp")
        specs["layers/w_down"] = P(None, "ep", "tp", None)
    else:
        specs["layers/w_gate"] = P(None, None, "tp")
        specs["layers/w_up"] = P(None, None, "tp")
        specs["layers/w_down"] = P(None, "tp", None)
    return specs


def kv_pspec(cfg: ModelConfig) -> P:
    """KV pages [L, 2, pages, page, Hkv, D]: shard kv heads over tp when
    divisible (GQA models with few kv heads and large tp replicate)."""
    return P(None, None, None, None, "tp", None)


def batch_pspecs() -> Dict[str, P]:
    """Decode batch arrays sharded over dp."""
    return {
        "tokens": P("dp"),
        "seq_lens": P("dp"),
        "page_table": P("dp", None),
        "prompt_tokens": P("dp", None),
    }


def _flatten_with_paths(tree: Any, prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_with_paths(v, f"{prefix}{k}/"))
    else:
        out[prefix[:-1]] = tree
    return out


def param_shardings(
    cfg: ModelConfig, mesh: Mesh
) -> Dict[str, NamedSharding]:
    """Path -> NamedSharding map (feeds the streaming safetensors loader)."""
    return {
        path: NamedSharding(mesh, spec) for path, spec in param_pspecs(cfg).items()
    }


def shard_params(params: Params, cfg: ModelConfig, mesh: Mesh) -> Params:
    """Device_put an assembled params pytree onto its TP layout.

    Axes that do not divide evenly (e.g. kv heads < tp) fall back to
    replication for that tensor.
    """
    flat = _flatten_with_paths(params)
    specs = param_pspecs(cfg)
    out_flat: Dict[str, jax.Array] = {}
    for path, leaf in flat.items():
        spec = specs.get(path, P())
        spec = _compatible_spec(spec, leaf.shape, mesh)
        out_flat[path] = jax.device_put(leaf, NamedSharding(mesh, spec))
    return _unflatten(out_flat)


def shard_kv(kv: jax.Array, cfg: ModelConfig, mesh: Mesh) -> jax.Array:
    from ..engine.kv_cache import QuantKV

    if isinstance(kv, QuantKV):
        # int8 pool: data shards like the dense pool (kv heads over tp);
        # the per-row scales carry no head axis and replicate
        spec = _compatible_spec(kv_pspec(cfg), kv.q.shape, mesh)
        return QuantKV(
            q=jax.device_put(kv.q, NamedSharding(mesh, spec)),
            s=jax.device_put(kv.s, NamedSharding(mesh, P())),
        )
    spec = _compatible_spec(kv_pspec(cfg), kv.shape, mesh)
    return jax.device_put(kv, NamedSharding(mesh, spec))


def _compatible_spec(spec: P, shape, mesh: Mesh) -> P:
    """Drop mesh axes that do not divide the corresponding dim."""
    fixed = []
    for i, axis in enumerate(spec):
        if axis is None:
            fixed.append(None)
            continue
        size = mesh.shape.get(axis, 1)
        if i < len(shape) and shape[i] % size == 0:
            fixed.append(axis)
        else:
            fixed.append(None)
    return P(*fixed)


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for path, leaf in flat.items():
        parts = path.split("/")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return out


# ---------------------------------------------------------------------------
# per-shard export: host-side reassembly of sharded device arrays
# ---------------------------------------------------------------------------


def kv_shard_geometry(arr: jax.Array) -> Optional[Dict[str, int]]:
    """Shard geometry of a (possibly sharded) KV array: ``{"axis": i,
    "parts": n}`` for the first sharded axis, or None when replicated /
    unsharded.  Recorded alongside every KV blob that leaves the device
    (disagg export meta, offload tier records, swap snapshots) so a
    restore site can assert it is scattering into a compatible pool."""
    sharding = getattr(arr, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if sharding is None or spec is None:
        return None
    mesh_shape = getattr(sharding, "mesh", None)
    for axis, names in enumerate(spec):
        if names is None:
            continue
        names = names if isinstance(names, tuple) else (names,)
        parts = 1
        for name in names:
            parts *= int(mesh_shape.shape.get(name, 1))
        if parts > 1:
            return {"axis": axis, "parts": parts}
    return None


def assemble_shards(arr: jax.Array) -> np.ndarray:
    """Materialize a device array on host by gathering each addressable
    shard's slice and reassembling -- ONE device->host transfer per shard,
    no cross-chip collective.

    This is the export half of the per-shard KV contract: a tp-sharded
    pool's pages come to host head-slice by head-slice (each chip moves
    only its own kv heads), and the host concatenation rebuilds the
    full-width blob the wire/offload formats carry.  Replicated or
    single-device arrays take the plain ``device_get``; so does the
    multi-host case (non-addressable shards), where the caller is expected
    to run SPMD-lockstep and use a collective fetch instead."""
    sharding = getattr(arr, "sharding", None)
    if (
        sharding is None
        or getattr(sharding, "is_fully_replicated", True)
        or not getattr(sharding, "is_fully_addressable", False)
    ):
        return np.asarray(jax.device_get(arr))
    out = np.empty(arr.shape, jax.numpy.dtype(arr.dtype))
    seen = set()
    for shard in arr.addressable_shards:
        key = tuple(
            (s.start, s.stop) for s in shard.index if isinstance(s, slice)
        )
        if key in seen:
            continue  # replicated twin of an already-copied slice
        seen.add(key)
        out[shard.index] = np.asarray(shard.data)
    return out


# ---------------------------------------------------------------------------
# sharded serving steps: the engine hot paths re-jitted with explicit
# in/out shardings (GSPMD inserts the collectives; nothing is left to
# propagation, so the KV pool can never be silently replicated)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ShardedSteps:
    """Sharding-pinned jit wrappers over the raw engine step functions.

    Built once per engine at startup (``make_sharded_steps``); the engine
    routes every decode-path dispatch through these when it has a mesh.
    Each wrapper declares in/out shardings for the recurrent state --
    params and KV over ``tp`` (kv heads sharded: zero cross-chip traffic
    on the decode hot path), batch/decode-state arrays over ``dp`` -- and
    leaves host-built scratch (row dicts, rng, packed host-bound outputs)
    unconstrained.  Every producer of recurrent decode state is wrapped,
    so the committed shardings form a closed cycle and a placement drift
    surfaces as a loud error at the very next dispatch, not as a silent
    all-gather."""

    mesh: Mesh
    kv_sharding: NamedSharding
    decode_block: Any
    unified_step: Any
    packed_unified_step: Any
    packed_unified_multistep: Any
    verify_and_sample: Any
    update_lanes: Any
    inject_token: Any
    inject_tokens: Any
    zero_count_rows: Any
    bump_counts: Any
    seed_count_rows: Any
    # KV-pool page primitives (disagg delivery, offload onboard, swap
    # snapshots): every producer that reassigns the pool pins its output
    # back onto the pool's sharding, so a host-built blob operand can
    # never drift the placement between dispatches
    scatter_block_pages: Any
    slice_block_pages: Any
    gather_layer_pages: Any
    scatter_layer_pages: Any


def make_sharded_steps(
    mesh: Mesh,
    cfg: ModelConfig,
    params: Params,
    kv_pages: jax.Array,
    max_batch_size: int,
) -> ShardedSteps:
    """Re-jit the serving entry points with explicit in/out shardings.

    Parameter shardings are harvested from the live (already-placed,
    possibly quantized) params pytree and the KV pool, so the declared
    layout is exactly what the loader/quantizer produced -- divisibility
    fallbacks included.  Decode-state arrays shard batch-major over
    ``dp`` (the ``vec``/``mat`` shardings below), filtered through
    :func:`_compatible_spec` at the engine's ``max_batch_size``."""
    from ..engine import step as _step

    param_sh = jax.tree_util.tree_map(lambda x: x.sharding, params)
    # the KV pool may be a QuantKV pytree (int8 data + replicated row
    # scales): harvest per-leaf, so the pinned in/out shardings follow
    # whatever layout the pool was actually placed with
    kv_sh = jax.tree_util.tree_map(lambda x: x.sharding, kv_pages)
    B = max_batch_size
    # the engine's whole device-resident decode state (tokens, seq_lens,
    # limit_lens, active, stop_ids, page_table, counts, SamplingParams
    # leaves) is batch-major with unsharded tails, so exactly two
    # shardings cover it: [B] vectors and [B, x] matrices over ``dp``
    # (dropped by _compatible_spec when B does not divide -- resolve_mesh
    # rejects that for the serving path, but an explicit mesh may hit it)
    vec = NamedSharding(mesh, _compatible_spec(P("dp"), (B,), mesh))
    mat = NamedSharding(
        mesh, _compatible_spec(P("dp", None), (B, 1), mesh)
    )
    samp = _step.SamplingParams(*([vec] * 7))  # every leaf is [B]

    decode_block = jax.jit(
        _step._decode_block,
        static_argnames=(
            "cfg", "num_steps", "use_filters", "top_n", "use_penalties"
        ),
        donate_argnames=("kv_pages", "counts"),
        # (params, kv, tokens, seq_lens, limit_lens, active, stop_ids,
        #  page_table, rng, sampling, counts): rng stays unconstrained (the
        # engine threads an uncommitted key), counts may be None
        in_shardings=(
            param_sh, kv_sh, vec, vec, vec, vec, mat, mat, None, samp, None,
        ),
        # (packed, tokens, seq_lens, active, kv, rng, counts): packed is
        # host-bound (device_get at commit) -- forcing it replicated would
        # insert an all-gather on the hot path for nothing
        out_shardings=(None, vec, vec, vec, kv_sh, None, mat),
    )
    unified_step = jax.jit(
        _step._unified_step,
        static_argnames=("cfg", "top_n", "use_filters"),
        donate_argnames=("kv_pages", "tokens", "seq_lens", "active"),
        # (params, kv, tokens, seq_lens, limit_lens, active, stop_ids,
        #  page_table, p_tokens, p_start, p_lens, p_sample, p_activate,
        #  rng, sampling)
        in_shardings=(
            param_sh, kv_sh, vec, vec, vec, vec, mat, mat,
            mat, vec, vec, vec, vec, None, samp,
        ),
        out_shardings=(None, vec, vec, vec, kv_sh, None),
    )
    packed_unified_step = jax.jit(
        _step._packed_unified_step,
        static_argnames=("cfg", "s_max", "s_spec", "top_n", "use_filters"),
        donate_argnames=("kv_pages", "tokens", "seq_lens", "active"),
        # (params, kv, tokens, seq_lens, limit_lens, active, stop_ids,
        #  page_table, t_tokens, t_lane, t_rel, t_dec, p_start, p_lens,
        #  p_sample, p_activate, dec_cap, seg_off, v_lens, rng, sampling):
        # the packed [Np] token axis interleaves lanes arbitrarily, so it
        # stays unconstrained (GSPMD gathers from the dp-sharded state);
        # the two packed outputs (single-token + folded-verify columns)
        # are host-bound device_get handles, left unconstrained like the
        # other steps' packed outputs
        in_shardings=(
            param_sh, kv_sh, vec, vec, vec, vec, mat, mat,
            None, None, None, None, vec, vec, vec, vec, vec, vec, vec,
            None, samp,
        ),
        out_shardings=(None, None, vec, vec, vec, kv_sh, None),
    )
    packed_unified_multistep = jax.jit(
        _step._packed_unified_multistep,
        static_argnames=(
            "cfg", "s_max", "num_steps", "s_spec", "top_n", "use_filters"
        ),
        donate_argnames=("kv_pages", "tokens", "seq_lens", "active"),
        # identical operand layout to packed_unified_step (the multi-step
        # entry IS that step plus a decode scan over the same state); the
        # widened [B, K, ...] packed output is host-bound like every other
        # packed output and stays unconstrained
        in_shardings=(
            param_sh, kv_sh, vec, vec, vec, vec, mat, mat,
            None, None, None, None, vec, vec, vec, vec, vec, vec, vec,
            None, samp,
        ),
        out_shardings=(None, None, vec, vec, vec, kv_sh, None),
    )
    verify_and_sample = jax.jit(
        _step._verify_and_sample,
        static_argnames=("cfg", "top_n", "use_filters"),
        donate_argnames=("kv_pages",),
        # (params, kv, tokens, base, n_tokens, page_table, rng, sampling)
        in_shardings=(param_sh, kv_sh, mat, vec, vec, mat, None, samp),
        out_shardings=(None, kv_sh),
    )
    update_lanes = jax.jit(
        _step._update_lanes,
        donate_argnames=_step.UPDATE_LANES_DONATED,
        # 13 decode-state arrays + slots + host rows dict (unconstrained)
        in_shardings=(
            vec, vec, vec, vec, mat, mat,
            vec, vec, vec, vec, vec, vec, vec, None, None,
        ),
        out_shardings=(
            vec, vec, vec, vec, mat, mat, vec, vec, vec, vec, vec, vec, vec,
        ),
    )
    inject_token = jax.jit(
        _step._inject_token,
        donate_argnames=("tokens",),
        in_shardings=(vec, None, None),
        out_shardings=vec,
    )
    inject_tokens = jax.jit(
        _step._inject_tokens,
        donate_argnames=("tokens",),
        in_shardings=(vec, None, None),
        out_shardings=vec,
    )
    zero_count_rows = jax.jit(
        _step._zero_count_rows,
        donate_argnames=("counts",),
        in_shardings=(mat, None),
        out_shardings=mat,
    )
    bump_counts = jax.jit(
        _step._bump_counts,
        donate_argnames=("counts",),
        in_shardings=(mat, None, None),
        out_shardings=mat,
    )
    seed_count_rows = jax.jit(
        _step._seed_count_rows,
        donate_argnames=("counts",),
        in_shardings=(mat, None, None, None),
        out_shardings=mat,
    )
    from ..ops import paged_attention as _pa

    # (kv, ids, blob): host-built blobs/ids stay unconstrained; the pool
    # result is pinned so delivery/restore can't drift its placement
    scatter_block_pages = jax.jit(
        _step._scatter_block_pages,
        donate_argnames=("kv_pages",),
        in_shardings=(kv_sh, None, None),
        out_shardings=kv_sh,
    )
    slice_block_pages = jax.jit(
        _step._slice_block_pages,
        in_shardings=(kv_sh, None),
        out_shardings=None,  # snapshot: head-sliced like the pool
    )
    gather_layer_pages = jax.jit(
        _pa._gather_layer_pages,
        in_shardings=(kv_sh, None, None),
        out_shardings=None,
    )
    scatter_layer_pages = jax.jit(
        _pa._scatter_layer_pages,
        donate_argnames=("kv_pages",),
        in_shardings=(kv_sh, None, None, None),
        out_shardings=kv_sh,
    )
    return ShardedSteps(
        mesh=mesh,
        kv_sharding=kv_sh,
        decode_block=decode_block,
        unified_step=unified_step,
        packed_unified_step=packed_unified_step,
        packed_unified_multistep=packed_unified_multistep,
        verify_and_sample=verify_and_sample,
        update_lanes=update_lanes,
        inject_token=inject_token,
        inject_tokens=inject_tokens,
        zero_count_rows=zero_count_rows,
        bump_counts=bump_counts,
        seed_count_rows=seed_count_rows,
        scatter_block_pages=scatter_block_pages,
        slice_block_pages=slice_block_pages,
        gather_layer_pages=gather_layer_pages,
        scatter_layer_pages=scatter_layer_pages,
    )


def make_sharded_drafter(mesh: Mesh, params: Params):
    """Re-jit the model drafter's greedy forward with explicit in/out
    shardings for the serving mesh (the make_sharded_steps contract
    applied to the SECOND weight load): draft params stay pinned to the
    tp layout the loader placed them with, the tiny token window and the
    [1, n] proposal are replicated -- a placement drift of the draft
    weights surfaces at the next propose, never as a silent all-gather
    on the target's decode path."""
    from ..spec.model_drafter import _draft_greedy_tokens

    param_sh = jax.tree_util.tree_map(lambda x: x.sharding, params)
    return jax.jit(
        _draft_greedy_tokens,
        static_argnames=("cfg", "n"),
        # (params, tokens, length): window/length/proposal replicated
        in_shardings=(param_sh, None, None),
        out_shardings=None,
    )
