"""TokenBlockSequence: incremental block-aligned view of a token stream.

Reference parity: lib/llm/src/tokens.rs (TokenBlockSequence with append /
extend / truncate / unwind and incremental block completion; ``split_tokens``
tokens.rs:396,482,813).  The engine appends generated tokens one at a time;
each time a block completes, its block/sequence hashes are computed and the
completion is surfaced so KV events can be published (router feedback loop)
and block-manager registrations can happen.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from ..runtime.thread_sentry import thread_confined
from .hashing import KV_HASH_SEED, block_hash, chain_hash, hash_blocks


@dataclass(frozen=True)
class TokenBlock:
    """One complete, immutable block of tokens."""

    tokens: Tuple[int, ...]
    block_hash: int
    sequence_hash: int
    parent_sequence_hash: int
    position: int  # block index in the sequence


@thread_confined("handoff")
class TokenBlockSequence:
    """Append-only (with unwind) sequence of tokens, chunked into blocks.

    Complete blocks are hashed and frozen; the tail (< block_size tokens)
    stays mutable.  ``append`` returns the newly-completed block, if any.

    Thread model (the ``handoff`` confinement, dynalint DT014): a sequence
    is a per-request value object.  It is built where the request arrives
    (event loop / mocker tick) and, on admission, ownership transfers to
    whichever domain drives the lane (the engine's tick domain) -- the
    admission handoff is the happens-before edge; two domains never hold
    a live reference concurrently.
    """

    def __init__(
        self,
        tokens: Optional[Sequence[int]] = None,
        block_size: int = 16,
        seed: int = KV_HASH_SEED,
    ) -> None:
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.block_size = block_size
        self.seed = seed
        self.blocks: List[TokenBlock] = []
        self._tail: List[int] = []
        self._tokens: List[int] = []
        if tokens:
            self.extend(tokens)

    # -- observers ---------------------------------------------------------

    @property
    def tokens(self) -> List[int]:
        return self._tokens

    def __len__(self) -> int:
        return len(self._tokens)

    @property
    def num_complete_blocks(self) -> int:
        return len(self.blocks)

    @property
    def tail_tokens(self) -> List[int]:
        return list(self._tail)

    def block_hashes(self) -> List[int]:
        return [b.block_hash for b in self.blocks]

    def sequence_hashes(self) -> List[int]:
        return [b.sequence_hash for b in self.blocks]

    @property
    def last_sequence_hash(self) -> int:
        return self.blocks[-1].sequence_hash if self.blocks else 0

    # -- mutation ----------------------------------------------------------

    def append(self, token: int) -> Optional[TokenBlock]:
        """Append one token; returns the block it completed, if any."""
        self._tokens.append(int(token))
        self._tail.append(int(token))
        if len(self._tail) == self.block_size:
            return self._seal_tail()
        return None

    def extend(self, tokens: Sequence[int]) -> List[TokenBlock]:
        """Append many tokens; returns all blocks completed by them."""
        completed: List[TokenBlock] = []
        for t in tokens:
            blk = self.append(t)
            if blk is not None:
                completed.append(blk)
        return completed

    def _seal_tail(self) -> TokenBlock:
        parent = self.last_sequence_hash
        bh = block_hash(self._tail, self.seed)
        sh = bh if not self.blocks else chain_hash(parent, bh, self.seed)
        blk = TokenBlock(
            tokens=tuple(self._tail),
            block_hash=bh,
            sequence_hash=sh,
            parent_sequence_hash=parent,
            position=len(self.blocks),
        )
        self.blocks.append(blk)
        self._tail.clear()
        return blk

    def truncate(self, n_tokens: int) -> None:
        """Drop tokens from the end until ``len(self) == n_tokens``."""
        if n_tokens < 0 or n_tokens > len(self._tokens):
            raise ValueError(f"cannot truncate to {n_tokens}")
        self._tokens = self._tokens[:n_tokens]
        n_complete = n_tokens // self.block_size
        self.blocks = self.blocks[:n_complete]
        self._tail = self._tokens[n_complete * self.block_size :]

    def unwind(self, n_tokens: int) -> None:
        """Remove the last ``n_tokens`` tokens (speculative-decode rollback)."""
        self.truncate(len(self._tokens) - n_tokens)


def split_tokens(
    tokens: Sequence[int], block_size: int, seed: int = KV_HASH_SEED
) -> Tuple[List[int], List[int], List[int]]:
    """One-shot helper for the router: hash all complete blocks of a prompt.

    Returns ``(block_hashes, sequence_hashes, tail_tokens)``.  Reference:
    TokenBlockSequence::split_tokens (tokens.rs:813), used by the KV router
    before the radix lookup (kv_router.rs:183-188).
    """
    bhs, shs = hash_blocks(tokens, block_size, seed)
    n = (len(tokens) // block_size) * block_size
    return bhs, shs, list(tokens[n:])
