"""Token block identity: hashing + block sequences (shared by router/engine/KVBM)."""

from .hashing import (
    KV_HASH_SEED,
    NATIVE,
    block_hash,
    chain_hash,
    hash_blocks,
    xxh64,
    xxh64_py,
)
from .sequence import TokenBlock, TokenBlockSequence, split_tokens

__all__ = [
    "KV_HASH_SEED",
    "NATIVE",
    "TokenBlock",
    "TokenBlockSequence",
    "block_hash",
    "chain_hash",
    "hash_blocks",
    "split_tokens",
    "xxh64",
    "xxh64_py",
]
