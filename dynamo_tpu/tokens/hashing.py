"""Token-block hashing: the canonical block identity of the framework.

Reference parity: lib/tokens/src/lib.rs (Tokens/TokenBlock, salt/block/
sequence xxHash chained hashing; SequenceHash binds position via the parent
hash).  Block identity must be bit-identical across the KV router, the block
manager, and the engine -- it is centralized here and nowhere else.

Hot path is native (native/tokenhash.cpp via ctypes); a pure-Python XXH64
(same from-spec algorithm) is the fallback so the package works without the
compiled library.  Both are cross-checked in tests.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

KV_HASH_SEED = 1337  # reference: kv_router/indexer.rs:86-102 uses seed 1337

# ---------------------------------------------------------------------------
# Pure-Python XXH64 (from the public spec)
# ---------------------------------------------------------------------------

_P1 = 11400714785074694791
_P2 = 14029467366897019727
_P3 = 1609587929392839161
_P4 = 9650029242287828579
_P5 = 2870177450012600261
_M = (1 << 64) - 1


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M


def _round(acc: int, lane: int) -> int:
    return (_rotl((acc + lane * _P2) & _M, 31) * _P1) & _M


def _merge(h: int, acc: int) -> int:
    return ((h ^ _round(0, acc)) * _P1 + _P4) & _M


def xxh64_py(data: bytes, seed: int = 0) -> int:
    n = len(data)
    p = 0
    if n >= 32:
        a1 = (seed + _P1 + _P2) & _M
        a2 = (seed + _P2) & _M
        a3 = seed & _M
        a4 = (seed - _P1) & _M
        while p + 32 <= n:
            a1 = _round(a1, int.from_bytes(data[p : p + 8], "little"))
            a2 = _round(a2, int.from_bytes(data[p + 8 : p + 16], "little"))
            a3 = _round(a3, int.from_bytes(data[p + 16 : p + 24], "little"))
            a4 = _round(a4, int.from_bytes(data[p + 24 : p + 32], "little"))
            p += 32
        h = (_rotl(a1, 1) + _rotl(a2, 7) + _rotl(a3, 12) + _rotl(a4, 18)) & _M
        h = _merge(h, a1)
        h = _merge(h, a2)
        h = _merge(h, a3)
        h = _merge(h, a4)
    else:
        h = (seed + _P5) & _M

    h = (h + n) & _M
    while p + 8 <= n:
        h ^= _round(0, int.from_bytes(data[p : p + 8], "little"))
        h = (_rotl(h, 27) * _P1 + _P4) & _M
        p += 8
    if p + 4 <= n:
        h ^= (int.from_bytes(data[p : p + 4], "little") * _P1) & _M
        h = (_rotl(h, 23) * _P2 + _P3) & _M
        p += 4
    while p < n:
        h ^= (data[p] * _P5) & _M
        h = (_rotl(h, 11) * _P1) & _M
        p += 1

    h ^= h >> 33
    h = (h * _P2) & _M
    h ^= h >> 29
    h = (h * _P3) & _M
    h ^= h >> 32
    return h


# ---------------------------------------------------------------------------
# Native library loader
# ---------------------------------------------------------------------------

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
_NATIVE_PATHS = [
    os.environ.get("DYN_NATIVE_LIB", ""),
    os.path.join(_REPO_ROOT, "native", "build", "libdynnative.so"),
]


def ensure_native_built() -> bool:
    """Build native/build/libdynnative.so if a toolchain is available.

    Called explicitly by conftest/bench (never at import time).  Returns True
    if the library exists afterwards.
    """
    lib_path = os.path.join(_REPO_ROOT, "native", "build", "libdynnative.so")
    if os.path.exists(lib_path):
        return True
    import shutil
    import subprocess

    make = shutil.which("make")
    if make is None or not os.path.exists(os.path.join(_REPO_ROOT, "native")):
        return False
    try:
        subprocess.run(
            [make, "-C", os.path.join(_REPO_ROOT, "native")],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (subprocess.SubprocessError, OSError):
        return False
    global NATIVE
    NATIVE = _load_native()
    return os.path.exists(lib_path)


def _load_native() -> Optional[ctypes.CDLL]:
    for path in _NATIVE_PATHS:
        if path and os.path.exists(path):
            try:
                lib = ctypes.CDLL(path)
                lib.dyn_xxh64.restype = ctypes.c_uint64
                lib.dyn_xxh64.argtypes = [
                    ctypes.c_void_p,
                    ctypes.c_size_t,
                    ctypes.c_uint64,
                ]
                lib.dyn_hash_blocks.restype = None
                lib.dyn_hash_blocks.argtypes = [
                    ctypes.c_void_p,
                    ctypes.c_size_t,
                    ctypes.c_size_t,
                    ctypes.c_uint64,
                    ctypes.c_void_p,
                    ctypes.c_void_p,
                    ctypes.c_size_t,
                ]
                return lib
            except OSError:
                continue
    return None


NATIVE = _load_native()


def xxh64(data: bytes, seed: int = 0) -> int:
    if NATIVE is not None:
        return NATIVE.dyn_xxh64(data, len(data), seed)
    return xxh64_py(data, seed)


# ---------------------------------------------------------------------------
# Block / sequence hashing
# ---------------------------------------------------------------------------


def block_hash(tokens: Sequence[int], seed: int = KV_HASH_SEED) -> int:
    """Hash one complete token block (content identity, position-free)."""
    arr = np.asarray(tokens, dtype=np.int32)
    return xxh64(arr.tobytes(), seed)


def chain_hash(parent: int, block: int, seed: int = KV_HASH_SEED) -> int:
    """Combine a parent sequence hash with a block hash (position binding)."""
    buf = np.array([parent, block], dtype=np.uint64).tobytes()
    return xxh64(buf, seed)


def hash_blocks(
    tokens: Sequence[int], block_size: int, seed: int = KV_HASH_SEED
) -> Tuple[List[int], List[int]]:
    """Hash all *complete* blocks of ``tokens``.

    Returns ``(block_hashes, sequence_hashes)``; ``sequence_hashes[i]`` chains
    ``sequence_hashes[i-1]`` so equal values imply an identical token prefix.
    The first block's sequence hash equals its block hash.
    """
    arr = np.ascontiguousarray(np.asarray(tokens, dtype=np.int32))
    n_blocks = len(arr) // block_size
    if n_blocks == 0:
        return [], []
    if NATIVE is not None:
        bh = np.empty(n_blocks, dtype=np.uint64)
        sh = np.empty(n_blocks, dtype=np.uint64)
        NATIVE.dyn_hash_blocks(
            arr.ctypes.data,
            len(arr),
            block_size,
            seed,
            bh.ctypes.data,
            sh.ctypes.data,
            n_blocks,
        )
        return bh.tolist(), sh.tolist()

    bhs: List[int] = []
    shs: List[int] = []
    parent = 0
    for i in range(n_blocks):
        block = arr[i * block_size : (i + 1) * block_size]
        bh_i = xxh64(block.tobytes(), seed)
        sh_i = bh_i if i == 0 else chain_hash(parent, bh_i, seed)
        bhs.append(bh_i)
        shs.append(sh_i)
        parent = sh_i
    return bhs, shs
