"""Process supervisor: restart-on-death worker management.

Reference parity: the SDK's ``dynamo serve`` runs each service under a
circus watcher (components/planner local_connector.py drives circus
add/remove), so a crashed worker restarts without operator action.  The
TPU build supervises plain subprocesses with asyncio -- no daemon
dependency -- and exposes the same two capabilities the reference uses:

  * **watchers**: a named command spec with a target replica count;
    crashed processes restart with exponential backoff, and a process
    that flaps too fast is parked (fail loud, don't spin);
  * **scaling**: ``scale(name, n)`` adds/removes replicas -- the planner's
    LocalConnector can drive a Supervisor factory to scale real worker
    processes instead of in-process handles.

Use standalone, or through ``LocalConnector`` factories:

    sup = Supervisor()
    sup.add_watcher("decode", [sys.executable, "-m", "dynamo_tpu", "run",
                    "in=dyn", "out=jax", "--hub", hub, ...], replicas=1)
    await sup.start()
    ...
    await sup.scale("decode", 3)
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import signal
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger("dynamo.supervisor")

# a process that exits faster than this is counted as a flap
FLAP_WINDOW_S = 2.0
# consecutive flaps before the replica is parked (fail loud)
MAX_FLAPS = 5
BACKOFF_BASE_S = 0.2
BACKOFF_CAP_S = 10.0


@dataclass
class _Replica:
    proc: Optional[asyncio.subprocess.Process] = None
    task: Optional[asyncio.Task] = None
    flaps: int = 0
    parked: bool = False


@dataclass
class Watcher:
    name: str
    cmd: List[str]
    replicas: int
    env: Optional[Dict[str, str]] = None
    cwd: Optional[str] = None
    # SIGTERM first: workers install a drain handler (deregister from
    # discovery, finish in-flight, exit) and get stop_grace_s to use it
    # before SIGKILL -- scale-down and shutdown never drop requests that a
    # drain could have finished
    stop_signal: int = signal.SIGTERM
    stop_grace_s: float = 5.0
    restarts: int = 0  # observability: total restart count
    graceful_stops: int = 0  # exited within grace after stop_signal
    forced_kills: int = 0  # needed SIGKILL after the grace expired
    planner_scales: int = 0  # scale() calls marked planner_intent
    _procs: List[_Replica] = field(default_factory=list)


class Supervisor:
    """Asyncio process supervisor (see module docstring)."""

    def __init__(self) -> None:
        self.watchers: Dict[str, Watcher] = {}
        self._running = False

    def add_watcher(
        self,
        name: str,
        cmd: List[str],
        replicas: int = 1,
        env: Optional[Dict[str, str]] = None,
        cwd: Optional[str] = None,
        stop_grace_s: float = 5.0,
    ) -> Watcher:
        if name in self.watchers:
            raise ValueError(f"watcher {name!r} already exists")
        w = Watcher(name=name, cmd=list(cmd), replicas=replicas,
                    env=env, cwd=cwd, stop_grace_s=stop_grace_s)
        self.watchers[name] = w
        return w

    async def start(self) -> None:
        self._running = True
        for w in self.watchers.values():
            await self._reconcile(w)

    async def stop(self) -> None:
        self._running = False
        for w in self.watchers.values():
            await self._scale_down_to(w, 0)

    async def scale(
        self, name: str, replicas: int, *, planner_intent: bool = False
    ) -> None:
        """Set the target replica count.

        ``planner_intent=True`` marks the change as a deliberate
        controller decision rather than crash recovery: flap counters on
        surviving replicas reset, so the restart-backoff machinery --
        which exists to contain *crashing* processes -- never fights a
        scale decision the planner just made (a replica that flapped
        during an incident would otherwise start its next life with
        inherited backoff debt)."""
        w = self.watchers[name]
        w.replicas = max(0, replicas)
        if planner_intent:
            w.planner_scales += 1
            for r in w._procs:
                if not r.parked:
                    r.flaps = 0
        await self._reconcile(w)

    def replica_count(self, name: str) -> int:
        """Live (non-parked) replicas."""
        w = self.watchers[name]
        return sum(1 for r in w._procs if not r.parked)

    async def _reconcile(self, w: Watcher) -> None:
        # parked slots are dead weight: drop them so the target count is
        # measured against LIVE replicas -- this is also what re-arms a
        # parked watcher on scale() (the logged remedy)
        w._procs = [r for r in w._procs if not r.parked]
        while len(w._procs) < w.replicas:
            r = _Replica()
            w._procs.append(r)
            r.task = asyncio.create_task(
                self._run_replica(w, r), name=f"sup-{w.name}-{len(w._procs)}"
            )
        if len(w._procs) > w.replicas:
            await self._scale_down_to(w, w.replicas)

    async def _scale_down_to(self, w: Watcher, n: int) -> None:
        # LIFO: the youngest replica drains first (coldest cache)
        while len(w._procs) > n:
            r = w._procs.pop()
            if r.task is not None:
                r.task.cancel()
                with contextlib.suppress(asyncio.CancelledError, Exception):
                    await r.task
            await self._kill(w, r)

    async def _kill(self, w: Watcher, r: _Replica) -> None:
        proc = r.proc
        r.proc = None
        if proc is None or proc.returncode is not None:
            return
        with contextlib.suppress(ProcessLookupError):
            proc.send_signal(w.stop_signal)
        try:
            await asyncio.wait_for(proc.wait(), w.stop_grace_s)
            w.graceful_stops += 1
        except asyncio.TimeoutError:
            logger.warning(
                "watcher %s: replica ignored signal %d for %.1fs; killing",
                w.name, w.stop_signal, w.stop_grace_s,
            )
            w.forced_kills += 1
            with contextlib.suppress(ProcessLookupError):
                proc.kill()
            await proc.wait()

    async def _run_replica(self, w: Watcher, r: _Replica) -> None:
        """Spawn-watch-restart loop for one replica slot."""
        try:
            while self._running and not r.parked:
                started = time.monotonic()
                env = dict(os.environ)
                if w.env:
                    env.update(w.env)
                spawn = asyncio.ensure_future(
                    asyncio.create_subprocess_exec(
                        *w.cmd, env=env, cwd=w.cwd,
                        stdout=sys.stderr, stderr=sys.stderr,
                    )
                )
                try:
                    # shield: a cancel landing mid-fork must not orphan the
                    # just-spawned process -- the reaper below kills it when
                    # the (uncancelled) spawn future completes
                    r.proc = await asyncio.shield(spawn)
                except asyncio.CancelledError:
                    def _reap(f: asyncio.Future) -> None:
                        if not f.cancelled() and f.exception() is None:
                            with contextlib.suppress(ProcessLookupError):
                                f.result().kill()

                    spawn.add_done_callback(_reap)
                    raise
                except Exception as e:  # noqa: BLE001 - spawn failure
                    logger.error(
                        "watcher %s: spawn failed: %s", w.name, e
                    )
                    r.flaps += 1
                    r.proc = None
                else:
                    rc = await r.proc.wait()
                    if not self._running:
                        return
                    lived = time.monotonic() - started
                    logger.warning(
                        "watcher %s: process exited rc=%s after %.1fs",
                        w.name, rc, lived,
                    )
                    r.flaps = r.flaps + 1 if lived < FLAP_WINDOW_S else 0
                    w.restarts += 1
                if r.flaps >= MAX_FLAPS:
                    r.parked = True
                    logger.error(
                        "watcher %s: replica flapping (%d fast exits); "
                        "parked -- fix the command and scale to re-arm",
                        w.name, r.flaps,
                    )
                    return
                await asyncio.sleep(
                    min(BACKOFF_CAP_S, BACKOFF_BASE_S * (2 ** r.flaps))
                )
        except asyncio.CancelledError:
            raise
