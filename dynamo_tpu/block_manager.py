"""Block manager (KVBM v1): the G1 page pool with a sequence-hash reuse
registry.

Rebuild of the reference block pool (lib/llm/src/block_manager/pool.rs:
339-444 allocate/register/match_sequence_hashes with reuse-priority
eviction; block/registry.rs sequence-hash registry), reshaped for the JAX
engine's paged KV layout: a "block" is ``pages_per_block`` consecutive KV
pages holding exactly one router-visible token block, identified by that
block's chained sequence hash.

States of a page:
  * **free** -- on the free list, contents dead.
  * **owned** -- allocated to one sequence (tail / growth pages), unshared.
  * **registered-active** -- part of a completed block some sequence(s)
    reference (refcount > 0).  Shared read-only.
  * **registered-inactive** -- completed block nobody references.  Contents
    still valid: a later request with the same prefix *reuses* it
    (``match`` + ``acquire``).  Reclaimed LRU-last when the free list runs
    dry -- that is the reuse-priority eviction.

Eviction publishes a ``removed`` KV event through ``event_sink`` so the
router's index never over-states residency; registration publishes
``stored``.  (The engine wires ``event_sink`` to its KvEventPublisher.)

G2 (host RAM) / G3 (disk) offload tiers compose on top of this module: the
``on_evict`` hook fires with the block *before* its pages return to the
free list (still under the pool lock, so no other thread can reuse the
pages until the hook's device read is dispatched); the engine wires it to
``offload.KVOffloadEngine`` so the snapshot's blocking materialize happens
on the dedicated offload thread, never here.
"""

from __future__ import annotations

import collections
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple


class OutOfPages(RuntimeError):
    pass


@dataclass
class RegisteredBlock:
    sequence_hash: int
    pages: Tuple[int, ...]
    refs: int = 1
    # router-facing identity, carried into stored events
    block_hash: int = 0
    parent_sequence_hash: int = 0
    position: int = 0


class PagePool:
    """Page allocator + block reuse registry over page ids 1..num_pages-1
    (page 0 is the trash page for inactive batch lanes)."""

    def __init__(
        self,
        num_pages: int,
        pages_per_block: int = 1,
        event_sink: Optional[Callable[[dict], None]] = None,
    ) -> None:
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        if pages_per_block < 1:
            raise ValueError("pages_per_block must be >= 1")
        self.num_pages = num_pages
        self.pages_per_block = pages_per_block
        self.event_sink = event_sink
        # offload hook: called with the RegisteredBlock *before* its pages
        # return to the free list, so the owner can snapshot the contents
        # (G1 -> G2 demotion; engine wires this to a device-slice dispatch
        # whose device ordering precedes any page reuse)
        self.on_evict: Optional[Callable[[RegisteredBlock], None]] = None
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._registered: Dict[int, RegisteredBlock] = {}
        # LRU over refs==0 registered blocks (insertion-ordered)
        self._inactive: "collections.OrderedDict[int, None]" = (
            collections.OrderedDict()
        )
        self.prefix_hits = 0
        self.prefix_lookups = 0
        # reuse-priority evictions performed (each one is an offload
        # opportunity: the tier-occupancy story starts here)
        self.evictions = 0
        # alloc/free/registry mutations are locked: the scheduler runs on
        # the tick-loop thread while JaxEngine._prefill_export (disagg
        # prefill-worker path) allocates scratch pages on the engine
        # executor thread
        self._lock = threading.RLock()

    # -- capacity ------------------------------------------------------------

    @property
    def free_pages(self) -> int:
        """Immediately allocatable pages: free list + evictable inactive."""
        return len(self._free) + len(self._inactive) * self.pages_per_block

    @property
    def used_pages(self) -> int:
        return (self.num_pages - 1) - self.free_pages

    @property
    def resident_pages(self) -> int:
        """Pages whose contents are live or reusable (excludes only free)."""
        return (self.num_pages - 1) - len(self._free)

    # -- allocation ----------------------------------------------------------

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` pages: free list first, then LRU eviction of inactive
        registered blocks (reuse-priority: most recently released last)."""
        if n <= 0:
            return []
        with self._lock:
            while len(self._free) < n and self._inactive:
                self._evict_one()
            if len(self._free) < n:
                raise OutOfPages(
                    f"requested {n} pages, {len(self._free)} free"
                )
            out = self._free[-n:][::-1]
            del self._free[len(self._free) - n:]
            return out

    def free(self, pages: Sequence[int]) -> None:
        """Return *owned* (unregistered) pages to the free list."""
        with self._lock:
            self._free.extend(pages)

    def _evict_one(self) -> None:
        seq_hash, _ = self._inactive.popitem(last=False)
        blk = self._registered.pop(seq_hash)
        self.evictions += 1
        if self.on_evict is not None:
            try:
                self.on_evict(blk)
            except Exception:  # offload is best-effort; eviction is not
                import logging

                logging.getLogger("dynamo.offload").exception(
                    "on_evict hook failed for block %x", seq_hash
                )
        self._free.extend(blk.pages)
        if self.event_sink is not None:
            self.event_sink(
                {"type": "removed", "sequence_hashes": [seq_hash]}
            )

    # -- registry ------------------------------------------------------------

    def match(self, sequence_hashes: Sequence[int]) -> List[RegisteredBlock]:
        """Longest resident prefix of ``sequence_hashes`` (reference
        pool.rs match_sequence_hashes).  Does not take references."""
        with self._lock:
            out: List[RegisteredBlock] = []
            for h in sequence_hashes:
                blk = self._registered.get(h)
                if blk is None:
                    break
                out.append(blk)
            self.prefix_lookups += len(sequence_hashes)
            self.prefix_hits += len(out)
            return out

    def acquire(self, sequence_hash: int) -> Optional[RegisteredBlock]:
        """Take a reference on a resident block (revives inactive)."""
        with self._lock:
            blk = self._registered.get(sequence_hash)
            if blk is None:
                return None
            if blk.refs == 0:
                self._inactive.pop(sequence_hash, None)
            blk.refs += 1
            return blk

    def register(
        self,
        sequence_hash: int,
        pages: Sequence[int],
        *,
        block_hash: int = 0,
        parent_sequence_hash: int = 0,
        position: int = 0,
    ) -> bool:
        """Register a completed block's pages under its sequence hash; the
        registrant holds one reference.  Returns False (caller keeps plain
        ownership of the pages) when the hash is already registered --
        duplicate content from concurrent identical prefixes."""
        if len(pages) != self.pages_per_block:
            raise ValueError(
                f"block needs {self.pages_per_block} pages, got {len(pages)}"
            )
        with self._lock:
            if sequence_hash in self._registered:
                return False
            self._registered[sequence_hash] = RegisteredBlock(
                sequence_hash=sequence_hash,
                pages=tuple(pages),
                refs=1,
                block_hash=block_hash,
                parent_sequence_hash=parent_sequence_hash,
                position=position,
            )
        if self.event_sink is not None:
            self.event_sink(
                {
                    "type": "stored",
                    "blocks": [
                        {
                            "block_hash": block_hash,
                            "sequence_hash": sequence_hash,
                            "parent_sequence_hash": parent_sequence_hash,
                            "position": position,
                        }
                    ],
                }
            )
        return True

    def release(self, sequence_hash: int) -> None:
        """Drop one reference; at zero the block turns inactive (reusable,
        evictable LRU)."""
        with self._lock:
            blk = self._registered.get(sequence_hash)
            if blk is None:
                return
            if blk.refs <= 0:
                raise RuntimeError(
                    f"negative refs for block {sequence_hash:x}"
                )
            blk.refs -= 1
            if blk.refs == 0:
                self._inactive[sequence_hash] = None
                self._inactive.move_to_end(sequence_hash)

    def is_registered(self, sequence_hash: int) -> bool:
        return sequence_hash in self._registered

    @property
    def num_registered(self) -> int:
        return len(self._registered)

    @property
    def num_inactive(self) -> int:
        return len(self._inactive)

    @property
    def hit_rate(self) -> float:
        return (
            self.prefix_hits / self.prefix_lookups if self.prefix_lookups else 0.0
        )
