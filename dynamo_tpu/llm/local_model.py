"""Model path resolution: local directory or Hugging Face repo id.

Reference lib/llm/src/local_model.rs:27 + hub.rs: ``--model-path`` accepts
either a local directory (used as-is) or a HF repo id, which is resolved by
downloading the snapshot into the local HF cache.  Same contract here via
``huggingface_hub.snapshot_download`` (honours HF_HOME/HF_HUB_OFFLINE and
reuses cached snapshots, so airgapped deployments that pre-seed the cache
never touch the network).
"""

from __future__ import annotations

import logging
import os
import re

logger = logging.getLogger("dynamo.local_model")

# org/name with the HF id charset; a path that exists locally always wins
_REPO_ID_RE = re.compile(r"^[\w.-]+/[\w.-]+$")

# weights + tokenizer + config: everything the engine/tokenizer loaders read
_SNAPSHOT_PATTERNS = [
    "*.safetensors",
    "*.json",
    "tokenizer.model",
    "*.txt",
]


def resolve_model_path(model_path: str) -> str:
    """Return a local directory for ``model_path``.

    A path that exists on disk (a model directory, or a single ``.gguf``
    file) is returned unchanged; otherwise a string shaped like
    ``org/repo`` is resolved through the HF hub (download or cache hit).
    Anything else fails with a clear error."""
    if os.path.isdir(model_path):
        return model_path
    if os.path.isfile(model_path):
        # existence wins over the repo-id shape: a relative
        # "models/weights.gguf" must never trigger a hub download.  Only
        # .gguf is a meaningful single-file model; anything else fails
        # here with a clear message instead of deep in a loader.
        if model_path.endswith(".gguf"):
            return model_path
        raise SystemExit(
            f"--model-path {model_path!r} is a file but not a .gguf; pass "
            f"the model directory instead"
        )
    if not _REPO_ID_RE.match(model_path):
        raise SystemExit(
            f"--model-path {model_path!r} is neither a local path nor "
            f"an org/repo Hugging Face id"
        )
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:  # pragma: no cover - baked into this image
        raise SystemExit(
            f"--model-path {model_path!r} looks like a HF repo id but "
            f"huggingface_hub is not installed: {e}"
        )
    logger.info("resolving %s via the Hugging Face hub ...", model_path)
    try:
        local = snapshot_download(
            model_path, allow_patterns=_SNAPSHOT_PATTERNS
        )
    except Exception as e:  # noqa: BLE001 - network/auth/id errors
        raise SystemExit(
            f"could not resolve {model_path!r} from the Hugging Face hub "
            f"({e.__class__.__name__}: {e}); pass a local directory, "
            f"pre-seed the HF cache, or set HF_HUB_OFFLINE=1 with a cached "
            f"snapshot"
        )
    logger.info("resolved %s -> %s", model_path, local)
    return local
