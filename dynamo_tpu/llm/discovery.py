"""ModelWatcher: discovery-driven model registration for the HTTP frontend.

Reference parity: lib/llm/src/discovery/watcher.rs:34-130 (watch the etcd
``models/`` prefix), handle_put :162-250 (download the MDC, build the
per-model pipeline -- Backend type means preprocessor + detokenizer +
PushRouter to the worker endpoint), handle_delete (remove the model when its
last instance is gone).

The watcher owns nothing about HTTP: it mutates a
:class:`~dynamo_tpu.http.service.ModelManager`, which the HttpService reads
per request -- models appear and disappear without frontend restarts.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from typing import Callable, Dict, Optional, Set

from ..http.service import ModelManager
from ..runtime.component import FailoverPolicy, PushRouter, RouterMode
from ..runtime.pipeline import link
from .backend import Backend
from .model_card import MODEL_ROOT, ModelDeploymentCard, ModelEntry
from .preprocessor import OpenAIPreprocessor

logger = logging.getLogger("dynamo.discovery")


class ModelWatcher:
    """Watch ``models/`` and keep a ModelManager in sync with the cluster."""

    def __init__(
        self,
        runtime,
        manager: ModelManager,
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
        engine_factory: Optional[Callable] = None,
    ) -> None:
        """``engine_factory(entry, card, client, router)`` (sync or async)
        may override pipeline construction (e.g. to insert a KvPushRouter);
        default is preprocessor -> backend -> PushRouter(client)."""
        self.runtime = runtime
        self.manager = manager
        self.router_mode = router_mode
        self.engine_factory = engine_factory
        # model slug -> live registration keys (instances of that model)
        self._instances: Dict[str, Set[str]] = {}
        # slug -> clients owned by that model's pipelines (generate endpoint
        # plus, when the worker embeds, its embed endpoint)
        self._clients: Dict[str, list] = {}
        # per-model async teardowns (e.g. a KvRouter chooser's stop())
        self._cleanups: Dict[str, object] = {}
        self._watch = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self._watch = await self.runtime.hub.watch_prefix(f"{MODEL_ROOT}/")
        for key, value in self._watch.snapshot:
            try:
                await self._handle_put(key, value)
            except Exception:
                # one bad registration must not block frontend startup; the
                # same isolation _loop applies per event
                logger.exception("model watcher failed on snapshot %s", key)
        self._task = asyncio.create_task(self._loop(), name="model-watcher")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._task
            self._task = None
        if self._watch is not None:
            await self._watch.close()
        for cleanup in self._cleanups.values():
            with contextlib.suppress(Exception):
                await cleanup()
        self._cleanups.clear()
        for clients in self._clients.values():
            for client in clients:
                with contextlib.suppress(Exception):
                    await client.close()
        self._clients.clear()

    async def _loop(self) -> None:
        try:
            async for ev in self._watch:
                try:
                    if ev.type == "put":
                        await self._handle_put(ev.key, ev.value)
                    elif ev.type == "delete":
                        await self._handle_delete(ev.key)
                except Exception:
                    logger.exception(
                        "model watcher failed on %s %s", ev.type, ev.key
                    )
        except ConnectionError:
            # hub gone: fail loudly -- drop every model so the frontend 404s
            # instead of routing from a frozen view to possibly-dead workers
            logger.critical(
                "hub connection lost; removing all %d models from the frontend",
                len(self._instances),
            )
            for m in list(self.manager.list_models()):
                self.manager.remove_model(m["id"])
            self._instances.clear()
            raise

    # -- put/delete (reference watcher.rs:162-250) ---------------------------

    @staticmethod
    def _slug_of(key: str) -> str:
        # models/{slug}/{lease_hex}
        parts = key.split("/")
        return parts[1] if len(parts) >= 3 else ""

    async def _handle_put(self, key: str, value: bytes) -> None:
        slug = self._slug_of(key)
        if not slug:
            return
        known = self._instances.setdefault(slug, set())
        if key in known:
            return
        known.add(key)
        if len(known) > 1:
            return  # pipeline already built; new instance joins via discovery
        try:
            entry = ModelEntry.from_json(value)
            card = await ModelDeploymentCard.download(self.runtime.hub, entry.name)
            if card is None:
                logger.error(
                    "model %s registered but no MDC published", entry.name
                )
                known.discard(key)
                return
            endpoint = (
                self.runtime.namespace(entry.namespace)
                .component(entry.component)
                .endpoint(entry.endpoint)
            )
            client = await endpoint.client()
            self._clients[slug] = [client]
            # the frontend's workers are fungible replicas: request-level
            # failover is safe (a worker lost before its first response
            # item redispatches to a survivor) and on by default
            router = PushRouter(
                client, mode=self.router_mode,
                failover=FailoverPolicy.from_env(),
            )
            if self.engine_factory is not None:
                engine = self.engine_factory(entry, card, client, router)
                if hasattr(engine, "__await__"):
                    engine = await engine
                # a factory may return (engine, async_cleanup) so auxiliary
                # resources (KV chooser tasks/subscriptions) die with the model
                if isinstance(engine, tuple):
                    engine, cleanup = engine
                    self._cleanups[slug] = cleanup
            else:
                tokenizer = card.tokenizer()
                engine = link(
                    OpenAIPreprocessor(entry.name, tokenizer),
                    Backend(tokenizer),
                    router,
                )
            embed_engine = None
            if entry.embed_endpoint:
                from .embedding import EmbeddingEngine, router_embedder

                embed_client = await (
                    self.runtime.namespace(entry.namespace)
                    .component(entry.component)
                    .endpoint(entry.embed_endpoint)
                    .client()
                )
                self._clients[slug].append(embed_client)
                embed_engine = EmbeddingEngine(
                    router_embedder(
                        PushRouter(embed_client, mode=self.router_mode)
                    ),
                    tokenizer=card.tokenizer(),
                    max_input_tokens=card.context_length,
                )
        except Exception:
            # transient failure must not wedge the model: un-claim the key so
            # a later put (this instance's or another's) rebuilds from scratch
            known.discard(key)
            cleanup = self._cleanups.pop(slug, None)
            if cleanup is not None:  # factory resources registered pre-failure
                with contextlib.suppress(Exception):
                    await cleanup()
            for client in self._clients.pop(slug, []):
                with contextlib.suppress(Exception):
                    await client.close()
            raise
        self.manager.add_chat_model(entry.name, engine)
        self.manager.add_completion_model(entry.name, engine)
        if embed_engine is not None:
            self.manager.add_embedding_model(entry.name, embed_engine)
        logger.info("model %s added (endpoint %s)", entry.name, endpoint.path)

    async def _handle_delete(self, key: str) -> None:
        slug = self._slug_of(key)
        known = self._instances.get(slug)
        if known is None:
            return
        known.discard(key)
        if known:
            return  # other instances still serve this model
        del self._instances[slug]
        cleanup = self._cleanups.pop(slug, None)
        if cleanup is not None:
            with contextlib.suppress(Exception):
                await cleanup()
        for client in self._clients.pop(slug, []):
            with contextlib.suppress(Exception):
                await client.close()
        # find the display name: manager keys are model names, the key holds
        # the slug; names map 1:1 through slugify
        from .model_card import slugify

        for m in list(self.manager.list_models()):
            if slugify(m["id"]) == slug:
                self.manager.remove_model(m["id"])
                logger.info("model %s removed (last instance gone)", m["id"])
