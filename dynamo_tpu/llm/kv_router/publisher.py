"""Worker-side publishers: KV events to the hub + load-metrics endpoint.

Rebuild of the reference publisher (lib/llm/src/kv_router/publisher.rs:
50-99 KvEventPublisher -> NATS ``{ns}.events.kv_events``; :463-520
WorkerMetricsPublisher serving ``ForwardPassMetrics`` on a ``load_metrics``
endpoint).  No ZMQ leg: the engine is first-party, so its ``kv_event_sink``
hook feeds the publisher directly in-process.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, AsyncIterator, Callable, Dict, Optional

from ...runtime.component import Component, Instance, Namespace
from ...runtime.engine import Annotated, Context, EngineFn, ResponseStream

logger = logging.getLogger("dynamo.kv_router")

KV_EVENT_TOPIC = "kv_events"
LOAD_METRICS_ENDPOINT = "load_metrics"


class KvEventPublisher:
    """Forwards engine KV events to the hub event plane.

    Wire shape on ``{ns}.events.kv_events``::

        {"worker_id": <instance id>, "event": {"type": "stored"|...}}

    Attach with ``publisher.hook(engine)`` -- it installs itself as the
    engine's ``kv_event_sink``.  Events are queued and drained by a
    background task so the engine's hot loop never blocks on the hub.
    """

    def __init__(self, namespace: Namespace, worker_id: int) -> None:
        self.namespace = namespace
        self.worker_id = worker_id
        self._queue: "asyncio.Queue[Dict[str, Any]]" = asyncio.Queue(maxsize=4096)
        self._task: Optional[asyncio.Task] = None

    def hook(self, engine: Any) -> None:
        engine.kv_event_sink = self.emit
        if self._task is None:
            self._task = asyncio.create_task(self._pump(), name="kv-event-pub")

    def emit(self, event: Dict[str, Any]) -> None:
        try:
            self._queue.put_nowait(event)
        except asyncio.QueueFull:
            if event.get("type") == "stored":
                # dropping a stored event only under-states this worker's
                # cache -- safe (the router just misses a hit opportunity)
                logger.warning("kv event queue full; dropping stored event")
                return
            # dropping a removed/cleared event would permanently over-state
            # the index; collapse the backlog into one full resync signal
            # (the router forgets this worker and rebuilds from later events)
            logger.warning(
                "kv event queue full on %s; collapsing to cleared",
                event.get("type"),
            )
            while not self._queue.empty():
                try:
                    self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
            self._queue.put_nowait({"type": "cleared"})

    async def _pump(self) -> None:
        while True:
            event = await self._queue.get()
            try:
                await self.namespace.publish(
                    KV_EVENT_TOPIC,
                    {"worker_id": self.worker_id, "event": event},
                )
            except Exception:
                logger.exception("kv event publish failed")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.debug("publisher loop raised during close", exc_info=True)
            self._task = None


class KvHoldingsPublisher:
    """Forwards offload-tier holdings deltas to the event plane.

    Rides the same ``{ns}.events.kv_events`` subject as the G1 publisher
    -- the indexer dispatches on ``event["type"]`` (``holdings`` /
    ``holdings_cleared``), so no extra subscription is needed router-side.
    Attach with ``publisher.hook(engine)``: it installs itself as the
    engine's ``kv_holdings_sink`` (fed from the offload thread via the
    engine's loop hop).

    Overflow policy differs from the G1 publisher: a dropped ``tier=None``
    row would leave the cluster-global index advertising a tier the worker
    already dropped (a fetch that can only miss), so a full queue
    collapses the backlog into one ``holdings_cleared`` resync -- the
    index forgets this worker's tiers until fresh deltas rebuild them.
    """

    def __init__(self, namespace: Namespace, worker_id: int) -> None:
        self.namespace = namespace
        self.worker_id = worker_id
        self._queue: "asyncio.Queue[Dict[str, Any]]" = asyncio.Queue(maxsize=4096)
        self._task: Optional[asyncio.Task] = None

    def hook(self, engine: Any) -> None:
        engine.kv_holdings_sink = self.emit
        if self._task is None:
            self._task = asyncio.create_task(
                self._pump(), name="kv-holdings-pub"
            )

    def emit(self, event: Dict[str, Any]) -> None:
        try:
            self._queue.put_nowait(event)
        except asyncio.QueueFull:
            logger.warning(
                "kv holdings queue full; collapsing to holdings_cleared"
            )
            while not self._queue.empty():
                try:
                    self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
            self._queue.put_nowait({"type": "holdings_cleared"})

    async def _pump(self) -> None:
        while True:
            event = await self._queue.get()
            try:
                await self.namespace.publish(
                    KV_EVENT_TOPIC,
                    {"worker_id": self.worker_id, "event": event},
                )
            except Exception:
                logger.exception("kv holdings publish failed")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.debug("publisher loop raised during close", exc_info=True)
            self._task = None


class WorkerMetricsPublisher:
    """Serves the engine's ``ForwardPassMetrics`` on a ``load_metrics``
    endpoint (single-item stream per request)."""

    def __init__(self, metrics_fn: Callable[[], Any]) -> None:
        self._metrics_fn = metrics_fn
        self.instance: Optional[Instance] = None

    async def attach(self, component: Component) -> Instance:
        ep = component.endpoint(LOAD_METRICS_ENDPOINT)
        self.instance = await ep.serve(EngineFn(self._generate))
        return self.instance

    async def _generate(self, request: Context[Any]) -> AsyncIterator[Annotated]:
        metrics = self._metrics_fn()
        payload = metrics.to_dict() if hasattr(metrics, "to_dict") else dict(metrics)

        async def one() -> AsyncIterator[Annotated]:
            yield Annotated.from_data(payload)

        return ResponseStream(request.ctx, one())
