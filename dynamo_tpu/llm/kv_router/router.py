"""KvRouter + KvPushRouter: KV-overlap-aware request dispatch.

Rebuild of the reference (lib/llm/src/kv_router.rs:104-255): the KvRouter
owns the indexer (fed by ``{ns}.events.kv_events`` subscriptions), the
metrics aggregator, and the scheduler; ``find_best_match(tokens)`` returns
the worker with the best cost.  KvPushRouter wraps a PushRouter: pick the
best worker, stamp ``estimated_prefix_hit_num_blocks`` into the request,
and dispatch with ``direct()``.

Worker death is handled on both feeds: the aggregator drops workers whose
``load_metrics`` instance disappeared (lease loss), and the indexer drops
their whole subtree (reference indexer.rs:382 semantics).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
from typing import Any, Dict, Optional, Sequence, Tuple

from ...protocols.common import PreprocessedRequest
from ...runtime import metrics as rtm
from ...runtime.component import (
    Component,
    InstanceNotFoundError,
    Namespace,
    PushRouter,
)
from ...runtime.transports.request_plane import WorkerLostError
from ...runtime.engine import Annotated, Context, ResponseStream
from ...tokens.hashing import hash_blocks
from .indexer import KvIndexer, KvIndexerSharded, OverlapScores
from .metrics_aggregator import KvMetricsAggregator
from .scheduler import DefaultWorkerSelector, KvRouterConfig, KvScheduler

logger = logging.getLogger("dynamo.kv_router")

KV_EVENT_SUBJECT = "kv_events"  # rides {ns}.events.kv_events
KV_HIT_RATE_SUBJECT = "kv-hit-rate"  # reference kv_router.rs:44


class KvRouter:
    """Chooses a worker; does not dispatch (reference kv_router.rs:104)."""

    def __init__(
        self,
        namespace: Namespace,
        component: Component,
        block_size: int = 16,
        config: Optional[KvRouterConfig] = None,
        scrape_interval_s: float = 0.2,
        index_shards: int = 1,
        quarantine=None,
    ) -> None:
        self.namespace = namespace
        self.component = component
        self.block_size = block_size
        if index_shards < 1:
            raise ValueError("index_shards must be >= 1")
        # index_shards > 1 switches to the worker-sharded index (reference
        # KvIndexerSharded) for large fleets
        if index_shards > 1:
            self.indexer = KvIndexerSharded(
                block_size=block_size, num_shards=index_shards
            )
        else:
            self.indexer = KvIndexer(block_size=block_size)
        # quarantine: FleetObservatory.quarantine_source() -- stragglers
        # flagged by the fleet plane stop winning selections until their
        # series recovers (scheduler.py weight-zeroing)
        self.scheduler = KvScheduler(
            block_size, DefaultWorkerSelector(config, quarantine=quarantine)
        )
        # one shared ProcessedEndpoints: the aggregator writes scrapes into
        # the same snapshot the scheduler reads/predictively bumps
        self.aggregator = KvMetricsAggregator(
            component,
            interval_s=scrape_interval_s,
            endpoints=self.scheduler.workers,
            on_remove=self._on_worker_removed,
        )
        self._sub = None
        self._sub_task: Optional[asyncio.Task] = None
        self._publish_tasks: set = set()

    async def start(self) -> None:
        self._sub = await self.namespace.subscribe(KV_EVENT_SUBJECT)
        self._sub_task = asyncio.create_task(
            self._consume_events(), name="kv-router-events"
        )
        # per-selection hit-rate telemetry -> {ns}.events.kv-hit-rate
        # (reference scheduler.rs:104); consumed by the metrics component
        self.scheduler.on_hit_rate = self._publish_hit_rate
        await self.aggregator.start()

    async def stop(self) -> None:
        if self._sub_task is not None:
            self._sub_task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._sub_task
            self._sub_task = None
        if self._sub is not None:
            await self._sub.close()
        await self.aggregator.stop()
        # release the sharded index's matching pool (flat index: no-op)
        close = getattr(self.indexer, "close", None)
        if close is not None:
            close()

    def _publish_hit_rate(self, ev) -> None:
        payload = {
            "worker_id": ev.worker_id,
            "isl_blocks": ev.isl_blocks,
            "overlap_blocks": ev.overlap_blocks,
        }

        async def _send() -> None:
            try:
                await self.namespace.publish(KV_HIT_RATE_SUBJECT, payload)
            except Exception:
                logger.debug("kv-hit-rate publish failed", exc_info=True)

        # hold a strong reference until done: a bare ensure_future() task
        # can be garbage-collected mid-await, silently dropping the event
        task = asyncio.ensure_future(_send())
        self._publish_tasks.add(task)
        task.add_done_callback(self._publish_tasks.discard)

    def _on_worker_removed(self, worker_id: int) -> None:
        # the aggregator already dropped it from the shared endpoint
        # snapshot; the index subtree is ours to clean up
        logger.info("worker %x removed; dropping its KV index entries", worker_id)
        self.indexer.remove_worker(worker_id)

    async def _consume_events(self) -> None:
        assert self._sub is not None
        async for _subject, payload in self._sub:
            try:
                msg = json.loads(payload)
                self.indexer.apply_event(int(msg["worker_id"]), msg["event"])
            except Exception:
                logger.exception("bad kv event payload")

    # -- selection -----------------------------------------------------------

    async def find_best_match(self, tokens: Sequence[int]) -> Tuple[int, int]:
        """Returns (worker_id, overlap_blocks) (reference kv_router.rs:
        176-196)."""
        worker_id, overlap, _donor = await self.find_best_match_with_donor(
            tokens
        )
        return worker_id, overlap

    async def find_best_match_with_donor(
        self, tokens: Sequence[int]
    ) -> Tuple[int, int, Optional[Tuple[int, int]]]:
        """Best-cost worker plus the best prefix *donor* when they differ.

        The cost function may send a request to a lightly-loaded worker even
        though another worker holds a longer cached prefix; that other
        worker is the onboarding donor (G4 cross-worker block import,
        reference block_manager.rs:119-146).  Returns ``(worker_id,
        overlap_blocks, donor)`` with ``donor = (instance, blocks)`` or
        None when nobody beats the chosen worker's own cache."""
        _, seq_hashes = hash_blocks(tokens, self.block_size)
        overlap = self.indexer.find_matches(seq_hashes)
        worker_id = self.scheduler.schedule(overlap, len(tokens))
        own = overlap.scores.get(worker_id, 0)
        donor: Optional[Tuple[int, int]] = None
        for w, blocks in overlap.scores.items():
            if w != worker_id and blocks > own and (
                donor is None or blocks > donor[1]
            ):
                donor = (w, blocks)
        return worker_id, own, donor


class KvPushRouter:
    """PushRouter wrapper: best-match then ``direct()`` (reference
    kv_router.rs:220-255)."""

    def __init__(self, inner: PushRouter, chooser: KvRouter) -> None:
        self.inner = inner
        self.chooser = chooser
        # routing decisions by cause: kv (best-match direct), kv_donor
        # (best-match plus a cross-worker onboarding donor), and the two
        # fallbacks -- the series smarter-routing work tunes against
        self._decisions = rtm.default_registry().counter(
            "dynamo_kv_router_decisions",
            "KV-router dispatch decisions by cause",
            ["cause"],
        )

    async def generate(self, request: Context[Any]) -> ResponseStream[Annotated]:
        data = request.data
        if isinstance(data, PreprocessedRequest):
            token_ids = data.token_ids
        else:
            token_ids = list((data or {}).get("token_ids") or [])
        def stamp(overlap_blocks: int) -> Context[Any]:
            if isinstance(data, PreprocessedRequest):
                data.estimated_prefix_hit_num_blocks = overlap_blocks
                return request
            return request.replace(
                dict(data or {}, estimated_prefix_hit_num_blocks=overlap_blocks)
            )

        try:
            (
                instance_id,
                overlap,
                donor,
            ) = await self.chooser.find_best_match_with_donor(token_ids)
        except Exception:
            # no metrics yet / no workers known to the scheduler: degrade to
            # plain load balancing over the live instances rather than failing
            logger.debug("kv selection failed; falling back", exc_info=True)
            self._decisions.labels("fallback_no_selection").inc()
            return await self.inner.generate(request)
        if donor is not None:
            # another worker holds a longer prefix: tell the chosen worker
            # where to import it from (llm/prefix_onboard.py consumes this)
            from ..prefix_onboard import DONOR_META_KEY

            request.metadata[DONOR_META_KEY] = {
                "instance": donor[0],
                "blocks": donor[1],
            }
        try:
            stream = await self.inner.direct(stamp(overlap), instance_id)
            self._decisions.labels(
                "kv_donor" if donor is not None else "kv"
            ).inc()
            return stream
        except (InstanceNotFoundError, ConnectionRefusedError, WorkerLostError):
            # retryable dispatch failures are exactly those where the
            # request provably never started: a stale selection (instance
            # gone from the live set), a refused connect (the worker died
            # before the lease expired), or a prologue-stage loss (the
            # worker drained its subject / the connection dropped before
            # the handler acked).  Anything later must propagate --
            # re-dispatching after the worker may have started executing
            # would run the request twice.  Clear the overlap estimate: it
            # described the dead worker's cache, not whoever the fallback
            # picks.
            logger.debug(
                "selected instance %x vanished; falling back", instance_id
            )
            self._decisions.labels("fallback_dead_instance").inc()
            return await self.inner.generate(stamp(0))
