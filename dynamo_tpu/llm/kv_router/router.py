"""KvRouter + KvPushRouter: KV-overlap-aware request dispatch.

Rebuild of the reference (lib/llm/src/kv_router.rs:104-255): the KvRouter
owns the indexer (fed by ``{ns}.events.kv_events`` subscriptions), the
metrics aggregator, and the scheduler; ``find_best_match(tokens)`` returns
the worker with the best cost.  KvPushRouter wraps a PushRouter: pick the
best worker, stamp ``estimated_prefix_hit_num_blocks`` into the request,
and dispatch with ``direct()``.

Worker death is handled on both feeds: the aggregator drops workers whose
``load_metrics`` instance disappeared (lease loss), and the indexer drops
their whole subtree (reference indexer.rs:382 semantics).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import time
from typing import Any, Dict, Optional, Sequence, Tuple

from ...protocols.common import PreprocessedRequest
from ...runtime import metrics as rtm
from ...runtime import tracing
from ...runtime.component import (
    Component,
    InstanceNotFoundError,
    Namespace,
    PushRouter,
)
from ...runtime.transports.request_plane import WorkerLostError
from ...runtime.engine import Annotated, Context, ResponseStream
from ...tokens.hashing import hash_blocks
from .indexer import (
    KvIndexer,
    KvIndexerSharded,
    OverlapScores,
    REMOTE_SOURCE_ID,
)
from .metrics_aggregator import KvMetricsAggregator
from .scheduler import DefaultWorkerSelector, KvRouterConfig, KvScheduler

logger = logging.getLogger("dynamo.kv_router")

KV_EVENT_SUBJECT = "kv_events"  # rides {ns}.events.kv_events
KV_HIT_RATE_SUBJECT = "kv-hit-rate"  # reference kv_router.rs:44


class KvRouter:
    """Chooses a worker; does not dispatch (reference kv_router.rs:104)."""

    def __init__(
        self,
        namespace: Namespace,
        component: Component,
        block_size: int = 16,
        config: Optional[KvRouterConfig] = None,
        scrape_interval_s: float = 0.2,
        index_shards: int = 1,
        quarantine=None,
    ) -> None:
        self.namespace = namespace
        self.component = component
        self.block_size = block_size
        if index_shards < 1:
            raise ValueError("index_shards must be >= 1")
        # index_shards > 1 switches to the worker-sharded index (reference
        # KvIndexerSharded) for large fleets
        if index_shards > 1:
            self.indexer = KvIndexerSharded(
                block_size=block_size, num_shards=index_shards
            )
        else:
            self.indexer = KvIndexer(block_size=block_size)
        # quarantine: FleetObservatory.quarantine_source() -- stragglers
        # flagged by the fleet plane stop winning selections until their
        # series recovers (scheduler.py weight-zeroing); kept here too so
        # donor selection never nominates a quarantined worker as a source
        self._quarantine = quarantine
        self.scheduler = KvScheduler(
            block_size, DefaultWorkerSelector(config, quarantine=quarantine)
        )
        # one shared ProcessedEndpoints: the aggregator writes scrapes into
        # the same snapshot the scheduler reads/predictively bumps
        self.aggregator = KvMetricsAggregator(
            component,
            interval_s=scrape_interval_s,
            endpoints=self.scheduler.workers,
            on_remove=self._on_worker_removed,
        )
        self._sub = None
        self._sub_task: Optional[asyncio.Task] = None
        self._publish_tasks: set = set()

    async def start(self) -> None:
        self._sub = await self.namespace.subscribe(KV_EVENT_SUBJECT)
        self._sub_task = asyncio.create_task(
            self._consume_events(), name="kv-router-events"
        )
        # per-selection hit-rate telemetry -> {ns}.events.kv-hit-rate
        # (reference scheduler.rs:104); consumed by the metrics component
        self.scheduler.on_hit_rate = self._publish_hit_rate
        await self.aggregator.start()

    async def stop(self) -> None:
        if self._sub_task is not None:
            self._sub_task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._sub_task
            self._sub_task = None
        if self._sub is not None:
            await self._sub.close()
        await self.aggregator.stop()
        # release the sharded index's matching pool (flat index: no-op)
        close = getattr(self.indexer, "close", None)
        if close is not None:
            close()

    def _publish_hit_rate(self, ev) -> None:
        payload = {
            "worker_id": ev.worker_id,
            "isl_blocks": ev.isl_blocks,
            "overlap_blocks": ev.overlap_blocks,
        }

        async def _send() -> None:
            try:
                await self.namespace.publish(KV_HIT_RATE_SUBJECT, payload)
            except Exception:
                logger.debug("kv-hit-rate publish failed", exc_info=True)

        # hold a strong reference until done: a bare ensure_future() task
        # can be garbage-collected mid-await, silently dropping the event
        task = asyncio.ensure_future(_send())
        self._publish_tasks.add(task)
        task.add_done_callback(self._publish_tasks.discard)

    def _on_worker_removed(self, worker_id: int) -> None:
        # the aggregator already dropped it from the shared endpoint
        # snapshot; the index subtree is ours to clean up
        logger.info("worker %x removed; dropping its KV index entries", worker_id)
        self.indexer.remove_worker(worker_id)

    async def _consume_events(self) -> None:
        assert self._sub is not None
        async for _subject, payload in self._sub:
            try:
                msg = json.loads(payload)
                self.indexer.apply_event(int(msg["worker_id"]), msg["event"])
            except Exception:
                logger.exception("bad kv event payload")

    # -- selection -----------------------------------------------------------

    async def find_best_match(self, tokens: Sequence[int]) -> Tuple[int, int]:
        """Returns (worker_id, overlap_blocks) (reference kv_router.rs:
        176-196)."""
        worker_id, overlap, _donor = await self.find_best_match_with_donor(
            tokens
        )
        return worker_id, overlap

    async def find_best_match_with_donor(
        self, tokens: Sequence[int]
    ) -> Tuple[int, int, Optional[Dict[str, Any]]]:
        """Best-cost worker plus the best prefix *donor* when they differ.

        The cost function may send a request to a lightly-loaded worker even
        though another worker holds a longer cached prefix; that other
        worker is the onboarding donor (cross-worker block import,
        reference block_manager.rs:119-146).  Donor candidates come from
        two planes: the G1 overlap index (live device blocks on peers) and
        the cluster-global holdings index (offload-tier copies -- peer
        host/disk and the shared G4 store).  Quarantined workers never
        donate; the G4 store cannot be quarantined away (it is a passive
        object store, not a straggler candidate).

        Returns ``(worker_id, overlap_blocks, donor)`` with ``donor`` a
        dict ``{"instance", "blocks", "source": "peer"|"remote",
        "nbytes"}`` (``nbytes`` None when only the G1 index knows the
        prefix; ``instance`` is ``REMOTE_SOURCE_ID`` for the G4 store) or
        None when nobody beats the chosen worker's own cache."""
        _, seq_hashes = hash_blocks(tokens, self.block_size)
        overlap = self.indexer.find_matches(seq_hashes)
        worker_id = self.scheduler.schedule(overlap, len(tokens))
        own = overlap.scores.get(worker_id, 0)
        quarantined: set = set()
        q = getattr(self, "_quarantine", None)
        if q is not None:
            try:
                quarantined = set(q())
            except Exception:
                logger.debug("quarantine source failed", exc_info=True)
        donor: Optional[Dict[str, Any]] = None
        for w, blocks in overlap.scores.items():
            if w == worker_id or w in quarantined or blocks <= own:
                continue
            if donor is None or blocks > donor["blocks"]:
                donor = {
                    "instance": w,
                    "blocks": blocks,
                    "source": "peer",
                    "nbytes": None,
                }
        holdings = getattr(self.indexer, "holdings", None)
        if holdings is not None and holdings.num_blocks:
            sources = holdings.prefix_sources(
                seq_hashes, exclude={worker_id} | quarantined
            )
            for src, info in sources.items():
                # strict improvement only: at equal coverage the G1 peer
                # donor wins (its blocks are already device-resident)
                if info["blocks"] <= own or (
                    donor is not None and info["blocks"] <= donor["blocks"]
                ):
                    continue
                donor = {
                    "instance": src,
                    "blocks": info["blocks"],
                    "source": "remote" if src == REMOTE_SOURCE_ID else "peer",
                    "nbytes": info["nbytes"],
                    "tier": info["tier"],
                }
        return worker_id, own, donor


class KvPushRouter:
    """PushRouter wrapper: best-match then ``direct()`` (reference
    kv_router.rs:220-255)."""

    # evidence ring: every gate evaluation appends a JSONL-able dict here
    # (bench.py dumps it); bounded so long-lived routers don't grow forever
    DECISION_LOG_CAP = 4096

    def __init__(
        self,
        inner: PushRouter,
        chooser: KvRouter,
        *,
        transfer_ms=None,
        remote_spec: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.inner = inner
        self.chooser = chooser
        # routing decisions by cause: kv (best-match direct), kv_donor
        # (best-match plus a cross-worker onboarding donor), kv_remote
        # (donor is the G4 store), and the two fallbacks -- the series
        # smarter-routing work tunes against
        reg = rtm.default_registry()
        self._decisions = reg.counter(
            "dynamo_kv_router_decisions",
            "KV-router dispatch decisions by cause",
            ["cause"],
        )
        # NetKV-style fetch-vs-recompute gate evidence: every donor
        # candidate is adjudicated on predicted transfer ms vs predicted
        # prefill ms, and both estimates are recorded whichever way the
        # decision goes
        self._gate_decisions = reg.counter(
            "dynamo_kv_prefix_fetch_decisions",
            "Fetch-vs-recompute gate outcomes by decision and donor source",
            ["decision", "source"],
        )
        self._gate_pred = reg.histogram(
            "dynamo_kv_prefix_fetch_pred_seconds",
            "Fetch-vs-recompute gate cost predictions",
            ["kind"],
            buckets=rtm.TRANSFER_LATENCY_BUCKETS,
        )
        # transfer_ms: (nbytes, src_id, dst_id) -> predicted ms or None --
        # normally FleetObservatory.predict_transfer_ms, which also fits
        # the G4 store link (src/dst G4_STORE_ID) from TransferLog rows
        self._transfer_ms = transfer_ms
        spec = dict(remote_spec or {})
        self._prefill_tok_s = float(spec.get("prefill_tok_s", 4000.0))
        self._gbps = float(spec.get("gbps", 1.0))
        self.decisions_log: list = []

    def _gate_donor(
        self,
        request_id: str,
        instance_id: int,
        own: int,
        donor: Dict[str, Any],
    ) -> Dict[str, Any]:
        """Adjudicate fetch-vs-recompute for one donor candidate.

        Predicted fetch cost: the observatory's fitted link model when it
        can price the (donor -> chosen worker) link, else the configured
        flat ``gbps``.  Predicted recompute cost: the saved tokens at the
        configured per-worker prefill rate.  Both estimates land as span
        attrs, metric observations, and a decisions-log row regardless of
        which way the decision goes -- the acceptance surface."""
        blocks = int(donor["blocks"])
        saved_blocks = max(blocks - own, 0)
        tokens_saved = saved_blocks * self.chooser.block_size
        pred_prefill_ms = tokens_saved / max(self._prefill_tok_s, 1e-9) * 1e3
        nbytes = donor.get("nbytes")
        pred_fetch_ms: Optional[float] = None
        ship_bytes: Optional[int] = None
        if nbytes:
            # pro-rate the advertised bytes to the blocks actually shipped:
            # the onboarder only imports blocks past the chosen worker's
            # own coverage
            ship_bytes = int(int(nbytes) * saved_blocks / max(blocks, 1))
            if self._transfer_ms is not None:
                try:
                    pred_fetch_ms = self._transfer_ms(
                        ship_bytes, donor["instance"], instance_id
                    )
                except Exception:
                    logger.debug("transfer predictor failed", exc_info=True)
            if pred_fetch_ms is None:
                pred_fetch_ms = ship_bytes / (self._gbps * 1e9) * 1e3
        # unknown bytes (a pure-G1 peer donor) cannot be priced: keep the
        # pre-gate behaviour and fetch -- the onboarder's own fallback
        # still recomputes on any failure
        decision = "fetch"
        if pred_fetch_ms is not None and pred_fetch_ms >= pred_prefill_ms:
            decision = "recompute"
        source = str(donor["source"])
        self._gate_decisions.labels(decision, source).inc()
        if pred_fetch_ms is not None:
            self._gate_pred.labels("fetch").observe(pred_fetch_ms / 1e3)
        self._gate_pred.labels("prefill").observe(pred_prefill_ms / 1e3)
        row = {
            "ts": time.time(),
            "request_id": request_id,
            "instance": instance_id,
            "donor": donor["instance"],
            "source": source,
            "decision": decision,
            "own_blocks": own,
            "donor_blocks": blocks,
            "ship_bytes": ship_bytes,
            "pred_fetch_ms": pred_fetch_ms,
            "pred_prefill_ms": pred_prefill_ms,
        }
        self.decisions_log.append(row)
        if len(self.decisions_log) > self.DECISION_LOG_CAP:
            del self.decisions_log[: -self.DECISION_LOG_CAP]
        with tracing.span(
            "router.prefill_dispatch",
            request_id,
            instance=f"{instance_id:x}",
        ) as sp:
            sp.set(
                gate_decision=decision,
                donor_source=source,
                donor_blocks=blocks,
                own_blocks=own,
                pred_fetch_ms=pred_fetch_ms,
                pred_prefill_ms=pred_prefill_ms,
            )
        return row

    async def generate(self, request: Context[Any]) -> ResponseStream[Annotated]:
        data = request.data
        if isinstance(data, PreprocessedRequest):
            token_ids = data.token_ids
        else:
            token_ids = list((data or {}).get("token_ids") or [])
        def stamp(overlap_blocks: int) -> Context[Any]:
            if isinstance(data, PreprocessedRequest):
                data.estimated_prefix_hit_num_blocks = overlap_blocks
                return request
            return request.replace(
                dict(data or {}, estimated_prefix_hit_num_blocks=overlap_blocks)
            )

        try:
            (
                instance_id,
                overlap,
                donor,
            ) = await self.chooser.find_best_match_with_donor(token_ids)
        except Exception:
            # no metrics yet / no workers known to the scheduler: degrade to
            # plain load balancing over the live instances rather than failing
            logger.debug("kv selection failed; falling back", exc_info=True)
            self._decisions.labels("fallback_no_selection").inc()
            return await self.inner.generate(request)
        if donor is not None:
            # fetch-vs-recompute gate: only stamp the donor when importing
            # its blocks is predicted cheaper than recomputing them
            gate = self._gate_donor(request.id, instance_id, overlap, donor)
            if gate["decision"] != "fetch":
                donor = None
        if donor is not None:
            # a donor holds a longer prefix and fetching won the gate:
            # tell the chosen worker where to import it from
            # (llm/prefix_onboard.py consumes this)
            from ..prefix_onboard import DONOR_META_KEY

            request.metadata[DONOR_META_KEY] = {
                "instance": donor["instance"],
                "blocks": donor["blocks"],
                "source": donor["source"],
            }
        try:
            stream = await self.inner.direct(stamp(overlap), instance_id)
            if donor is None:
                cause = "kv"
            elif donor["source"] == "remote":
                cause = "kv_remote"
            else:
                cause = "kv_donor"
            self._decisions.labels(cause).inc()
            return stream
        except (InstanceNotFoundError, ConnectionRefusedError, WorkerLostError):
            # retryable dispatch failures are exactly those where the
            # request provably never started: a stale selection (instance
            # gone from the live set), a refused connect (the worker died
            # before the lease expired), or a prologue-stage loss (the
            # worker drained its subject / the connection dropped before
            # the handler acked).  Anything later must propagate --
            # re-dispatching after the worker may have started executing
            # would run the request twice.  Clear the overlap estimate: it
            # described the dead worker's cache, not whoever the fallback
            # picks.
            logger.debug(
                "selected instance %x vanished; falling back", instance_id
            )
            self._decisions.labels("fallback_dead_instance").inc()
            return await self.inner.generate(stamp(0))
