"""Router-side metrics scraping: poll every worker's load_metrics endpoint.

Rebuild of the reference aggregator (lib/llm/src/kv_router/
metrics_aggregator.rs:31-60): periodically collect ``ForwardPassMetrics``
from each live instance of the component's ``load_metrics`` endpoint into a
``ProcessedEndpoints`` snapshot.  The snapshot object is shared with the
scheduler (passed in by the KvRouter) so there is exactly one copy of
worker-load truth; the scheduler's predictive bumps land on it and the next
scrape overwrites them.  The reference scrapes NATS ``$SRV.STATS``; here
the workers serve a first-class endpoint the aggregator calls directly.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from typing import Callable, Optional

from ...protocols.common import ForwardPassMetrics
from ...runtime import metrics as rtm
from ...runtime.component import Client, Component, PushRouter
from ...runtime.engine import Context
from .publisher import LOAD_METRICS_ENDPOINT
from .scheduler import ProcessedEndpoints

logger = logging.getLogger("dynamo.kv_router")


class KvMetricsAggregator:
    """Background scrape loop feeding a shared ProcessedEndpoints snapshot."""

    def __init__(
        self,
        component: Component,
        interval_s: float = 0.2,
        endpoints: Optional[ProcessedEndpoints] = None,
        on_remove: Optional[Callable[[int], None]] = None,
    ) -> None:
        self.component = component
        self.interval_s = interval_s
        self.endpoints = endpoints if endpoints is not None else ProcessedEndpoints()
        self.on_remove = on_remove
        # a wedged worker must not stall the whole control loop
        self.scrape_timeout_s = max(interval_s * 5, 1.0)
        self._client: Optional[Client] = None
        self._router: Optional[PushRouter] = None
        self._task: Optional[asyncio.Task] = None
        # per-worker KV load, exported from the router's vantage point (the
        # planner and dashboards read the same snapshot routing runs on)
        self._kv_load = rtm.default_registry().gauge(
            "dynamo_kv_router_worker_kv_load",
            "Per-worker KV cache usage as last scraped by the router",
            ["worker"],
        )

    async def start(self) -> None:
        ep = self.component.endpoint(LOAD_METRICS_ENDPOINT)
        self._client = await ep.client()
        self._router = PushRouter(self._client)
        self._task = asyncio.create_task(self._loop(), name="kv-metrics-scrape")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._task
            self._task = None
        if self._client is not None:
            await self._client.close()

    async def _scrape_instance(self, instance_id: int) -> None:
        assert self._router is not None
        stream = await self._router.direct(Context.new({}), instance_id)
        async for item in stream:
            if item.data is not None:
                m = ForwardPassMetrics.from_dict(item.data)
                self.endpoints.update(instance_id, m)
                self._kv_load.labels(f"{instance_id:x}").set(
                    m.gpu_cache_usage_perc
                )

    async def scrape_once(self) -> ProcessedEndpoints:
        assert self._client is not None
        live = {i.instance_id for i in self._client.instances}
        for worker_id in list(self.endpoints.endpoints):
            if worker_id not in live:
                self.endpoints.remove(worker_id)
                with contextlib.suppress(KeyError):
                    self._kv_load.remove(f"{worker_id:x}")
                if self.on_remove is not None:
                    self.on_remove(worker_id)
        # scrape concurrently: one wedged worker costs scrape_timeout_s in
        # total, not per instance, and cycle latency stays flat in fleet size
        async def one(instance_id: int) -> None:
            try:
                await asyncio.wait_for(
                    self._scrape_instance(instance_id),
                    timeout=self.scrape_timeout_s,
                )
            except Exception:
                logger.debug("metrics scrape failed for %x", instance_id,
                             exc_info=True)

        await asyncio.gather(
            *(one(inst.instance_id) for inst in list(self._client.instances))
        )
        return self.endpoints

    async def _loop(self) -> None:
        while True:
            await self.scrape_once()
            await asyncio.sleep(self.interval_s)
