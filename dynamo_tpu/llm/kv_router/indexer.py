"""KvIndexer: which worker holds which KV blocks.

Rebuild of the reference radix-tree indexer (lib/llm/src/kv_router/
indexer.rs:187 RadixTree, :239 find_matches with early exit, :283
apply_event, :382 remove_worker).  Because this framework's sequence hashes
already bind the full prefix chain (parent-chained hashing,
dynamo_tpu/tokens/hashing.py), the radix tree collapses to a flat map keyed
by sequence hash: level-i lookup is one O(1) probe, and the walk stops at
the first level held by nobody -- the same early exit as the reference's
radix descent.

Hot path is native (native/radix.cpp via ctypes); the pure-Python fallback
implements identical semantics.  Single-threaded by contract: one asyncio
event loop owns each indexer (the reference runs its tree in a dedicated
single-threaded actor for the same reason).
"""

from __future__ import annotations

import ctypes
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ...tokens import hashing as _hashing


@dataclass
class OverlapScores:
    """Per-worker count of matched prefix blocks (reference indexer.rs
    OverlapScores).  Selection happens in the scheduler's cost function."""

    scores: Dict[int, int] = field(default_factory=dict)


class _PyIndex:
    """Pure-Python flat-map index; mirrors native/radix.cpp exactly."""

    def __init__(self) -> None:
        self.blocks: Dict[int, Set[int]] = {}
        self.by_worker: Dict[int, Set[int]] = {}

    def store(self, worker: int, hashes: Sequence[int]) -> None:
        mine = self.by_worker.setdefault(worker, set())
        for h in hashes:
            self.blocks.setdefault(h, set()).add(worker)
            mine.add(h)

    def remove(self, worker: int, hashes: Sequence[int]) -> None:
        mine = self.by_worker.get(worker)
        for h in hashes:
            ws = self.blocks.get(h)
            if ws is not None:
                ws.discard(worker)
                if not ws:
                    del self.blocks[h]
            if mine is not None:
                mine.discard(h)

    def remove_worker(self, worker: int) -> None:
        mine = self.by_worker.pop(worker, None)
        if not mine:
            return
        for h in mine:
            ws = self.blocks.get(h)
            if ws is not None:
                ws.discard(worker)
                if not ws:
                    del self.blocks[h]

    def find_matches(
        self, hashes: Sequence[int], early_exit: bool = True
    ) -> Dict[int, int]:
        scores: Dict[int, int] = {}
        for h in hashes:
            ws = self.blocks.get(h)
            if not ws:
                if early_exit:
                    break  # deeper blocks chain through this one
                continue
            for w in ws:
                scores[w] = scores.get(w, 0) + 1
        return scores

    def coverage(self, hashes: Sequence[int]) -> List[bool]:
        """Per-position: does ANY worker here hold the hash (sharded merge)."""
        return [bool(self.blocks.get(h)) for h in hashes]

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    @property
    def num_workers(self) -> int:
        return len(self.by_worker)


class _NativeIndex:
    """ctypes wrapper over native/radix.cpp."""

    MAX_WORKERS = 4096

    def __init__(self, lib: ctypes.CDLL) -> None:
        self._lib = lib
        lib.dyn_radix_new.restype = ctypes.c_void_p
        lib.dyn_radix_free.argtypes = [ctypes.c_void_p]
        lib.dyn_radix_store.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dyn_radix_remove.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dyn_radix_remove_worker.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.dyn_radix_find_matches.restype = ctypes.c_size_t
        lib.dyn_radix_find_matches.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dyn_radix_num_blocks.restype = ctypes.c_size_t
        lib.dyn_radix_num_blocks.argtypes = [ctypes.c_void_p]
        lib.dyn_radix_num_workers.restype = ctypes.c_size_t
        lib.dyn_radix_num_workers.argtypes = [ctypes.c_void_p]
        # sharded-index entry points (absent in pre-r4 cached builds; the
        # sharded wrapper degrades to the py index when missing)
        self.has_sharded_api = hasattr(lib, "dyn_radix_find_matches_all")
        if self.has_sharded_api:
            lib.dyn_radix_find_matches_all.restype = ctypes.c_size_t
            lib.dyn_radix_find_matches_all.argtypes = (
                lib.dyn_radix_find_matches.argtypes
            )
            lib.dyn_radix_coverage.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_void_p,
            ]
        self._ptr = lib.dyn_radix_new()
        # reused across queries (single-threaded by contract): find_matches
        # is the per-request routing hot path
        self._out_w = np.empty(self.MAX_WORKERS, dtype=np.uint64)
        self._out_s = np.empty(self.MAX_WORKERS, dtype=np.uint32)

    def __del__(self) -> None:
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr:
            self._lib.dyn_radix_free(ptr)

    @staticmethod
    def _arr(hashes: Sequence[int]) -> np.ndarray:
        return np.ascontiguousarray(np.asarray(hashes, dtype=np.uint64))

    def store(self, worker: int, hashes: Sequence[int]) -> None:
        a = self._arr(hashes)
        self._lib.dyn_radix_store(self._ptr, worker, a.ctypes.data, len(a))

    def remove(self, worker: int, hashes: Sequence[int]) -> None:
        a = self._arr(hashes)
        self._lib.dyn_radix_remove(self._ptr, worker, a.ctypes.data, len(a))

    def remove_worker(self, worker: int) -> None:
        self._lib.dyn_radix_remove_worker(self._ptr, worker)

    def find_matches(
        self, hashes: Sequence[int], early_exit: bool = True
    ) -> Dict[int, int]:
        a = self._arr(hashes)
        out_w, out_s = self._out_w, self._out_s
        fn = (
            self._lib.dyn_radix_find_matches
            if early_exit
            else self._lib.dyn_radix_find_matches_all
        )
        k = fn(
            self._ptr, a.ctypes.data, len(a),
            out_w.ctypes.data, out_s.ctypes.data, self.MAX_WORKERS,
        )
        return {int(out_w[i]): int(out_s[i]) for i in range(k)}

    def coverage(self, hashes: Sequence[int]) -> List[bool]:
        a = self._arr(hashes)
        out = np.zeros(len(a), dtype=np.uint8)
        self._lib.dyn_radix_coverage(
            self._ptr, a.ctypes.data, len(a), out.ctypes.data
        )
        return [bool(x) for x in out]

    @property
    def num_blocks(self) -> int:
        return self._lib.dyn_radix_num_blocks(self._ptr)

    @property
    def num_workers(self) -> int:
        return self._lib.dyn_radix_num_workers(self._ptr)


class KvIndexer:
    """The router-side global KV-block index.

    Consumes worker KV events (``stored`` / ``removed`` / ``cleared``) and
    answers ``find_matches`` queries with per-worker overlap scores.
    """

    def __init__(self, block_size: int = 16, use_native: bool = True) -> None:
        self.block_size = block_size
        lib = _hashing.NATIVE if use_native else None
        self._index = (
            _NativeIndex(lib)
            if lib is not None and hasattr(lib, "dyn_radix_new")
            else _PyIndex()
        )
        self.native = isinstance(self._index, _NativeIndex)

    # -- event ingestion -----------------------------------------------------

    def apply_event(self, worker_id: int, event: Dict) -> None:
        """Apply one worker KV event (reference indexer.rs:283).

        Shapes (as emitted by JaxEngine/_publish_stored and the mocker):
          {"type": "stored", "blocks": [{"sequence_hash": h, ...}, ...]}
          {"type": "removed", "sequence_hashes": [h, ...]}
          {"type": "cleared"}
        """
        etype = event.get("type")
        if etype == "stored":
            hashes = [int(b["sequence_hash"]) for b in event.get("blocks", [])]
            self._index.store(worker_id, hashes)
        elif etype == "removed":
            self._index.remove(
                worker_id, [int(h) for h in event.get("sequence_hashes", [])]
            )
        elif etype == "cleared":
            self._index.remove_worker(worker_id)

    def remove_worker(self, worker_id: int) -> None:
        """Drop every entry of a dead worker (reference indexer.rs:382)."""
        self._index.remove_worker(worker_id)

    # -- queries -------------------------------------------------------------

    def find_matches(self, sequence_hashes: Sequence[int]) -> OverlapScores:
        return OverlapScores(scores=self._index.find_matches(sequence_hashes))

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> OverlapScores:
        _, seq_hashes = _hashing.hash_blocks(tokens, self.block_size)
        return self.find_matches(seq_hashes)

    @property
    def num_blocks(self) -> int:
        return self._index.num_blocks

    @property
    def num_workers(self) -> int:
        return self._index.num_workers


class KvIndexerSharded:
    """Worker-sharded KV index (reference indexer.rs:696 KvIndexerSharded).

    Large fleets overwhelm one index: the reference pins each worker to a
    shard (least-loaded assignment), routes that worker's event stream to
    its shard's thread, broadcasts match requests to every shard, and
    merges the per-shard overlap scores.  Same structure here over N
    :class:`KvIndexer` shards; a match executes the shards through a small
    thread pool when the native index is in use (the ctypes calls drop the
    GIL, so shard matching genuinely overlaps), and falls back to a
    sequential sweep on the pure-Python index.
    """

    def __init__(
        self,
        block_size: int = 16,
        num_shards: int = 4,
        use_native: bool = True,
    ) -> None:
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.block_size = block_size
        self.shards = [
            KvIndexer(block_size, use_native=use_native)
            for _ in range(num_shards)
        ]
        if self.shards[0].native and not getattr(
            self.shards[0]._index, "has_sharded_api", False
        ):
            # stale pre-r4 native build without coverage/no-exit entry
            # points: correctness over speed, use the python index
            self.shards = [
                KvIndexer(block_size, use_native=False)
                for _ in range(num_shards)
            ]
        self._assignment: Dict[int, int] = {}  # worker -> shard
        self._counts = [0] * num_shards
        self._pool = None
        if self.shards[0].native and num_shards > 1:
            import concurrent.futures

            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=num_shards, thread_name_prefix="kv-index-shard"
            )

    def _shard_of(self, worker_id: int) -> int:
        s = self._assignment.get(worker_id)
        if s is None:
            # least-loaded assignment (reference worker_counts)
            s = min(range(len(self.shards)), key=lambda i: self._counts[i])
            self._assignment[worker_id] = s
            self._counts[s] += 1
        return s

    def apply_event(self, worker_id: int, event: Dict) -> None:
        if event.get("type") == "cleared":
            # the flat index forgets the worker entirely on "cleared"; the
            # assignment and load count must follow, or dead-cleared
            # workers skew least-loaded pinning forever
            self.remove_worker(worker_id)
            return
        self.shards[self._shard_of(worker_id)].apply_event(worker_id, event)

    def remove_worker(self, worker_id: int) -> None:
        s = self._assignment.pop(worker_id, None)
        if s is not None:
            self._counts[s] -= 1
            self.shards[s].remove_worker(worker_id)

    def find_matches(self, sequence_hashes: Sequence[int]) -> OverlapScores:
        """Two-pass match preserving the flat index's semantics exactly.

        The flat walk stops at the first hash held by NO worker fleet-wide;
        a single shard cannot see that boundary (a hole in its own workers'
        holdings is not a fleet-wide hole).  Pass 1 ORs per-shard coverage
        to find the global early-exit point; pass 2 sweeps each shard over
        the truncated chain without a shard-local exit and merges (worker
        sets are disjoint across shards)."""
        hashes = list(sequence_hashes)
        if not hashes:
            return OverlapScores(scores={})

        def shard_cov(sh):
            return sh._index.coverage(hashes)

        if self._pool is not None:
            covs = list(self._pool.map(shard_cov, self.shards))
        else:
            covs = [shard_cov(sh) for sh in self.shards]
        L = len(hashes)
        for i in range(len(hashes)):
            if not any(c[i] for c in covs):
                L = i
                break
        prefix = hashes[:L]
        if not prefix:
            return OverlapScores(scores={})

        def shard_match(sh):
            return sh._index.find_matches(prefix, early_exit=False)

        if self._pool is not None:
            results = list(self._pool.map(shard_match, self.shards))
        else:
            results = [shard_match(sh) for sh in self.shards]
        merged: Dict[int, int] = {}
        for r in results:
            merged.update(r)
        return OverlapScores(scores=merged)

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> OverlapScores:
        _, seq_hashes = _hashing.hash_blocks(tokens, self.block_size)
        return self.find_matches(seq_hashes)

    def close(self) -> None:
        """Release the shard-matching thread pool (long-lived routers that
        rebuild their index must not leak a pool per rebuild)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    @property
    def num_blocks(self) -> int:
        # sum of per-shard uniques: a block cached by workers on different
        # shards counts once per shard (the reference's per-shard tries
        # have the same property)
        return sum(sh.num_blocks for sh in self.shards)

    @property
    def num_workers(self) -> int:
        return len(self._assignment)
