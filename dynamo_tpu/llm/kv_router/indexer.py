"""KvIndexer: which worker holds which KV blocks.

Rebuild of the reference radix-tree indexer (lib/llm/src/kv_router/
indexer.rs:187 RadixTree, :239 find_matches with early exit, :283
apply_event, :382 remove_worker).  Because this framework's sequence hashes
already bind the full prefix chain (parent-chained hashing,
dynamo_tpu/tokens/hashing.py), the radix tree collapses to a flat map keyed
by sequence hash: level-i lookup is one O(1) probe, and the walk stops at
the first level held by nobody -- the same early exit as the reference's
radix descent.

Hot path is native (native/radix.cpp via ctypes); the pure-Python fallback
implements identical semantics.  Single-threaded by contract: one asyncio
event loop owns each indexer (the reference runs its tree in a dedicated
single-threaded actor for the same reason).
"""

from __future__ import annotations

import ctypes
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ...tokens import hashing as _hashing


@dataclass
class OverlapScores:
    """Per-worker count of matched prefix blocks (reference indexer.rs
    OverlapScores).  Selection happens in the scheduler's cost function."""

    scores: Dict[int, int] = field(default_factory=dict)


class _PyIndex:
    """Pure-Python flat-map index; mirrors native/radix.cpp exactly."""

    def __init__(self) -> None:
        self.blocks: Dict[int, Set[int]] = {}
        self.by_worker: Dict[int, Set[int]] = {}

    def store(self, worker: int, hashes: Sequence[int]) -> None:
        mine = self.by_worker.setdefault(worker, set())
        for h in hashes:
            self.blocks.setdefault(h, set()).add(worker)
            mine.add(h)

    def remove(self, worker: int, hashes: Sequence[int]) -> None:
        mine = self.by_worker.get(worker)
        for h in hashes:
            ws = self.blocks.get(h)
            if ws is not None:
                ws.discard(worker)
                if not ws:
                    del self.blocks[h]
            if mine is not None:
                mine.discard(h)

    def remove_worker(self, worker: int) -> None:
        mine = self.by_worker.pop(worker, None)
        if not mine:
            return
        for h in mine:
            ws = self.blocks.get(h)
            if ws is not None:
                ws.discard(worker)
                if not ws:
                    del self.blocks[h]

    def find_matches(
        self, hashes: Sequence[int], early_exit: bool = True
    ) -> Dict[int, int]:
        scores: Dict[int, int] = {}
        for h in hashes:
            ws = self.blocks.get(h)
            if not ws:
                if early_exit:
                    break  # deeper blocks chain through this one
                continue
            for w in ws:
                scores[w] = scores.get(w, 0) + 1
        return scores

    def coverage(self, hashes: Sequence[int]) -> List[bool]:
        """Per-position: does ANY worker here hold the hash (sharded merge)."""
        return [bool(self.blocks.get(h)) for h in hashes]

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    @property
    def num_workers(self) -> int:
        return len(self.by_worker)


class _NativeIndex:
    """ctypes wrapper over native/radix.cpp."""

    MAX_WORKERS = 4096

    def __init__(self, lib: ctypes.CDLL) -> None:
        self._lib = lib
        lib.dyn_radix_new.restype = ctypes.c_void_p
        lib.dyn_radix_free.argtypes = [ctypes.c_void_p]
        lib.dyn_radix_store.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dyn_radix_remove.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dyn_radix_remove_worker.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.dyn_radix_find_matches.restype = ctypes.c_size_t
        lib.dyn_radix_find_matches.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dyn_radix_num_blocks.restype = ctypes.c_size_t
        lib.dyn_radix_num_blocks.argtypes = [ctypes.c_void_p]
        lib.dyn_radix_num_workers.restype = ctypes.c_size_t
        lib.dyn_radix_num_workers.argtypes = [ctypes.c_void_p]
        # sharded-index entry points (absent in pre-r4 cached builds; the
        # sharded wrapper degrades to the py index when missing)
        self.has_sharded_api = hasattr(lib, "dyn_radix_find_matches_all")
        if self.has_sharded_api:
            lib.dyn_radix_find_matches_all.restype = ctypes.c_size_t
            lib.dyn_radix_find_matches_all.argtypes = (
                lib.dyn_radix_find_matches.argtypes
            )
            lib.dyn_radix_coverage.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_void_p,
            ]
        self._ptr = lib.dyn_radix_new()
        # reused across queries (single-threaded by contract): find_matches
        # is the per-request routing hot path
        self._out_w = np.empty(self.MAX_WORKERS, dtype=np.uint64)
        self._out_s = np.empty(self.MAX_WORKERS, dtype=np.uint32)

    def __del__(self) -> None:
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr:
            self._lib.dyn_radix_free(ptr)

    @staticmethod
    def _arr(hashes: Sequence[int]) -> np.ndarray:
        return np.ascontiguousarray(np.asarray(hashes, dtype=np.uint64))

    def store(self, worker: int, hashes: Sequence[int]) -> None:
        a = self._arr(hashes)
        self._lib.dyn_radix_store(self._ptr, worker, a.ctypes.data, len(a))

    def remove(self, worker: int, hashes: Sequence[int]) -> None:
        a = self._arr(hashes)
        self._lib.dyn_radix_remove(self._ptr, worker, a.ctypes.data, len(a))

    def remove_worker(self, worker: int) -> None:
        self._lib.dyn_radix_remove_worker(self._ptr, worker)

    def find_matches(
        self, hashes: Sequence[int], early_exit: bool = True
    ) -> Dict[int, int]:
        a = self._arr(hashes)
        out_w, out_s = self._out_w, self._out_s
        fn = (
            self._lib.dyn_radix_find_matches
            if early_exit
            else self._lib.dyn_radix_find_matches_all
        )
        k = fn(
            self._ptr, a.ctypes.data, len(a),
            out_w.ctypes.data, out_s.ctypes.data, self.MAX_WORKERS,
        )
        return {int(out_w[i]): int(out_s[i]) for i in range(k)}

    def coverage(self, hashes: Sequence[int]) -> List[bool]:
        a = self._arr(hashes)
        out = np.zeros(len(a), dtype=np.uint8)
        self._lib.dyn_radix_coverage(
            self._ptr, a.ctypes.data, len(a), out.ctypes.data
        )
        return [bool(x) for x in out]

    @property
    def num_blocks(self) -> int:
        return self._lib.dyn_radix_num_blocks(self._ptr)

    @property
    def num_workers(self) -> int:
        return self._lib.dyn_radix_num_workers(self._ptr)


# Pseudo worker id the G4 fleet store answers prefix_sources under
# (mirrors offload.G4_STORE_ID without importing the offload plane)
REMOTE_SOURCE_ID = -4


class HoldingsIndex:
    """Cluster-global offload-tier holdings: which worker parks which
    block in which tier (G2 host / G3 disk / G4 remote), at what size.

    The G1 index above answers "route to the warm worker"; this one
    answers "fetch the prefix from a peer's tiers or from the G4 store".
    Fed by the workers' ``kv_holdings`` topic (tier residency deltas from
    the offload plane -- every put/promote/demote/evict publishes, so
    the index never advertises a tier a worker already dropped).

    ``tier == "remote"`` adverts are keyed under :data:`REMOTE_SOURCE_ID`
    rather than the publishing worker: a blob in the fleet store is
    fetchable regardless of which worker uploaded it, and its lifecycle
    is the STORE's, not the uploader's -- the worker later evicting its
    own host copy (a ``tier=None`` row) or dying must not wipe the G4
    advert while the blob still sits in the store.  A stale G4 advert
    (the store LRU'd the blob out) self-heals as a fetch miss: the
    onboarder recomputes, and the fetching tier forgets the hash.
    Single-threaded by contract, like the owning indexer."""

    def __init__(self) -> None:
        # hash -> {source_id: (tier, nbytes)}; source is the holding
        # worker, or REMOTE_SOURCE_ID for fleet-store entries
        self._by_hash: Dict[int, Dict[int, tuple]] = {}
        self._by_worker: Dict[int, Set[int]] = {}

    def apply(self, worker_id: int, delta: Sequence[Dict]) -> None:
        """Merge one holdings delta: rows ``{"sequence_hash", "tier",
        "nbytes"}``; ``tier=None`` drops the worker's entry (never the
        fleet store's -- see the class docstring)."""
        worker_id = int(worker_id)
        mine = self._by_worker.setdefault(worker_id, set())
        for row in delta:
            try:
                h = int(row["sequence_hash"])
            except (KeyError, TypeError, ValueError):
                continue
            tier = row.get("tier")
            if tier is None:
                holders = self._by_hash.get(h)
                if holders is not None:
                    holders.pop(worker_id, None)
                    if not holders:
                        del self._by_hash[h]
                mine.discard(h)
            else:
                src = REMOTE_SOURCE_ID if tier == "remote" else worker_id
                self._by_hash.setdefault(h, {})[src] = (
                    str(tier),
                    int(row.get("nbytes") or 0),
                )
                if src == worker_id:
                    mine.add(h)
        if not mine:
            self._by_worker.pop(worker_id, None)

    def remove_worker(self, worker_id: int) -> None:
        """Forget a dead worker's own-tier holdings.  Its G4 adverts stay:
        the store outlives the worker and the blobs remain fetchable."""
        mine = self._by_worker.pop(int(worker_id), None)
        if not mine:
            return
        for h in mine:
            holders = self._by_hash.get(h)
            if holders is not None:
                holders.pop(int(worker_id), None)
                if not holders:
                    del self._by_hash[h]

    def holders(self, seq_hash: int) -> Dict[int, tuple]:
        return dict(self._by_hash.get(int(seq_hash), {}))

    def prefix_sources(
        self, sequence_hashes: Sequence[int], exclude: Sequence[int] = ()
    ) -> Dict[int, Dict[str, int]]:
        """Per-source contiguous-prefix holdings over the request's block
        chain: ``{source_id: {"blocks": n, "nbytes": total, "tier": t}}``
        where ``blocks`` counts how many leading chain blocks the source
        holds contiguously from position 0 (prefix chains are only usable
        contiguously, same contract as the offload prefetch walk).  G4
        entries aggregate under ``REMOTE_SOURCE_ID``; ``exclude`` drops
        candidate workers (the chosen worker itself, quarantined ids)."""
        excluded = {int(w) for w in exclude}
        out: Dict[int, Dict[str, int]] = {}
        for i, h in enumerate(sequence_hashes):
            holders = self._by_hash.get(int(h))
            if not holders:
                break  # nobody holds position i: deeper blocks unusable
            for worker_id, (tier, nbytes) in holders.items():
                src = REMOTE_SOURCE_ID if tier == "remote" else worker_id
                if src != REMOTE_SOURCE_ID and src in excluded:
                    continue
                ent = out.get(src)
                if ent is None:
                    if i == 0:
                        out[src] = {"blocks": 1, "nbytes": nbytes, "tier": tier}
                elif ent["blocks"] == i:
                    ent["blocks"] = i + 1
                    ent["nbytes"] += nbytes
        return {s: e for s, e in out.items() if e["blocks"] > 0}

    @property
    def num_blocks(self) -> int:
        return len(self._by_hash)

    @property
    def num_workers(self) -> int:
        return len(self._by_worker)


class KvIndexer:
    """The router-side global KV-block index.

    Consumes worker KV events (``stored`` / ``removed`` / ``cleared``) and
    answers ``find_matches`` queries with per-worker overlap scores.  The
    attached :class:`HoldingsIndex` extends the view below G1: holdings
    events (``holdings`` / ``holdings_cleared``) track which offload tier
    parks which block fleet-wide.
    """

    def __init__(self, block_size: int = 16, use_native: bool = True) -> None:
        self.block_size = block_size
        lib = _hashing.NATIVE if use_native else None
        self._index = (
            _NativeIndex(lib)
            if lib is not None and hasattr(lib, "dyn_radix_new")
            else _PyIndex()
        )
        self.native = isinstance(self._index, _NativeIndex)
        self.holdings = HoldingsIndex()

    # -- event ingestion -----------------------------------------------------

    def apply_event(self, worker_id: int, event: Dict) -> None:
        """Apply one worker KV event (reference indexer.rs:283).

        Shapes (as emitted by JaxEngine/_publish_stored and the mocker):
          {"type": "stored", "blocks": [{"sequence_hash": h, ...}, ...]}
          {"type": "removed", "sequence_hashes": [h, ...]}
          {"type": "cleared"}
        plus the offload plane's tier-residency stream (KvHoldingsPublisher):
          {"type": "holdings", "delta": [{"sequence_hash", "tier", "nbytes"}]}
          {"type": "holdings_cleared"}  (publisher overflow collapse)
        """
        etype = event.get("type")
        if etype == "stored":
            hashes = [int(b["sequence_hash"]) for b in event.get("blocks", [])]
            self._index.store(worker_id, hashes)
        elif etype == "removed":
            self._index.remove(
                worker_id, [int(h) for h in event.get("sequence_hashes", [])]
            )
        elif etype == "cleared":
            self._index.remove_worker(worker_id)
            self.holdings.remove_worker(worker_id)
        elif etype == "holdings":
            self.holdings.apply(worker_id, event.get("delta", []))
        elif etype == "holdings_cleared":
            self.holdings.remove_worker(worker_id)

    def remove_worker(self, worker_id: int) -> None:
        """Drop every entry of a dead worker (reference indexer.rs:382)."""
        self._index.remove_worker(worker_id)
        self.holdings.remove_worker(worker_id)

    # -- queries -------------------------------------------------------------

    def find_matches(self, sequence_hashes: Sequence[int]) -> OverlapScores:
        return OverlapScores(scores=self._index.find_matches(sequence_hashes))

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> OverlapScores:
        _, seq_hashes = _hashing.hash_blocks(tokens, self.block_size)
        return self.find_matches(seq_hashes)

    @property
    def num_blocks(self) -> int:
        return self._index.num_blocks

    @property
    def num_workers(self) -> int:
        return self._index.num_workers


class KvIndexerSharded:
    """Worker-sharded KV index (reference indexer.rs:696 KvIndexerSharded).

    Large fleets overwhelm one index: the reference pins each worker to a
    shard (least-loaded assignment), routes that worker's event stream to
    its shard's thread, broadcasts match requests to every shard, and
    merges the per-shard overlap scores.  Same structure here over N
    :class:`KvIndexer` shards; a match executes the shards through a small
    thread pool when the native index is in use (the ctypes calls drop the
    GIL, so shard matching genuinely overlaps), and falls back to a
    sequential sweep on the pure-Python index.
    """

    def __init__(
        self,
        block_size: int = 16,
        num_shards: int = 4,
        use_native: bool = True,
    ) -> None:
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.block_size = block_size
        self.shards = [
            KvIndexer(block_size, use_native=use_native)
            for _ in range(num_shards)
        ]
        if self.shards[0].native and not getattr(
            self.shards[0]._index, "has_sharded_api", False
        ):
            # stale pre-r4 native build without coverage/no-exit entry
            # points: correctness over speed, use the python index
            self.shards = [
                KvIndexer(block_size, use_native=False)
                for _ in range(num_shards)
            ]
        self._assignment: Dict[int, int] = {}  # worker -> shard
        self._counts = [0] * num_shards
        # ONE wrapper-level holdings index (tier adverts are tiny next to
        # G1 block sets; sharding them would force a merge per query)
        self.holdings = HoldingsIndex()
        self._pool = None
        if self.shards[0].native and num_shards > 1:
            import concurrent.futures

            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=num_shards, thread_name_prefix="kv-index-shard"
            )

    def _shard_of(self, worker_id: int) -> int:
        s = self._assignment.get(worker_id)
        if s is None:
            # least-loaded assignment (reference worker_counts)
            s = min(range(len(self.shards)), key=lambda i: self._counts[i])
            self._assignment[worker_id] = s
            self._counts[s] += 1
        return s

    def apply_event(self, worker_id: int, event: Dict) -> None:
        etype = event.get("type")
        if etype == "cleared":
            # the flat index forgets the worker entirely on "cleared"; the
            # assignment and load count must follow, or dead-cleared
            # workers skew least-loaded pinning forever
            self.remove_worker(worker_id)
            return
        if etype == "holdings":
            self.holdings.apply(worker_id, event.get("delta", []))
            return
        if etype == "holdings_cleared":
            self.holdings.remove_worker(worker_id)
            return
        self.shards[self._shard_of(worker_id)].apply_event(worker_id, event)

    def remove_worker(self, worker_id: int) -> None:
        self.holdings.remove_worker(worker_id)
        s = self._assignment.pop(worker_id, None)
        if s is not None:
            self._counts[s] -= 1
            self.shards[s].remove_worker(worker_id)

    def find_matches(self, sequence_hashes: Sequence[int]) -> OverlapScores:
        """Two-pass match preserving the flat index's semantics exactly.

        The flat walk stops at the first hash held by NO worker fleet-wide;
        a single shard cannot see that boundary (a hole in its own workers'
        holdings is not a fleet-wide hole).  Pass 1 ORs per-shard coverage
        to find the global early-exit point; pass 2 sweeps each shard over
        the truncated chain without a shard-local exit and merges (worker
        sets are disjoint across shards)."""
        hashes = list(sequence_hashes)
        if not hashes:
            return OverlapScores(scores={})

        def shard_cov(sh):
            return sh._index.coverage(hashes)

        if self._pool is not None:
            covs = list(self._pool.map(shard_cov, self.shards))
        else:
            covs = [shard_cov(sh) for sh in self.shards]
        L = len(hashes)
        for i in range(len(hashes)):
            if not any(c[i] for c in covs):
                L = i
                break
        prefix = hashes[:L]
        if not prefix:
            return OverlapScores(scores={})

        def shard_match(sh):
            return sh._index.find_matches(prefix, early_exit=False)

        if self._pool is not None:
            results = list(self._pool.map(shard_match, self.shards))
        else:
            results = [shard_match(sh) for sh in self.shards]
        merged: Dict[int, int] = {}
        for r in results:
            merged.update(r)
        return OverlapScores(scores=merged)

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> OverlapScores:
        _, seq_hashes = _hashing.hash_blocks(tokens, self.block_size)
        return self.find_matches(seq_hashes)

    def close(self) -> None:
        """Release the shard-matching thread pool (long-lived routers that
        rebuild their index must not leak a pool per rebuild)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    @property
    def num_blocks(self) -> int:
        # sum of per-shard uniques: a block cached by workers on different
        # shards counts once per shard (the reference's per-shard tries
        # have the same property)
        return sum(sh.num_blocks for sh in self.shards)

    @property
    def num_workers(self) -> int:
        return len(self._assignment)
