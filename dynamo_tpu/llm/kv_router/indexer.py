"""KvIndexer: which worker holds which KV blocks.

Rebuild of the reference radix-tree indexer (lib/llm/src/kv_router/
indexer.rs:187 RadixTree, :239 find_matches with early exit, :283
apply_event, :382 remove_worker).  Because this framework's sequence hashes
already bind the full prefix chain (parent-chained hashing,
dynamo_tpu/tokens/hashing.py), the radix tree collapses to a flat map keyed
by sequence hash: level-i lookup is one O(1) probe, and the walk stops at
the first level held by nobody -- the same early exit as the reference's
radix descent.

Hot path is native (native/radix.cpp via ctypes); the pure-Python fallback
implements identical semantics.  Single-threaded by contract: one asyncio
event loop owns each indexer (the reference runs its tree in a dedicated
single-threaded actor for the same reason).
"""

from __future__ import annotations

import ctypes
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ...tokens import hashing as _hashing


@dataclass
class OverlapScores:
    """Per-worker count of matched prefix blocks (reference indexer.rs
    OverlapScores).  Selection happens in the scheduler's cost function."""

    scores: Dict[int, int] = field(default_factory=dict)


class _PyIndex:
    """Pure-Python flat-map index; mirrors native/radix.cpp exactly."""

    def __init__(self) -> None:
        self.blocks: Dict[int, Set[int]] = {}
        self.by_worker: Dict[int, Set[int]] = {}

    def store(self, worker: int, hashes: Sequence[int]) -> None:
        mine = self.by_worker.setdefault(worker, set())
        for h in hashes:
            self.blocks.setdefault(h, set()).add(worker)
            mine.add(h)

    def remove(self, worker: int, hashes: Sequence[int]) -> None:
        mine = self.by_worker.get(worker)
        for h in hashes:
            ws = self.blocks.get(h)
            if ws is not None:
                ws.discard(worker)
                if not ws:
                    del self.blocks[h]
            if mine is not None:
                mine.discard(h)

    def remove_worker(self, worker: int) -> None:
        mine = self.by_worker.pop(worker, None)
        if not mine:
            return
        for h in mine:
            ws = self.blocks.get(h)
            if ws is not None:
                ws.discard(worker)
                if not ws:
                    del self.blocks[h]

    def find_matches(self, hashes: Sequence[int]) -> Dict[int, int]:
        scores: Dict[int, int] = {}
        for h in hashes:
            ws = self.blocks.get(h)
            if not ws:
                break  # early exit: deeper blocks chain through this one
            for w in ws:
                scores[w] = scores.get(w, 0) + 1
        return scores

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    @property
    def num_workers(self) -> int:
        return len(self.by_worker)


class _NativeIndex:
    """ctypes wrapper over native/radix.cpp."""

    MAX_WORKERS = 4096

    def __init__(self, lib: ctypes.CDLL) -> None:
        self._lib = lib
        lib.dyn_radix_new.restype = ctypes.c_void_p
        lib.dyn_radix_free.argtypes = [ctypes.c_void_p]
        lib.dyn_radix_store.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dyn_radix_remove.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dyn_radix_remove_worker.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.dyn_radix_find_matches.restype = ctypes.c_size_t
        lib.dyn_radix_find_matches.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dyn_radix_num_blocks.restype = ctypes.c_size_t
        lib.dyn_radix_num_blocks.argtypes = [ctypes.c_void_p]
        lib.dyn_radix_num_workers.restype = ctypes.c_size_t
        lib.dyn_radix_num_workers.argtypes = [ctypes.c_void_p]
        self._ptr = lib.dyn_radix_new()
        # reused across queries (single-threaded by contract): find_matches
        # is the per-request routing hot path
        self._out_w = np.empty(self.MAX_WORKERS, dtype=np.uint64)
        self._out_s = np.empty(self.MAX_WORKERS, dtype=np.uint32)

    def __del__(self) -> None:
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr:
            self._lib.dyn_radix_free(ptr)

    @staticmethod
    def _arr(hashes: Sequence[int]) -> np.ndarray:
        return np.ascontiguousarray(np.asarray(hashes, dtype=np.uint64))

    def store(self, worker: int, hashes: Sequence[int]) -> None:
        a = self._arr(hashes)
        self._lib.dyn_radix_store(self._ptr, worker, a.ctypes.data, len(a))

    def remove(self, worker: int, hashes: Sequence[int]) -> None:
        a = self._arr(hashes)
        self._lib.dyn_radix_remove(self._ptr, worker, a.ctypes.data, len(a))

    def remove_worker(self, worker: int) -> None:
        self._lib.dyn_radix_remove_worker(self._ptr, worker)

    def find_matches(self, hashes: Sequence[int]) -> Dict[int, int]:
        a = self._arr(hashes)
        out_w, out_s = self._out_w, self._out_s
        k = self._lib.dyn_radix_find_matches(
            self._ptr, a.ctypes.data, len(a),
            out_w.ctypes.data, out_s.ctypes.data, self.MAX_WORKERS,
        )
        return {int(out_w[i]): int(out_s[i]) for i in range(k)}

    @property
    def num_blocks(self) -> int:
        return self._lib.dyn_radix_num_blocks(self._ptr)

    @property
    def num_workers(self) -> int:
        return self._lib.dyn_radix_num_workers(self._ptr)


class KvIndexer:
    """The router-side global KV-block index.

    Consumes worker KV events (``stored`` / ``removed`` / ``cleared``) and
    answers ``find_matches`` queries with per-worker overlap scores.
    """

    def __init__(self, block_size: int = 16, use_native: bool = True) -> None:
        self.block_size = block_size
        lib = _hashing.NATIVE if use_native else None
        self._index = (
            _NativeIndex(lib)
            if lib is not None and hasattr(lib, "dyn_radix_new")
            else _PyIndex()
        )
        self.native = isinstance(self._index, _NativeIndex)

    # -- event ingestion -----------------------------------------------------

    def apply_event(self, worker_id: int, event: Dict) -> None:
        """Apply one worker KV event (reference indexer.rs:283).

        Shapes (as emitted by JaxEngine/_publish_stored and the mocker):
          {"type": "stored", "blocks": [{"sequence_hash": h, ...}, ...]}
          {"type": "removed", "sequence_hashes": [h, ...]}
          {"type": "cleared"}
        """
        etype = event.get("type")
        if etype == "stored":
            hashes = [int(b["sequence_hash"]) for b in event.get("blocks", [])]
            self._index.store(worker_id, hashes)
        elif etype == "removed":
            self._index.remove(
                worker_id, [int(h) for h in event.get("sequence_hashes", [])]
            )
        elif etype == "cleared":
            self._index.remove_worker(worker_id)

    def remove_worker(self, worker_id: int) -> None:
        """Drop every entry of a dead worker (reference indexer.rs:382)."""
        self._index.remove_worker(worker_id)

    # -- queries -------------------------------------------------------------

    def find_matches(self, sequence_hashes: Sequence[int]) -> OverlapScores:
        return OverlapScores(scores=self._index.find_matches(sequence_hashes))

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> OverlapScores:
        _, seq_hashes = _hashing.hash_blocks(tokens, self.block_size)
        return self.find_matches(seq_hashes)

    @property
    def num_blocks(self) -> int:
        return self._index.num_blocks

    @property
    def num_workers(self) -> int:
        return self._index.num_workers
