"""KvScheduler: pick the best worker from overlap scores + load metrics.

Rebuild of the reference scheduler (lib/llm/src/kv_router/scheduler.rs:
88-227 select loop + predictive load update, :248-330 DefaultWorkerSelector)
with the identical cost function:

    score  = overlap_blocks * block_size / isl_tokens
    logit  = w_overlap * score
           - w_usage   * gpu_cache_usage_perc
           - w_wait    * num_requests_waiting / max_waiting

argmax wins; ties break randomly.  After a selection the chosen worker's
load is updated predictively (waiting += 1, kv_active_blocks += uncached
blocks) so back-to-back requests spread out before the next metrics scrape
overwrites the estimates.
"""

from __future__ import annotations

import logging
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ...protocols.common import ForwardPassMetrics
from .indexer import OverlapScores


@dataclass
class KvRouterConfig:
    """Cost-function weights (reference kv_router.rs:59-100).

    ``tier_hit_weight`` extends the reference function with the offload
    plane's warmth signal: a worker whose G2/G3 tiers keep serving prefix
    hits onboards a repeat prefix from host RAM (no re-prefill), so it
    beats an otherwise-equal cold worker.  Deliberately smaller than the
    G1 overlap weight -- an HBM-resident prefix still wins outright.

    ``transfer_ms_weight`` is the NetKV-style link-cost term: when a
    selector is built with a ``transfer_cost`` source (the fleet
    observatory's learned per-link model), each candidate's logit is
    charged ``weight * predicted_seconds`` for moving the request's
    uncached KV to it.  0.0 (default) keeps the reference function
    bit-identical."""

    overlap_score_weight: float = 2.0
    gpu_cache_usage_weight: float = 1.0
    waiting_requests_weight: float = 1.0
    tier_hit_weight: float = 0.25
    transfer_ms_weight: float = 0.0


@dataclass
class KVHitRateEvent:
    """Emitted per selection (reference scheduler.rs:31-36)."""

    worker_id: int
    isl_blocks: int
    overlap_blocks: int


class NoEndpointsError(RuntimeError):
    pass


@dataclass
class ProcessedEndpoints:
    """Live per-worker load snapshot (reference scoring.rs:24)."""

    endpoints: Dict[int, ForwardPassMetrics] = field(default_factory=dict)

    def update(self, worker_id: int, metrics: ForwardPassMetrics) -> None:
        self.endpoints[worker_id] = metrics

    def remove(self, worker_id: int) -> None:
        self.endpoints.pop(worker_id, None)


class DefaultWorkerSelector:
    """The reference cost function (scheduler.rs:248-330)."""

    def __init__(
        self,
        config: Optional[KvRouterConfig] = None,
        transfer_cost: Optional[Callable[[int, int], Optional[float]]] = None,
        quarantine: Optional[Callable[[], object]] = None,
    ) -> None:
        self.config = config or KvRouterConfig()
        # (worker_id, uncached_tokens) -> predicted transfer ms, or None
        # while the link has no observations (no penalty applied) -- see
        # FleetObservatory.transfer_cost_source
        self.transfer_cost = transfer_cost
        # worker ids excluded from new placements (fleet straggler
        # quarantine: FleetObservatory.quarantine_source()); a quarantined
        # worker keeps serving what it already has, it just stops winning
        # selections until its step series recovers
        self.quarantine = quarantine

    def select_worker(
        self,
        workers: ProcessedEndpoints,
        overlap: OverlapScores,
        isl_tokens: int,
        block_size: int,
    ) -> Tuple[int, float]:
        """Returns (worker_id, best_logit).  Raises NoEndpointsError when no
        workers are known."""
        if not workers.endpoints:
            raise NoEndpointsError("no endpoints")
        isl_tokens = max(isl_tokens, 1)
        cfg = self.config

        candidates = workers.endpoints
        if self.quarantine is not None:
            try:
                bad = set(self.quarantine())
            except Exception:
                # a broken quarantine feed must not break placement
                from ...runtime.utils import log_throttled

                log_throttled(
                    logging.getLogger("dynamo.kv_router"),
                    "quarantine_source_failed",
                    "quarantine source failed; selecting from all workers",
                    exc_info=True,
                )
                bad = set()
            filtered = {
                wid: m for wid, m in candidates.items() if wid not in bad
            }
            # weight-zero, not hard-fail: if quarantine covers the whole
            # fleet, serving degraded on a known straggler beats serving
            # nothing at all
            if filtered:
                candidates = filtered

        max_waiting = max(
            (m.num_requests_waiting for m in candidates.values()),
            default=0.0,
        )
        best_logit = float("-inf")
        best: List[int] = []
        for worker_id, m in candidates.items():
            score = (
                overlap.scores.get(worker_id, 0) * block_size / isl_tokens
            )
            normalized_waiting = (
                m.num_requests_waiting / max_waiting if max_waiting > 0 else 0.0
            )
            # offload-tier warmth: only workers actually holding parked
            # blocks get the bonus, scaled by how often their tiers hit
            tier_warmth = (
                m.tier_hit_rate if getattr(m, "host_tier_blocks", 0) > 0
                or getattr(m, "disk_tier_blocks", 0) > 0 else 0.0
            )
            logit = (
                cfg.overlap_score_weight * score
                - cfg.gpu_cache_usage_weight * m.gpu_cache_usage_perc
                - cfg.waiting_requests_weight * normalized_waiting
                + cfg.tier_hit_weight * tier_warmth
            )
            if cfg.transfer_ms_weight > 0.0 and self.transfer_cost is not None:
                uncached_tokens = max(
                    isl_tokens
                    - overlap.scores.get(worker_id, 0) * block_size,
                    0,
                )
                pred_ms = self.transfer_cost(worker_id, uncached_tokens)
                if pred_ms is not None:
                    logit -= cfg.transfer_ms_weight * pred_ms / 1000.0
            if logit > best_logit:
                best_logit = logit
                best = [worker_id]
            elif logit == best_logit:
                best.append(worker_id)
        if not best:
            raise NoEndpointsError("no valid workers")
        return (best[0] if len(best) == 1 else random.choice(best)), best_logit


class KvScheduler:
    """Selection + predictive load update (reference scheduler.rs:88-232)."""

    def __init__(
        self,
        block_size: int,
        selector: Optional[DefaultWorkerSelector] = None,
    ) -> None:
        self.block_size = block_size
        self.selector = selector or DefaultWorkerSelector()
        self.workers = ProcessedEndpoints()
        self.hit_rate_events: List[KVHitRateEvent] = []
        # per-selection sink; the KvRouter wires this to publish on the
        # {ns}.events.kv-hit-rate subject (reference scheduler.rs:31-36,104)
        self.on_hit_rate: Optional[Callable[[KVHitRateEvent], None]] = None

    def update_metrics(self, worker_id: int, metrics: ForwardPassMetrics) -> None:
        self.workers.update(worker_id, metrics)

    def remove_worker(self, worker_id: int) -> None:
        self.workers.remove(worker_id)

    def schedule(self, overlap: OverlapScores, isl_tokens: int) -> int:
        worker_id, _ = self.selector.select_worker(
            self.workers, overlap, isl_tokens, self.block_size
        )
        self._process_selection(worker_id, overlap, isl_tokens)
        return worker_id

    def _process_selection(
        self, worker_id: int, overlap: OverlapScores, isl_tokens: int
    ) -> None:
        """Predictive update, overwritten by the next metrics scrape
        (reference scheduler.rs:201-232)."""
        m = self.workers.endpoints.get(worker_id)
        required_blocks = -(-isl_tokens // self.block_size)
        overlap_blocks = overlap.scores.get(worker_id, 0)
        if m is not None:
            m.num_requests_waiting += 1
            m.kv_active_blocks += max(required_blocks - overlap_blocks, 0)
            if m.kv_total_blocks:
                m.gpu_cache_usage_perc = min(
                    m.kv_active_blocks / m.kv_total_blocks, 1.0
                )
        ev = KVHitRateEvent(
            worker_id=worker_id,
            isl_blocks=required_blocks,
            overlap_blocks=overlap_blocks,
        )
        if self.on_hit_rate is not None:
            self.on_hit_rate(ev)
        else:
            # no publisher wired (standalone scheduler): keep a bounded
            # in-memory tail for introspection/tests
            self.hit_rate_events.append(ev)
            if len(self.hit_rate_events) > 1024:
                del self.hit_rate_events[:512]
