"""KV-aware request routing.

Rebuild of the reference KV router (lib/llm/src/kv_router.rs, kv_router/
{indexer,scheduler,publisher,metrics_aggregator}.rs): a global index of
which worker holds which KV blocks, fed by worker events over the hub, a
cost-function scheduler over live worker metrics, and a PushRouter wrapper
that sends each request to the worker with the best prefix overlap.
"""

from .indexer import KvIndexer, KvIndexerSharded, OverlapScores
from .scheduler import KvRouterConfig, KvScheduler, DefaultWorkerSelector
from .publisher import KvEventPublisher, WorkerMetricsPublisher
from .metrics_aggregator import KvMetricsAggregator
from .router import KV_EVENT_SUBJECT, KvRouter, KvPushRouter

__all__ = [
    "DefaultWorkerSelector",
    "KV_EVENT_SUBJECT",
    "KvEventPublisher",
    "KvIndexer",
    "KvIndexerSharded",
    "KvMetricsAggregator",
    "KvPushRouter",
    "KvRouter",
    "KvRouterConfig",
    "KvScheduler",
    "OverlapScores",
    "WorkerMetricsPublisher",
]
