"""LLM service layer: KV-aware routing, preprocessing, HTTP frontend.

TPU-native rebuild of the reference lib/llm crate's service surface
(lib/llm/src: kv_router, preprocessor, backend, http, block_manager) on top
of the dynamo_tpu runtime.
"""
