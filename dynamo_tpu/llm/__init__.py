"""LLM service layer: KV-aware routing, preprocessing, HTTP frontend.

TPU-native rebuild of the reference lib/llm crate's service surface
(lib/llm/src: kv_router, preprocessor, backend, http, block_manager) on top
of the dynamo_tpu runtime.
"""

from .backend import Backend, StopJail
from .preprocessor import OpenAIPreprocessor, PromptFormatter
from .tokenizer import DecodeStream, Tokenizer

__all__ = [
    "Backend",
    "DecodeStream",
    "OpenAIPreprocessor",
    "PromptFormatter",
    "StopJail",
    "Tokenizer",
]
