"""Disaggregated prefill/decode serving.

Reference architecture (examples/llm/components/worker.py:186-235 conditional
disagg decision, prefill_worker.py:139-207 queue consumer + KV write-back,
lib/llm/src/disagg_router.rs:25-90 policy): the decode worker owns the
request and its KV pages; long prefills are shipped to a pool of prefill
workers through a shared hub queue; the prefill worker computes the prompt
KV and writes it back into the decode worker's reserved pages, and decode
resumes.

TPU-native transfer plane (SURVEY.md 5.8): the reference's NIXL one-sided
RDMA write (block_manager/storage/nixl.rs:173, block/transfer.rs) becomes a
peer-to-peer chunked upload over the request plane -- the prefill worker
device_gets its scratch pages and streams the blob directly into the decode
worker's ``kv_deliver`` raw endpoint; the decode worker assembles chunks
into a preallocated host buffer as they arrive and scatters the pages into
HBM.  The hub carries only the queue item; bulk KV never transits it
(honouring the hub contract, runtime/transports/hub.py).  Same handshake
shape as block_manager.rs:119-146.

Wire pieces:

  * queue ``{ns}_prefill_queue``  -- serialized PreprocessedRequest + return
    address (decode component/instance)
  * raw endpoint ``kv_deliver``   -- chunked KV upload straight into the
    decode worker's engine (or an error notification, meta-only)
"""

from __future__ import annotations

import asyncio
import collections
import contextlib
import json
import logging
import os
import time
import weakref
from dataclasses import dataclass
from typing import Any, AsyncIterator, Dict, Iterator, Optional

import numpy as np

from ..protocols.common import PreprocessedRequest
from ..runtime import faults
from ..runtime import metrics as rtm
from ..runtime import tracing
from ..runtime.component import Namespace, PushRouter
from ..runtime.engine import Annotated, AsyncEngineContext, Context
from ..runtime.transports.codec import ChunkAssembler, iter_chunk_frames
from ..runtime.utils import log_throttled

logger = logging.getLogger("dynamo.disagg")

PREFILL_QUEUE_SUFFIX = "_prefill_queue"  # reference {ns}_prefill_queue
KV_DELIVER_ENDPOINT = "kv_deliver"

# Hub key carrying a live DisaggConfig override for a namespace; decode
# workers watch it and hot-reload the routing policy (reference
# disagg_router.rs:38-90 watches the same concept in etcd).
DISAGG_CONF_KEY = "disagg/{ns}/router_conf"


def disagg_conf_key(namespace: str) -> str:
    return DISAGG_CONF_KEY.format(ns=namespace)

# Upload chunk size: large enough to amortize framing, comfortably under
# codec.MAX_FRAME, small enough that assembly overlaps the socket.
KV_CHUNK_BYTES = 8 * 1024 * 1024

# How long the decode side's queue-depth snapshot stays fresh.  One hub RTT
# per window instead of one per long request (the depth only gates a
# heuristic ship/local decision; sub-window staleness is harmless).
DEPTH_CACHE_TTL_S = 0.25

# Process-local decode-engine registry for same-process delivery: when the
# prefill worker and a decode worker share one process (one-host serving,
# colocated engine pairs), the KV blob is handed over as a device-resident
# array -- zero host transit, the TPU analog of NIXL's device-to-device DMA
# (reference block_manager/storage/nixl.rs:173).  Keyed by (hub identity,
# namespace, component, instance) so two hubs in one process cannot collide;
# weak values so a stopped decode engine drops out instead of pinning.
_LOCAL_DECODE: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


def _local_key(namespace: Namespace, component: str, instance_id: int):
    hub = namespace.runtime.hub
    hub_id = (getattr(hub, "host", None), getattr(hub, "port", None))
    if hub_id == (None, None):
        hub_id = id(hub)  # static mode: the hub object is the identity
    return (hub_id, namespace.name, component, int(instance_id))


@dataclass
class DisaggConfig:
    """Reference DisaggRouterConf + queue cap (disagg_router.rs:25-90,
    disagg_router.py)."""

    # prefills at most this long (after prefix-cache credit) run locally
    max_local_prefill_length: int = 512
    # stop shipping prefills when the queue is this deep (prefill pool is
    # saturated; local prefill beats queueing)
    max_prefill_queue_depth: int = 16


class DisaggMetrics:
    """Registry-backed disagg transfer-plane series (runtime/metrics.py);
    the Prometheus face of ``PrefillWorker.delivery_stats`` plus the decode
    side's placement counters.  Catalog: README "Observability"."""

    def __init__(self, registry: Optional[rtm.MetricsRegistry] = None) -> None:
        reg = registry or rtm.default_registry()
        self.transfer_bytes = reg.counter(
            "dynamo_disagg_transfer_bytes",
            "KV bytes delivered prefill->decode",
            ["path"],  # wire | device
        )
        self.transfer_latency = reg.histogram(
            "dynamo_disagg_transfer_seconds",
            "KV delivery (upload or device handoff) latency",
            ["path"],
            buckets=rtm.TRANSFER_LATENCY_BUCKETS,
        )
        self.export_latency = reg.histogram(
            "dynamo_disagg_export_seconds",
            "Prefill KV export latency before the first byte hits the wire",
            buckets=rtm.TRANSFER_LATENCY_BUCKETS,
        )
        self.overlap_ratio = reg.histogram(
            "dynamo_disagg_overlap_ratio",
            "Fraction of export materialization overlapped with transfer "
            "(0 = monolithic, -> 1 = fully pipelined)",
            buckets=rtm.RATIO_BUCKETS,
        )
        self.prefills = reg.counter(
            "dynamo_disagg_prefills",
            "Prefill placement decisions on the decode worker",
            ["target"],  # local | remote
        )
        self.queue_depth = reg.gauge(
            "dynamo_disagg_prefill_queue_depth",
            "Last observed shared prefill queue depth",
        )
        self.breaker_state = reg.gauge(
            "dynamo_disagg_breaker_state",
            "Remote-prefill circuit breaker state "
            "(0 closed, 1 open, 2 half-open)",
        )
        self.breaker_events = reg.counter(
            "dynamo_disagg_breaker_events",
            "Remote-prefill circuit breaker events",
            ["event"],  # open | close | half_open | fallback
        )


class CircuitBreaker:
    """Closed/open/half-open breaker on the remote-prefill path.

    Remote prefill is an *optimization*: when the hub queue is failing
    (enqueue errors) or saturating (enqueue latency past the breach
    threshold), shipping more work there hurts every request.  After
    ``failure_threshold`` consecutive breaches the breaker opens: requests
    run local aggregated prefill with zero hub traffic for ``open_s``.
    Then one half-open probe is let through; success closes the breaker,
    failure re-opens it.

    Env knobs: ``DYN_BREAKER_FAILURES``, ``DYN_BREAKER_OPEN_S``,
    ``DYN_BREAKER_MAX_ENQUEUE_S``."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"
    _STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

    def __init__(
        self,
        failure_threshold: Optional[int] = None,
        open_s: Optional[float] = None,
        max_enqueue_latency_s: Optional[float] = None,
        obs: Optional[DisaggMetrics] = None,
    ) -> None:
        if failure_threshold is None:
            failure_threshold = int(os.environ.get("DYN_BREAKER_FAILURES", "3"))
        if open_s is None:
            open_s = float(os.environ.get("DYN_BREAKER_OPEN_S", "5"))
        if max_enqueue_latency_s is None:
            max_enqueue_latency_s = float(
                os.environ.get("DYN_BREAKER_MAX_ENQUEUE_S", "1")
            )
        self.failure_threshold = failure_threshold
        self.open_s = open_s
        self.max_enqueue_latency_s = max_enqueue_latency_s
        self.state = self.CLOSED
        self.obs = obs
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False

    def _transition(self, state: str) -> None:
        if state == self.state:
            return
        logger.warning(
            "remote-prefill circuit breaker: %s -> %s", self.state, state
        )
        prev = self.state
        self.state = state
        if self.obs is not None:
            self.obs.breaker_state.set(self._STATE_CODE[state])
            self.obs.breaker_events.labels(state).inc()
        if state == self.OPEN:
            # breaker-open is a fleet-health edge: snapshot the flight
            # recorder so the postmortem has the tick ring + queue state
            # from the moment the remote path went dark
            from ..runtime import profiling

            profiling.flight_recorder.snapshot(
                "breaker_open", previous_state=prev
            )

    def allow(self) -> bool:
        """May a request take the remote path right now?"""
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN:
            if time.monotonic() - self._opened_at < self.open_s:
                return False
            self._transition(self.HALF_OPEN)
        # half-open: exactly one probe in flight at a time
        if self._probe_inflight:
            return False
        self._probe_inflight = True
        return True

    def release_probe(self) -> None:
        """The caller took the probe slot but never attempted the remote
        path (admission failed, engine raised): free the slot with NO
        verdict -- only a real enqueue outcome may move the state."""
        self._probe_inflight = False

    def record_success(self) -> None:
        self._probe_inflight = False
        self._consecutive_failures = 0
        if self.state != self.CLOSED:
            self._transition(self.CLOSED)

    def record_failure(self) -> None:
        self._probe_inflight = False
        self._consecutive_failures += 1
        if (
            self.state == self.HALF_OPEN
            or self._consecutive_failures >= self.failure_threshold
        ):
            self._opened_at = time.monotonic()
            self._transition(self.OPEN)


class DisaggRouter:
    """Local-vs-remote prefill policy (reference disagg_router.py:66)."""

    def __init__(self, cfg: Optional[DisaggConfig] = None) -> None:
        self.cfg = cfg or DisaggConfig()

    def prefill_remote(
        self, prefill_length: int, prefix_hit_length: int, queue_depth: int
    ) -> bool:
        effective = prefill_length - prefix_hit_length
        return (
            effective > self.cfg.max_local_prefill_length
            and queue_depth < self.cfg.max_prefill_queue_depth
        )


class PrefillQueue:
    """Hub work queue facade (reference utils/nats_queue.py:24-56)."""

    def __init__(self, namespace: Namespace) -> None:
        self.hub = namespace.runtime.hub
        self.name = f"{namespace.name}{PREFILL_QUEUE_SUFFIX}"

    async def enqueue(self, msg: Dict[str, Any]) -> None:
        await self.hub.queue_push(self.name, json.dumps(msg).encode())

    async def dequeue(self, block: bool = True) -> Optional[Dict[str, Any]]:
        payload = await self.hub.queue_pop(self.name, block=block)
        return json.loads(payload) if payload is not None else None

    async def depth(self) -> int:
        return await self.hub.queue_depth(self.name)


def _queue_deadline_expired(msg: Dict[str, Any]) -> bool:
    """Did this queue item's deadline budget die while it waited?  The
    item carries (remaining_s, wall-clock enqueue time); coarse cross-host
    wall skew is acceptable for multi-second budgets."""
    dl = msg.get("deadline")
    if not isinstance(dl, dict):
        return False
    try:
        elapsed = time.time() - float(dl.get("wall", 0.0))
        return elapsed >= float(dl.get("remaining_s", 0.0))
    except (TypeError, ValueError):
        return False


def _blob_chunks(blob: np.ndarray) -> Iterator[bytes]:
    """Yield the blob's bytes in KV_CHUNK_BYTES slices.

    One ``tobytes`` copy total -- it emits C-order bytes even from a
    non-contiguous view (the batch-export results are slices into the group
    transfer), and bfloat16 arrays don't expose a buffer protocol that
    ``memoryview`` could cast copy-free anyway.  The per-chunk slices are
    zero-copy memoryviews over it.
    """
    yield from _byte_chunks(blob.tobytes())


def _byte_chunks(raw: bytes) -> Iterator[bytes]:
    """KV_CHUNK_BYTES slices over pre-packed bytes (the one chunking
    loop; :func:`_blob_chunks` and the quantized-blob wire form --
    data followed by row scales -- both route through it)."""
    view = memoryview(raw)
    for off in range(0, len(view), KV_CHUNK_BYTES):
        yield view[off : off + KV_CHUNK_BYTES]
    if not len(view):
        yield b""


class DisaggDecodeEngine:
    """Decode-worker serving engine: conditionally ships prefills.

    Serve this (instead of the engine) on the worker's ``generate`` endpoint
    and attach :meth:`kv_deliver_handler` via ``serve_raw`` on the
    ``kv_deliver`` endpoint.
    """

    def __init__(
        self,
        engine,  # JaxEngine (generate / generate_external / deliver_external)
        namespace: Namespace,
        component_name: str,
        instance_id: int,
        cfg: Optional[DisaggConfig] = None,
        block_size: int = 16,
    ) -> None:
        self.engine = engine
        self.namespace = namespace
        self.component_name = component_name
        self.instance_id = instance_id
        self.router = DisaggRouter(cfg)
        self.queue = PrefillQueue(namespace)
        self.block_size = block_size
        # observability: how many prefills went remote vs local
        self.remote_prefills = 0
        self.local_prefills = 0
        self.obs = DisaggMetrics()
        # graceful degradation: enqueue failures / latency breaches open
        # the breaker and prefills run locally instead of hard-failing
        self.breaker = CircuitBreaker(obs=self.obs)
        self._depth_at = -1e9  # monotonic time of the last depth fetch
        self._depth = 0
        # same-process delivery fast path (see _LOCAL_DECODE)
        _LOCAL_DECODE[
            _local_key(namespace, component_name, instance_id)
        ] = engine
        self._conf_watch = None
        self._conf_task: Optional[asyncio.Task] = None

    async def start_config_watch(self) -> None:
        """Hot-reload the routing policy from the hub (reference
        disagg_router.rs:38-90: etcd watch on the router conf).  An operator
        updates the key (``dynamo-tpu disagg-conf``) and every decode
        worker's local/remote threshold follows without restarts."""
        self._conf_watch = await self.namespace.runtime.hub.watch_prefix(
            disagg_conf_key(self.namespace.name)
        )
        for _key, value in self._conf_watch.snapshot:
            self._apply_conf(value)
        self._conf_task = asyncio.create_task(
            self._conf_loop(), name="disagg-conf-watch"
        )

    async def stop_config_watch(self) -> None:
        if self._conf_task is not None:
            self._conf_task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._conf_task
            self._conf_task = None
        if self._conf_watch is not None:
            with contextlib.suppress(Exception):
                await self._conf_watch.close()
            self._conf_watch = None

    async def _conf_loop(self) -> None:
        assert self._conf_watch is not None
        with contextlib.suppress(asyncio.CancelledError):
            while True:
                ev = await self._conf_watch.events.get()
                if ev.type == "put":
                    self._apply_conf(ev.value)

    def _apply_conf(self, raw: bytes) -> None:
        # parse + validate EVERY field before assigning any: a conf update
        # with one good and one malformed field must be ignored whole, not
        # half-applied while the log claims it was ignored
        try:
            d = json.loads(raw)
            updates = {}
            if "max_local_prefill_length" in d:
                updates["max_local_prefill_length"] = int(
                    d["max_local_prefill_length"]
                )
            if "max_prefill_queue_depth" in d:
                updates["max_prefill_queue_depth"] = int(
                    d["max_prefill_queue_depth"]
                )
        except Exception:
            logger.exception("malformed disagg conf update ignored")
            return
        cfg = self.router.cfg
        for field_name, value in updates.items():
            setattr(cfg, field_name, value)
        logger.info(
            "disagg conf reloaded: max_local_prefill_length=%d "
            "max_prefill_queue_depth=%d",
            cfg.max_local_prefill_length, cfg.max_prefill_queue_depth,
        )

    async def _queue_depth(self) -> int:
        """Queue depth with a short-TTL cache: the ship/local heuristic
        tolerates DEPTH_CACHE_TTL_S of staleness; a hub RTT per request on
        the hot path does not (VERDICT r3 weak: disagg.py paid one RTT per
        long request)."""
        now = time.monotonic()
        if now - self._depth_at > DEPTH_CACHE_TTL_S:
            try:
                self._depth = await self.queue.depth()
            except Exception:
                # force local on hub trouble -- and say so: every request
                # silently running local prefill is a capacity regression
                # someone must be able to see (throttled: this fires per
                # request window while the hub is down)
                log_throttled(
                    logger, "disagg-depth",
                    "prefill queue depth unavailable (hub unreachable?); "
                    "forcing local prefill", exc_info=True,
                )
                self._depth = self.router.cfg.max_prefill_queue_depth
            self._depth_at = now
        return self._depth

    async def generate(self, request: Context[Any]) -> AsyncIterator[Annotated]:
        data = request.data
        req = (
            PreprocessedRequest.from_dict(data) if isinstance(data, dict) else data
        )
        prefix_hit_tokens = (
            (req.estimated_prefix_hit_num_blocks or 0) * self.block_size
        )
        effective = len(req.token_ids) - prefix_hit_tokens
        if effective <= self.router.cfg.max_local_prefill_length:
            # short prefill can only run locally: skip the hub round trip
            # for the queue depth on the request hot path
            self.local_prefills += 1
            self.obs.prefills.labels("local").inc()
            return await self.engine.generate(request)
        depth = await self._queue_depth()
        self.obs.queue_depth.set(depth)
        if not self.router.prefill_remote(
            len(req.token_ids), prefix_hit_tokens, depth
        ):
            self.local_prefills += 1
            self.obs.prefills.labels("local").inc()
            return await self.engine.generate(request)
        if not self.breaker.allow():
            # breaker open: the remote path is known-bad right now -- run
            # the prefill locally with zero hub traffic instead of failing
            self.local_prefills += 1
            self.obs.prefills.labels("local").inc()
            self.obs.breaker_events.labels("fallback").inc()
            return await self.engine.generate(request)

        try:
            stream = await self.engine.generate_external(request)
        except BaseException:
            # no remote attempt happened: free a half-open probe slot
            # verdict-free so the breaker can still probe later
            self.breaker.release_probe()
            raise
        if not self.engine.awaiting_external(request.id):
            # admission failed (e.g. prompt > max_seq_len): the stream already
            # carries the error; don't waste a prefill worker on it.  This is
            # NOT a hub-probe outcome -- release the slot without a verdict.
            self.breaker.release_probe()
            self.local_prefills += 1
            self.obs.prefills.labels("local").inc()
            return stream
        msg = {
            "request_id": request.id,
            "request": req.to_dict(),
            "decode_component": self.component_name,
            "decode_instance": self.instance_id,
        }
        # thread the trace context through the hub-queue hop so the
        # prefill worker's spans link under this request's tree
        trace = tracing.wire_context(request.id)
        if trace:
            msg["trace"] = trace
        # the deadline budget rides the queue item too: a job whose budget
        # died on the queue is dropped by the prefill worker, and the
        # decode-side lane fails fast (pages freed) via its error notice
        rem = request.ctx.deadline_remaining()
        if rem is not None:
            msg["deadline"] = {
                "remaining_s": round(rem, 4), "wall": time.time(),
            }
        t0 = time.monotonic()
        try:
            if faults.injector.enabled and faults.injector.should_fire(
                "disagg.enqueue_fail", request.id
            ):
                raise faults.InjectedFault("injected enqueue failure")
            await self.queue.enqueue(msg)
        except Exception as e:  # noqa: BLE001 - degrade, don't hard-fail
            # graceful degradation: unpark the admitted lane (slot + pages
            # released), count the breach, and serve the request with LOCAL
            # aggregated prefill -- an unreachable hub must cost capacity,
            # not correctness
            self.breaker.record_failure()
            self.engine.fail_external(
                request.id, f"failed to enqueue remote prefill: {e}"
            )
            aclose = getattr(stream, "aclose", None)
            if aclose is not None:
                with contextlib.suppress(Exception):
                    await aclose()
            log_throttled(
                logger, "disagg-enqueue",
                "remote prefill enqueue failed (%s); falling back to local "
                "prefill", e,
            )
            self.local_prefills += 1
            self.obs.prefills.labels("local").inc()
            self.obs.breaker_events.labels("fallback").inc()
            return await self.engine.generate(request)
        except BaseException:
            # cancellation mid-enqueue: not a verdict on the hub -- free
            # the probe slot so the breaker can still probe later
            self.breaker.release_probe()
            raise
        if time.monotonic() - t0 > self.breaker.max_enqueue_latency_s:
            self.breaker.record_failure()  # queue-latency breach
        else:
            self.breaker.record_success()
        self.remote_prefills += 1
        self.obs.prefills.labels("remote").inc()
        self._depth += 1  # keep the cached snapshot roughly honest
        return stream

    async def _kv_deliver(
        self,
        hdr: Dict[str, Any],
        chunks: AsyncIterator[bytes],
        ctx: AsyncEngineContext,
    ) -> AsyncIterator[bytes]:
        """Raw ``kv_deliver`` handler: assemble the chunked KV upload into a
        preallocated host buffer and unpark the lane.  Assembly overlaps the
        sender's socket writes; the device scatter happens on the engine's
        executor at the next tick."""
        del ctx
        import jax.numpy as jnp

        meta = hdr.get("meta") or {}
        rid = meta["request_id"]
        if "kv_shards" in meta:
            # the blob is full-width regardless of the sender's mesh (per-
            # shard slices reassemble at export), so a geometry difference
            # is legal -- surfaced for operators diagnosing cross-mesh
            # prefill/decode pools (e.g. tp=8 prefill feeding tp=4 decode)
            local = getattr(
                getattr(self.engine, "kv", None), "shard_geometry", None
            )
            if meta["kv_shards"] != local:
                logger.debug(
                    "cross-mesh KV delivery for %s: prefill shards %s, "
                    "decode shards %s", rid, meta["kv_shards"], local,
                )
        ok = False
        if meta.get("error"):
            # prefill worker reporting failure: fail the parked lane now
            # instead of riding out the delivery timeout
            async for _chunk in chunks:
                pass
            ok = self.engine.fail_external(rid, str(meta["error"]))
        elif meta.get("chunked"):
            ok = await self._kv_deliver_chunked(rid, meta, chunks)
        else:
            dtype = jnp.dtype(meta["dtype"])  # resolves bfloat16 via ml_dtypes
            shape = tuple(int(s) for s in meta["shape"])
            quant = dtype == jnp.dtype(jnp.int8)
            if quant:
                # quantized wire form: data bytes then f32 row scales
                # (kv_cache.pack_quant_blob_bytes); extents derive from
                # (shape, dtype) on both ends
                from ..engine.kv_cache import quant_blob_nbytes

                flat = np.empty((quant_blob_nbytes(shape),), np.uint8)
                buf = None
            else:
                buf = np.empty(shape, dtype)
                flat = buf.view(np.uint8).reshape(-1)
            size = flat.size
            off = 0
            truncated = False
            async for chunk in chunks:
                n = len(chunk)
                if truncated:
                    # drain: stopping mid-upload would stall the connection
                    # read loop on the bounded chunk queue
                    continue
                if off + n > size:
                    truncated = True  # oversized: sender/receiver disagree
                    continue
                flat[off : off + n] = np.frombuffer(chunk, np.uint8)
                off += n
            if truncated or off != size:
                # connection died mid-upload (the chunk iterator terminates
                # on peer loss) or a geometry mismatch: fail fast, don't
                # scatter garbage
                self.engine.fail_external(
                    rid,
                    f"KV delivery truncated: got {off} of {size} bytes",
                )
            else:
                if quant:
                    from ..engine.kv_cache import unpack_quant_blob_bytes

                    # zero-copy: the delivered pair aliases the landing
                    # buffer (multi-GB blobs must not double on receive)
                    buf = unpack_quant_blob_bytes(flat, shape)
                lp_row = meta.get("lp_row")
                ok = self.engine.deliver_external(
                    rid, buf, int(meta["first_token"]),
                    np.asarray(lp_row, np.int32) if lp_row else None,
                )

        yield json.dumps({"ok": ok}).encode()

    async def _kv_deliver_chunked(
        self, rid: str, meta: Dict[str, Any], chunks: AsyncIterator[bytes]
    ) -> bool:
        """Pipelined delivery leg: each wire frame carries (chunk index,
        byte offset, payload); bytes land in a preallocated host buffer as
        they arrive (out-of-order chunks welcome), and every COMPLETED
        layer-group chunk is staged into the engine immediately -- the
        decode-side pages fill while later chunks are still on the wire.
        The engine holds the completion barrier: the first decode step
        waits for every layer plus the final commit."""
        import jax.numpy as jnp

        from ..offload import KVStagingBuffer

        cm = meta["chunked"]
        error: Optional[str] = None
        begun = False
        spans: list = []
        staging = asm = None
        try:
            dtype = jnp.dtype(meta["dtype"])  # resolves bfloat16
            shape = tuple(int(s) for s in meta["shape"])
            spans = [(int(a), int(b)) for a, b in cm["layers"]]
            # spans must tile [0, L) disjointly in order: duplicate or
            # gapped spans could sum to L layers while leaving some layer
            # never written, and the engine's applied-layer barrier counts,
            # it does not track coverage
            expect_lo = 0
            for lo, hi in spans:
                if lo != expect_lo or hi <= lo:
                    raise ValueError(
                        f"layer spans {spans} do not tile [0, {shape[0]})"
                    )
                expect_lo = hi
            if expect_lo != shape[0]:
                raise ValueError(
                    f"layer spans {spans} do not tile [0, {shape[0]})"
                )
            staging = KVStagingBuffer.for_layer_spans(shape, dtype, spans)
            if int(cm.get("total_bytes", staging.flat.size)) != staging.flat.size:
                raise ValueError(
                    f"sender claims {cm['total_bytes']} bytes, geometry "
                    f"holds {staging.flat.size}"
                )
            asm = ChunkAssembler(staging.memoryview, staging.bounds)
            begun = self.engine.begin_external_chunked(rid, shape, str(dtype))
        except (ValueError, KeyError, TypeError) as e:
            error = str(e)
        async for chunk in chunks:
            if error is not None:
                # drain: stopping mid-upload would stall the connection
                # read loop on the bounded chunk queue
                continue
            try:
                for done_idx in asm.add(chunk):
                    if begun:
                        lo, hi = spans[done_idx]
                        # a view into the staging buffer: the completed
                        # chunk's bytes never change again
                        self.engine.deliver_external_chunk(
                            rid, lo, hi, staging.layer_slice(lo, hi)
                        )
            except ValueError as e:
                error = str(e)
        if error is not None:
            return self.engine.fail_external(
                rid, f"chunked KV delivery rejected: {error}"
            )
        if not asm.complete:
            # connection died mid-upload (the chunk iterator terminates on
            # peer loss): fail fast, don't commit a half-filled cache
            return self.engine.fail_external(
                rid,
                f"KV delivery truncated: got {asm.received_bytes} of "
                f"{staging.flat.size} bytes",
            )
        if not begun:
            return False  # request no longer waiting (cancelled/failed)
        lp_row = meta.get("lp_row")
        return self.engine.commit_external_chunked(
            rid,
            int(meta["first_token"]),
            np.asarray(lp_row, np.int32) if lp_row else None,
        )

    def kv_deliver_handler(self):
        """Raw handler for ``Endpoint.serve_raw`` on ``kv_deliver``."""

        async def handler(hdr, chunks, ctx):
            return self._kv_deliver(hdr, chunks, ctx)

        return handler


class PrefillWorker:
    """Queue consumer: prefill remotely-shipped prompts and deliver their KV
    peer-to-peer (reference prefill_worker.py:139-207).

    Drains bursts from the queue into one batched engine dispatch
    (``prefill_export_batch``) and uploads each result concurrently, so N
    queued prefills cost one padded device program + one device->host
    transfer instead of N of each.
    """

    def __init__(
        self,
        engine,
        namespace: Namespace,
        max_batch: int = 8,
        allow_local: bool = True,
        chunked: bool = True,
        layers_per_chunk: Optional[int] = None,
    ) -> None:
        self.engine = engine
        self.namespace = namespace
        self.queue = PrefillQueue(namespace)
        self.max_batch = max_batch
        self.allow_local = allow_local  # same-process device handoff opt-out
        # chunked wire path: stream layer-group chunks as they materialize
        # (export overlaps transfer); False forces the legacy monolithic
        # blob upload.  layers_per_chunk pins the chunk granularity (None =
        # engine default, ~DEFAULT_EXPORT_CHUNKS groups).
        self.chunked = chunked and hasattr(
            engine, "prefill_export_batch_stream"
        )
        if layers_per_chunk is not None and layers_per_chunk <= 0:
            # fail at startup, not per-request inside the export fallback
            raise ValueError(
                f"layers_per_chunk must be positive, got {layers_per_chunk}"
            )
        self.layers_per_chunk = layers_per_chunk
        self.prefills_done = 0
        self.local_deliveries = 0  # same-process device handoffs
        self._task: Optional[asyncio.Task] = None
        self._clients: Dict[str, PushRouter] = {}
        # per-delivery transfer instrumentation (VERDICT r4 #8: separate
        # transfer-plane cost from chip contention): bytes moved, amortized
        # export (dispatch+compute+materialize) ms, upload/handoff ms
        self.delivery_stats: "collections.deque" = collections.deque(
            maxlen=512
        )
        self.obs = DisaggMetrics()

    def _record_delivery(self, row: Dict[str, Any]) -> None:
        """One delivery's stats -> the local deque AND the registry (the
        Prometheus face of the same numbers the bench surface reads)."""
        self.delivery_stats.append(row)
        path = row["path"]
        self.obs.transfer_bytes.labels(path).inc(row["bytes"])
        self.obs.transfer_latency.labels(path).observe(
            row["deliver_ms"] / 1e3
        )
        self.obs.export_latency.observe(row["export_ms"] / 1e3)
        if "overlap_ratio" in row:
            self.obs.overlap_ratio.observe(row["overlap_ratio"])
        # fleet plane: dst-attributed wire transfers feed the observatory's
        # per-(src, dst) link model via the next telemetry snapshot
        if path == "wire" and "dst" in row:
            from ..runtime import telemetry

            telemetry.note_transfer(
                src=self.namespace.runtime.primary_lease,
                dst=row["dst"],
                nbytes=row["bytes"],
                seconds=row["deliver_ms"] / 1e3,
            )

    def transfer_stats(self) -> Dict[str, Any]:
        """Percentile summary of the recorded deliveries (bench/metrics
        surface): separates transfer-plane cost (deliver_ms, bytes) from
        prefill compute (export_ms) per path."""

        def pct(vals, p):
            if not vals:
                return None
            s = sorted(vals)
            return round(s[min(int(p * (len(s) - 1) + 0.5), len(s) - 1)], 2)

        out: Dict[str, Any] = {"deliveries": len(self.delivery_stats)}
        for path in ("wire", "device"):
            rows = [r for r in self.delivery_stats if r["path"] == path]
            if not rows:
                continue
            out[path] = {
                "count": len(rows),
                "bytes_p50": pct([r["bytes"] for r in rows], 0.5),
                "deliver_ms_p50": pct([r["deliver_ms"] for r in rows], 0.5),
                "deliver_ms_p99": pct([r["deliver_ms"] for r in rows], 0.99),
                "export_ms_p50": pct([r["export_ms"] for r in rows], 0.5),
                # chunked-path pipeline metrics (absent rows = legacy path)
                "export_total_ms_p50": pct(
                    [r["export_total_ms"] for r in rows
                     if "export_total_ms" in r], 0.5,
                ),
                "overlap_ratio_p50": pct(
                    [r["overlap_ratio"] for r in rows
                     if "overlap_ratio" in r], 0.5,
                ),
                "chunks_p50": pct(
                    [r["chunks"] for r in rows if "chunks" in r], 0.5
                ),
            }
        return out

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop(), name="prefill-worker")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._task
            self._task = None
        for router in self._clients.values():
            with contextlib.suppress(Exception):
                await router.client.close()
        self._clients.clear()

    async def _loop(self) -> None:
        while True:
            try:
                msg = await self.queue.dequeue(block=True)
                if msg is None:
                    continue
                batch = [msg]
                # burst drain: whatever else is already queued rides the
                # same dispatch (non-blocking pops)
                while len(batch) < self.max_batch:
                    extra = await self.queue.dequeue(block=False)
                    if extra is None:
                        break
                    batch.append(extra)
                await self._process_batch(batch)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("prefill worker failed on a queue batch")
                # a persistent fault (hub down, conn refused) must not spin
                # the loop hot re-raising the same error
                await asyncio.sleep(0.5)

    def _kv_shard_geometry(self):
        """The prefill engine's KV shard geometry (None for unsharded /
        non-JaxEngine backends) -- stamped into delivery meta so a decode
        worker can see which mesh produced the blob."""
        kv = getattr(self.engine, "kv", None)
        return getattr(kv, "shard_geometry", None)

    def _local_engine(self, msg: Dict[str, Any]):
        if not self.allow_local:
            return None
        return _LOCAL_DECODE.get(
            _local_key(
                self.namespace,
                msg["decode_component"],
                int(msg["decode_instance"]),
            )
        )

    async def _process_batch(self, batch: list) -> None:
        # per-item decode: one malformed queue item must fail alone, not
        # discard its batch-mates (their lanes would ride out the delivery
        # timeout holding slots + pages)
        parsed: list = []
        for msg in batch:
            try:
                # validate the return address too: _deliver and the locality
                # probe both dereference it, and one malformed item must not
                # abort the batch
                _ = (msg["decode_component"], int(msg["decode_instance"]))
                if _queue_deadline_expired(msg):
                    # budget died on the queue: skip the prefill, tell the
                    # decode side now so its parked lane frees slot + pages
                    parsed.append(
                        TimeoutError("deadline exceeded before remote prefill")
                    )
                    continue
                parsed.append(PreprocessedRequest.from_dict(msg["request"]))
            except Exception as e:  # noqa: BLE001
                logger.exception("malformed prefill queue item")
                parsed.append(e)
        good = [i for i, p in enumerate(parsed) if not isinstance(p, Exception)]
        results: list = list(parsed)
        # device-resident export when every target decode engine lives in
        # this process (colocated serving): the blob never touches the host
        all_local = bool(good) and all(
            self._local_engine(batch[i]) is not None for i in good
        )
        export_ms_per_item = 0.0
        if good:
            t0 = time.perf_counter()
            try:
                if not all_local and self.chunked:
                    # chunked wire path: streams come back BEFORE any blob
                    # materializes; per-delivery export timing rides the
                    # stream's own first/last-chunk timestamps
                    exported = await self.engine.prefill_export_batch_stream(
                        [parsed[i] for i in good], self.layers_per_chunk
                    )
                    for res in exported:
                        if not isinstance(res, Exception):
                            res.started_at = t0
                else:
                    exported = await self.engine.prefill_export_batch(
                        [parsed[i] for i in good], device=all_local
                    )
            except Exception as e:  # noqa: BLE001 - engine-wide failure
                logger.exception("prefill export batch failed")
                exported = [e] * len(good)
            export_ms_per_item = (
                (time.perf_counter() - t0) * 1000.0 / max(len(good), 1)
            )
            for i, res in zip(good, exported):
                results[i] = res
        # deliver concurrently: uploads to distinct decode workers ride
        # distinct connections; to the same worker they multiplex
        await asyncio.gather(
            *[
                self._deliver_traced(msg, res, export_ms_per_item)
                for msg, res in zip(batch, results)
            ],
            return_exceptions=True,
        )

    async def _deliver_traced(
        self, msg: Dict[str, Any], result: Any, export_ms: float
    ) -> None:
        """Delivery wrapped in a span linked (via the trace context the
        decode worker put in the queue item) under the originating
        request's tree -- the 'prefill worker' leg of the frontend ->
        router -> prefill -> decode timeline."""
        parent = None
        if tracing.collector.enabled:
            parent = tracing.TraceContext.from_wire(msg.get("trace"))
        with tracing.span(
            "prefill.deliver",
            str(msg.get("request_id", "")),
            parent=parent,
            error=isinstance(result, Exception),
        ):
            await self._deliver(msg, result, export_ms)

    async def _deliver(
        self, msg: Dict[str, Any], result: Any, export_ms: float = 0.0
    ) -> None:
        rid = msg["request_id"]
        if isinstance(result, Exception):
            # tell the decode worker so its parked lane fails immediately
            # (the decode-side timeout is only the backstop for lost items)
            logger.error("prefill failed for request %s: %s", rid, result)
            local = self._local_engine(msg)
            if local is not None:
                local.fail_external(rid, str(result))
                return
            try:
                await self._upload(
                    msg, {"request_id": rid, "error": str(result)}, iter(())
                )
            except Exception:
                # the lane now rides out the delivery timeout; leave a trace
                logger.exception(
                    "error notification failed for request %s", rid
                )
            return
        if not isinstance(result, tuple):
            # chunked export stream: layer-group chunks go on the wire as
            # they materialize
            await self._deliver_stream(msg, result)
            return
        blob, row = result  # row: packed [2 + 2N] (token | logprob | tops)
        first = int(np.asarray(row).reshape(-1)[0])
        lp_row = [int(x) for x in np.asarray(row).reshape(-1)]
        local = self._local_engine(msg)
        # lazy: QuantKV lives with the (jax-importing) engine package, and
        # chip-free stacks import this module without jax
        from ..engine.kv_cache import QuantKV, blob_to_host

        quant = isinstance(blob, QuantKV)
        t0 = time.perf_counter()
        if local is not None and not isinstance(blob, np.ndarray):
            # same-process handoff: the device-resident blob (or quantized
            # pair) goes straight into the decode engine's delivery queue;
            # the scatter is a device-to-device copy at its next tick
            self.local_deliveries += 1
            local.deliver_external(
                rid, blob, first, np.asarray(lp_row, np.int32)
            )
            nbytes = (
                blob.nbytes
                if quant
                else int(np.prod(blob.shape)) * blob.dtype.itemsize
            )
            path = "device"
        else:
            meta = {
                "request_id": rid,
                "dtype": str(blob.dtype),
                "shape": list(blob.shape),
                "first_token": first,
                "lp_row": lp_row,
            }
            shards = self._kv_shard_geometry()
            if shards is not None:
                meta["kv_shards"] = shards
            if quant:
                # int8 export: the wire carries data bytes then the f32
                # row scales (the pack_quant_blob_bytes layout, streamed
                # as two buffer-protocol views so no (q+s)-sized concat
                # buffer ever materializes); the receiver re-derives both
                # extents from (shape, dtype)
                import itertools

                blob = blob_to_host(blob)
                q_arr = np.ascontiguousarray(blob.q)
                s_arr = np.ascontiguousarray(blob.s, np.float32)
                chunks_iter = itertools.chain(
                    _byte_chunks(q_arr.reshape(-1).view(np.uint8)),
                    _byte_chunks(s_arr.reshape(-1).view(np.uint8)),
                )
                nbytes = q_arr.nbytes + s_arr.nbytes
            else:
                if not isinstance(blob, np.ndarray):
                    # mixed batch: a device export targeting a remote
                    # decode worker still ships over the wire
                    blob = np.asarray(blob)
                chunks_iter = _blob_chunks(blob)
                nbytes = blob.nbytes
            try:
                if faults.injector.enabled:
                    await faults.injector.maybe_delay("disagg.slow_export", rid)
                await self._upload(msg, meta, chunks_iter)
            except Exception:
                logger.exception("KV delivery failed for request %s", rid)
                raise
            path = "wire"
        self._record_delivery(
            {
                "path": path,
                "dst": int(msg["decode_instance"]),
                "bytes": nbytes,
                "export_ms": export_ms,
                "deliver_ms": (time.perf_counter() - t0) * 1000.0,
            }
        )
        self.prefills_done += 1
        prompt_tokens = len((msg.get("request") or {}).get("token_ids") or ())
        logger.info(
            "prefilled %d tokens for %s -> %s/%d",
            # the true prompt length, not the page-padded blob capacity
            prompt_tokens or blob.shape[2] * blob.shape[3], rid,
            msg["decode_component"], int(msg["decode_instance"]),
        )

    async def _deliver_stream(self, msg: Dict[str, Any], stream) -> None:
        """Upload a chunked export: frame each layer-group chunk with its
        index + absolute byte offset (codec.encode_chunk_frame) and send it
        the moment it lands on host -- chunk i rides the socket while chunk
        i+1 is still in device->host flight.  A same-process decode target
        takes the wire too: the chunked path exists to pipeline the host
        transit that the device handoff never pays."""
        rid = msg["request_id"]
        row = np.asarray(stream.row).reshape(-1)
        bounds = stream.chunk_bounds
        meta = {
            "request_id": rid,
            "dtype": stream.dtype,
            "shape": list(stream.shape),
            "first_token": int(row[0]),
            "lp_row": [int(x) for x in row],
            "chunked": {
                "layers": [list(s) for s in stream.spans],
                "total_bytes": stream.nbytes,
            },
        }
        if stream.shards is not None:
            # exporting-pool shard geometry (tp: kv heads sharded); blobs
            # are full-width -- provenance for the decode-side check
            meta["kv_shards"] = stream.shards

        async def frames() -> AsyncIterator[bytes]:
            from ..engine.kv_cache import QuantKV, pack_quant_blob_bytes

            truncated = False
            async for idx, _lo, _hi, part in stream.chunks():
                if truncated:
                    continue  # drain the export without sending (fault)
                if isinstance(part, QuantKV):
                    # quantized slab: int8 data then f32 row scales --
                    # matches the receiver's quant staging-buffer bounds
                    raw = pack_quant_blob_bytes(part)
                else:
                    raw = part.tobytes()  # C-order bytes of the layer slab
                for frame in iter_chunk_frames(
                    idx, bounds[idx][0], raw, KV_CHUNK_BYTES
                ):
                    yield frame
                if faults.injector.enabled and faults.injector.should_fire(
                    "disagg.chunk_truncate", rid
                ):
                    # simulated mid-transfer loss: the receiver's assembler
                    # must detect the truncation and fail the lane fast
                    truncated = True

        t0 = time.perf_counter()
        try:
            if faults.injector.enabled:
                await faults.injector.maybe_delay("disagg.slow_export", rid)
            await self._upload(msg, meta, frames())
        except Exception:
            logger.exception("KV delivery failed for request %s", rid)
            raise
        started = stream.started_at or t0
        first_at = stream.first_ready_at or started
        last_at = stream.last_ready_at or first_at
        export_first = (first_at - started) * 1000.0
        export_total = (last_at - started) * 1000.0
        self._record_delivery(
            {
                "path": "wire",
                "dst": int(msg["decode_instance"]),
                "bytes": stream.nbytes,
                # export-before-first-byte: the number the chunked pipeline
                # exists to shrink (the legacy path's export_ms covers the
                # WHOLE blob's dispatch+compute+materialize)
                "export_ms": export_first,
                "export_total_ms": export_total,
                # fraction of export materialization that overlapped wire
                # transfer (0 = monolithic behavior, -> 1 = fully pipelined)
                "overlap_ratio": (
                    1.0 - export_first / export_total
                    if export_total > 0 else 0.0
                ),
                "chunks": len(stream.spans),
                "deliver_ms": (time.perf_counter() - t0) * 1000.0,
            }
        )
        self.prefills_done += 1
        prompt_tokens = len((msg.get("request") or {}).get("token_ids") or ())
        logger.info(
            "prefilled %d tokens for %s -> %s/%d (%d chunks)",
            prompt_tokens, rid, msg["decode_component"],
            int(msg["decode_instance"]), len(stream.spans),
        )

    async def _upload(
        self, msg: Dict[str, Any], meta: Dict[str, Any], chunks
    ) -> None:
        router = await self._router_for(msg["decode_component"])
        ctx = AsyncEngineContext(meta["request_id"])
        stream = await router.direct_upload(
            int(msg["decode_instance"]), meta["request_id"], meta, chunks, ctx
        )
        async for _ack in stream:
            pass  # single-ack stream

    async def _router_for(self, component: str) -> PushRouter:
        router = self._clients.get(component)
        if router is None:
            client = await (
                self.namespace.component(component)
                .endpoint(KV_DELIVER_ENDPOINT)
                .client()
            )
            router = PushRouter(client)
            self._clients[component] = router
        return router
