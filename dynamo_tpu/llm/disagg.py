"""Disaggregated prefill/decode serving v1.

Reference architecture (examples/llm/components/worker.py:186-235 conditional
disagg decision, prefill_worker.py:139-207 queue consumer + KV write-back,
lib/llm/src/disagg_router.rs:25-90 policy): the decode worker owns the
request and its KV pages; long prefills are shipped to a pool of prefill
workers through a shared hub queue; the prefill worker computes the prompt
KV and writes it back into the decode worker's reserved pages, and decode
resumes.

TPU-native transfer plane (SURVEY.md 5.8): the reference's NIXL one-sided
RDMA write becomes an explicit blockset export/import -- the prefill worker
device_gets its scratch pages, stages the blob in the hub object store, and
notifies the decode worker over the data plane (``kv_deliver`` endpoint);
the decode worker scatters the pages into HBM and unparks the lane.  Same
handshake shape as block_manager.rs:119-146, host-staged.

Wire pieces:

  * queue ``{ns}_prefill_queue``  -- serialized PreprocessedRequest + return
    address (decode component/instance)
  * object  ``kvx/{request_id}``  -- the raw KV blob (deleted after import)
  * endpoint ``kv_deliver``       -- completion notification into the
    decode worker's engine
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
from dataclasses import dataclass
from typing import Any, AsyncIterator, Dict, Optional

import numpy as np

from ..protocols.common import PreprocessedRequest
from ..runtime.component import Namespace, PushRouter
from ..runtime.engine import Annotated, Context, EngineFn, ResponseStream

logger = logging.getLogger("dynamo.disagg")

PREFILL_QUEUE_SUFFIX = "_prefill_queue"  # reference {ns}_prefill_queue
KV_DELIVER_ENDPOINT = "kv_deliver"
KV_OBJ_PREFIX = "kvx"


@dataclass
class DisaggConfig:
    """Reference DisaggRouterConf + queue cap (disagg_router.rs:25-90,
    disagg_router.py)."""

    # prefills at most this long (after prefix-cache credit) run locally
    max_local_prefill_length: int = 512
    # stop shipping prefills when the queue is this deep (prefill pool is
    # saturated; local prefill beats queueing)
    max_prefill_queue_depth: int = 16


class DisaggRouter:
    """Local-vs-remote prefill policy (reference disagg_router.py:66)."""

    def __init__(self, cfg: Optional[DisaggConfig] = None) -> None:
        self.cfg = cfg or DisaggConfig()

    def prefill_remote(
        self, prefill_length: int, prefix_hit_length: int, queue_depth: int
    ) -> bool:
        effective = prefill_length - prefix_hit_length
        return (
            effective > self.cfg.max_local_prefill_length
            and queue_depth < self.cfg.max_prefill_queue_depth
        )


class PrefillQueue:
    """Hub work queue facade (reference utils/nats_queue.py:24-56)."""

    def __init__(self, namespace: Namespace) -> None:
        self.hub = namespace.runtime.hub
        self.name = f"{namespace.name}{PREFILL_QUEUE_SUFFIX}"

    async def enqueue(self, msg: Dict[str, Any]) -> None:
        await self.hub.queue_push(self.name, json.dumps(msg).encode())

    async def dequeue(self, block: bool = True) -> Optional[Dict[str, Any]]:
        payload = await self.hub.queue_pop(self.name, block=block)
        return json.loads(payload) if payload is not None else None

    async def depth(self) -> int:
        return await self.hub.queue_depth(self.name)


def _encode_blob(blob: np.ndarray) -> Dict[str, Any]:
    return {"dtype": str(blob.dtype), "shape": list(blob.shape)}


def _decode_blob(raw: bytes, meta: Dict[str, Any]) -> np.ndarray:
    import jax.numpy as jnp

    dtype = jnp.dtype(meta["dtype"])  # resolves bfloat16 via ml_dtypes
    return np.frombuffer(raw, dtype=dtype).reshape(meta["shape"])


class DisaggDecodeEngine:
    """Decode-worker serving engine: conditionally ships prefills.

    Serve this (instead of the engine) on the worker's ``generate`` endpoint
    and attach :meth:`deliver_handler` on the ``kv_deliver`` endpoint.
    """

    def __init__(
        self,
        engine,  # JaxEngine (generate / generate_external / deliver_external)
        namespace: Namespace,
        component_name: str,
        instance_id: int,
        cfg: Optional[DisaggConfig] = None,
        block_size: int = 16,
    ) -> None:
        self.engine = engine
        self.namespace = namespace
        self.component_name = component_name
        self.instance_id = instance_id
        self.router = DisaggRouter(cfg)
        self.queue = PrefillQueue(namespace)
        self.block_size = block_size
        # observability: how many prefills went remote vs local
        self.remote_prefills = 0
        self.local_prefills = 0

    async def generate(self, request: Context[Any]) -> AsyncIterator[Annotated]:
        data = request.data
        req = (
            PreprocessedRequest.from_dict(data) if isinstance(data, dict) else data
        )
        prefix_hit_tokens = (
            (req.estimated_prefix_hit_num_blocks or 0) * self.block_size
        )
        effective = len(req.token_ids) - prefix_hit_tokens
        if effective <= self.router.cfg.max_local_prefill_length:
            # short prefill can only run locally: skip the hub round trip
            # for the queue depth on the request hot path
            self.local_prefills += 1
            return await self.engine.generate(request)
        try:
            depth = await self.queue.depth()
        except Exception:
            depth = self.router.cfg.max_prefill_queue_depth  # force local
        if not self.router.prefill_remote(
            len(req.token_ids), prefix_hit_tokens, depth
        ):
            self.local_prefills += 1
            return await self.engine.generate(request)

        stream = await self.engine.generate_external(request)
        if not self.engine.awaiting_external(request.id):
            # admission failed (e.g. prompt > max_seq_len): the stream already
            # carries the error; don't waste a prefill worker on it
            self.local_prefills += 1
            return stream
        self.remote_prefills += 1
        try:
            await self.queue.enqueue(
                {
                    "request_id": request.id,
                    "request": req.to_dict(),
                    "decode_component": self.component_name,
                    "decode_instance": self.instance_id,
                }
            )
        except Exception as e:
            # unpark the admitted lane now -- don't hold its slot + pages
            # hostage to the delivery timeout for a job that never shipped
            self.engine.fail_external(
                request.id, f"failed to enqueue remote prefill: {e}"
            )
            raise
        return stream

    async def _deliver(self, request: Context[Any]) -> AsyncIterator[Annotated]:
        d = request.data or {}
        rid = d["request_id"]
        ok = False
        if d.get("error"):
            # prefill worker reporting failure: fail the parked lane now
            # instead of riding out the delivery timeout
            ok = self.engine.fail_external(rid, str(d["error"]))
        else:
            obj = d["obj"]
            raw = await self.namespace.runtime.hub.obj_get(obj)
            if raw is not None:
                blob = _decode_blob(raw, d["meta"])
                ok = self.engine.deliver_external(
                    rid, blob, int(d["first_token"])
                )
                await self.namespace.runtime.hub.obj_del(obj)
            else:
                logger.error("kv blob %s missing for request %s", obj, rid)
                self.engine.fail_external(
                    rid, f"prefilled KV blob {obj} missing from object store"
                )

        async def one() -> AsyncIterator[Annotated]:
            yield Annotated.from_data({"ok": ok})

        return ResponseStream(request.ctx, one())

    def deliver_handler(self):
        """AsyncEngine for the ``kv_deliver`` endpoint."""
        return EngineFn(self._deliver)


class PrefillWorker:
    """Queue consumer: prefill remotely-shipped prompts and deliver their KV
    (reference prefill_worker.py:139-207)."""

    def __init__(self, engine, namespace: Namespace) -> None:
        self.engine = engine
        self.namespace = namespace
        self.queue = PrefillQueue(namespace)
        self.prefills_done = 0
        self._task: Optional[asyncio.Task] = None
        self._clients: Dict[str, PushRouter] = {}

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop(), name="prefill-worker")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._task
            self._task = None
        for router in self._clients.values():
            with contextlib.suppress(Exception):
                await router.client.close()
        self._clients.clear()

    async def _loop(self) -> None:
        while True:
            try:
                msg = await self.queue.dequeue(block=True)
                if msg is None:
                    continue
                await self._process(msg)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("prefill worker failed on a queue item")
                # a persistent fault (hub down, conn refused) must not spin
                # the loop hot re-raising the same error
                await asyncio.sleep(0.5)

    async def _process(self, msg: Dict[str, Any]) -> None:
        rid = msg["request_id"]
        req = PreprocessedRequest.from_dict(msg["request"])
        try:
            blob, first = await self.engine.prefill_export(req)
        except Exception as e:
            # tell the decode worker so its parked lane fails immediately
            # (the decode-side timeout is only the backstop for lost items)
            logger.exception("prefill_export failed for request %s", rid)
            await self._notify(msg, {"request_id": rid, "error": str(e)})
            return
        obj = f"{KV_OBJ_PREFIX}/{rid}"
        hub = self.namespace.runtime.hub
        await hub.obj_put(obj, np.ascontiguousarray(blob).tobytes())
        try:
            await self._notify(
                msg,
                {
                    "request_id": rid,
                    "obj": obj,
                    "meta": _encode_blob(blob),
                    "first_token": first,
                },
            )
        except Exception:
            # undelivered blob must not sit in the hub forever (the decode
            # side only deletes what it imports)
            with contextlib.suppress(Exception):
                await hub.obj_del(obj)
            raise
        self.prefills_done += 1
        logger.info(
            "prefilled %d tokens for %s -> %s/%d",
            len(req.token_ids), rid,
            msg["decode_component"], int(msg["decode_instance"]),
        )

    async def _notify(self, msg: Dict[str, Any], payload: Dict[str, Any]) -> None:
        router = await self._router_for(msg["decode_component"])
        stream = await router.direct(
            Context.new(payload), int(msg["decode_instance"])
        )
        async for _item in stream:
            pass  # single-ack stream

    async def _router_for(self, component: str) -> PushRouter:
        router = self._clients.get(component)
        if router is None:
            client = await (
                self.namespace.component(component)
                .endpoint(KV_DELIVER_ENDPOINT)
                .client()
            )
            router = PushRouter(client)
            self._clients[component] = router
        return router
