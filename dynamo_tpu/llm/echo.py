"""Echo engines: deterministic no-model backends for wiring tests.

Reference parity: launch/dynamo-run echo engines (``out=echo_core`` echoes
token ids through the full preprocessor/backend pipeline, ``out=echo_full``
echoes the rendered prompt text).  Useful for driving the HTTP/router/
pipeline stack with zero model weight and exact, predictable output.
"""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator

from ..protocols.common import FinishReason, LLMEngineOutput, PreprocessedRequest
from ..runtime.engine import Annotated, AsyncEngine, Context, ResponseStream


class EchoEngineCore(AsyncEngine):
    """Token-level echo: streams the prompt's token ids back one at a time
    (capped by max_tokens), then finishes with STOP.  Sits exactly where
    JaxEngine sits, so the preprocessor -> backend -> detokenize path runs
    unchanged."""

    def __init__(self, delay_ms: float = 0.0) -> None:
        self.delay_ms = delay_ms

    async def stop(self) -> None:
        """Lifecycle parity with the real engines (callers stop() whatever
        _make_engine built)."""

    async def generate(self, request: Context[Any]) -> AsyncIterator[Annotated]:
        data = request.data
        req = (
            PreprocessedRequest.from_dict(data) if isinstance(data, dict) else data
        )
        ctx = request.ctx
        tokens = list(req.token_ids)
        cap = req.stop_conditions.max_tokens
        if cap is not None:
            tokens = tokens[:cap]
        delay = self.delay_ms / 1e3

        async def gen() -> AsyncIterator[Annotated]:
            for t in tokens:
                if ctx.is_stopped():
                    yield Annotated.from_data(
                        LLMEngineOutput.finished(FinishReason.CANCELLED).to_dict()
                    )
                    return
                if delay:
                    await asyncio.sleep(delay)
                yield Annotated.from_data(
                    LLMEngineOutput(token_ids=[t]).to_dict()
                )
            yield Annotated.from_data(
                LLMEngineOutput.finished(FinishReason.STOP).to_dict()
            )

        return ResponseStream(ctx, gen())
