"""Cross-worker prefix onboarding (KVBM G4): import another worker's
registered KV blocks instead of recomputing them.

Reference block_manager.rs:119-146: any worker can export a blockset and
any worker can import it, turning per-worker prefix caches into
cluster-wide cache capacity.  Mechanism here:

  * every worker serves ``kv_export`` (raw endpoint): given a chain of
    sequence hashes, it streams back the longest resident prefix as
    (meta, blob) frame pairs -- G1 pages slice on device in one bundled
    transfer, offload tiers fill the tail (engine.export_blocks);
  * the KV router already knows who holds what (its index built the
    overlap scores); when the *best-cost* worker is not the *best-overlap*
    worker, it stamps the donor's instance + block count into the request
    metadata (``prefix_donor``);
  * the serving wrapper on the chosen worker fetches the missing blocks
    from the donor into the engine's host offload tier **before** engine
    admission -- the scheduler's existing offload-onboarding path
    (scheduler.py _match_prefix G2 chain) then scatters them into HBM and
    registers them exactly as if they had been evicted locally.  No new
    scheduler states; the tested onboard path is the only onboard path.

The import staging uses the host tier (G2) via the engine's
``KVOffloadEngine`` (every put rides its dedicated offload thread), so
onboarding requires the offload plane to be armed -- either
``host_offload_blocks > 0`` or ``DYN_KV_OFFLOAD``.
"""

from __future__ import annotations

import json
import logging
from typing import Any, AsyncIterator, Dict, List, Optional

import numpy as np

from ..offload import BlockMeta, KVStagingBuffer
from ..runtime.component import Namespace, PushRouter
from ..runtime.engine import Annotated, AsyncEngineContext, Context
from ..runtime.transports.codec import ChunkAssembler, encode_chunk_frame

logger = logging.getLogger("dynamo.prefix_onboard")

KV_EXPORT_ENDPOINT = "kv_export"
DONOR_META_KEY = "prefix_donor"  # request metadata: {"instance": i, "blocks": n}

# Block blobs ride the wire in chunk frames of this size: big models' blocks
# can exceed codec.MAX_FRAME as one payload, and the importer stages each
# block incrementally instead of buffering whole frames (same framing as the
# disagg KV delivery, runtime/transports/codec.py).
EXPORT_CHUNK_BYTES = 8 * 1024 * 1024


def kv_export_handler(engine):
    """Raw handler for the ``kv_export`` endpoint: meta carries the hash
    chain; the response alternates JSON-meta frames and the block's chunk
    frames (index + offset framed, codec.encode_chunk_frame)."""

    async def handler(
        hdr: Dict[str, Any],
        chunks: AsyncIterator[bytes],
        ctx: AsyncEngineContext,
    ) -> AsyncIterator[bytes]:
        del ctx
        async for _chunk in chunks:
            pass  # no request body expected

        async def gen() -> AsyncIterator[bytes]:
            from ..engine.kv_cache import QuantKV, pack_quant_blob_bytes

            hashes = [int(h) for h in (hdr.get("meta") or {}).get("hashes", [])]
            found = await engine.export_blocks(hashes)
            for seq_hash, blob, meta in found:
                if isinstance(blob, QuantKV):
                    # quantized donor block: int8 data then f32 row scales
                    # -- the importer re-derives both extents from
                    # (shape, dtype), and the scales travel with the bytes
                    raw = pack_quant_blob_bytes(blob)
                else:
                    raw = np.asarray(blob).tobytes()  # C-order bytes
                yield json.dumps(
                    {
                        "seq_hash": int(seq_hash),
                        "dtype": str(blob.dtype),
                        "shape": list(blob.shape),
                        "chunk_bytes": EXPORT_CHUNK_BYTES,
                        "total_bytes": len(raw),
                        "meta": meta,
                    }
                ).encode()
                view = memoryview(raw)
                # zero-byte blobs emit no chunk frames: the importer's
                # assembler is already complete at meta time.  Chunk i
                # covers bytes [i*CB, (i+1)*CB) -- the same bounds
                # KVStagingBuffer.for_byte_chunks derives on the importer.
                for idx, off in enumerate(
                    range(0, len(view), EXPORT_CHUNK_BYTES)
                ):
                    yield encode_chunk_frame(
                        idx, off, view[off : off + EXPORT_CHUNK_BYTES]
                    )

        return gen()

    return handler


class PrefixOnboardEngine:
    """Serving wrapper: fetch donor blocks into the host tier, then delegate.

    Sits between the endpoint and the engine (compose freely with
    DisaggDecodeEngine -- onboarding concerns the prefix, disagg the
    remainder of the prefill)."""

    def __init__(
        self,
        inner,  # the serving engine to delegate to (engine or disagg wrapper)
        namespace: Namespace,
        component: str,
        engine=None,  # the JaxEngine owning pool/offload (defaults to inner)
    ) -> None:
        self.inner = inner
        self.engine = engine if engine is not None else inner
        self.namespace = namespace
        self.component = component
        self._export_router: Optional[PushRouter] = None
        self.onboarded_blocks = 0  # observability
        self.failed_fetches = 0

    async def _router(self) -> PushRouter:
        if self._export_router is None:
            client = await (
                self.namespace.component(self.component)
                .endpoint(KV_EXPORT_ENDPOINT)
                .client()
            )
            self._export_router = PushRouter(client)
        return self._export_router

    async def close(self) -> None:
        if self._export_router is not None:
            await self._export_router.client.close()
            self._export_router = None

    async def generate(self, request: Context[Any]) -> AsyncIterator[Annotated]:
        donor = (request.metadata or {}).get(DONOR_META_KEY)
        if donor and self.engine.offload is not None:
            try:
                await self._onboard(request, donor)
            except Exception:
                # onboarding is an optimization: a donor failure must never
                # fail the request -- it just recomputes the prefix
                self.failed_fetches += 1
                logger.exception("prefix onboarding failed; recomputing")
        return await self.inner.generate(request)

    async def _onboard(self, request: Context[Any], donor: Dict[str, Any]) -> None:
        from ..tokens.hashing import hash_blocks

        data = request.data
        token_ids = (
            data.token_ids
            if hasattr(data, "token_ids")
            else list((data or {}).get("token_ids") or [])
        )
        block_size = self.engine.sched.block_size
        n = min(int(donor.get("blocks", 0)), max(0, (len(token_ids) - 1) // block_size))
        if n <= 0:
            return
        _, seq_hashes = hash_blocks(token_ids, block_size)
        seq_hashes = seq_hashes[:n]
        pool = self.engine.kv.allocator
        offload = self.engine.offload_engine
        # only fetch what neither HBM nor the local tiers already hold; the
        # donor chain must stay contiguous, so cut at the first local hit
        # gap is fine -- we request the full chain and the donor returns its
        # own longest prefix
        missing = [
            h
            for h in seq_hashes
            if not (
                getattr(pool, "is_registered", lambda _h: False)(h)
                or offload.contains(h)
            )
        ]
        if not missing:
            return
        if donor.get("source") == "remote":
            # the donor is the shared G4 object store, not a peer worker:
            # fetch over the offload engine's remote tier instead of the
            # kv_export endpoint
            await self._onboard_remote(missing)
            return
        router = await self._router()
        stream = await router.direct_raw(
            int(donor["instance"]),
            request.id,
            {"hashes": [int(h) for h in missing]},
            b"",
            AsyncEngineContext(request.id),
        )
        import jax.numpy as jnp

        pending_meta: Optional[Dict[str, Any]] = None
        staging: Optional[KVStagingBuffer] = None
        asm: Optional[ChunkAssembler] = None
        fetched = 0

        def _store() -> None:
            nonlocal fetched, pending_meta, staging, asm
            # the host-ring copy (and any disk demotion it cascades into)
            # runs on the offload engine's thread, never this event loop;
            # payload() unpacks quantized wire bytes into the (data,
            # scales) pair the tiers store
            offload.submit_put(
                int(pending_meta["seq_hash"]),
                staging.payload(),
                BlockMeta.from_dict(pending_meta["meta"]),
            )
            fetched += 1
            pending_meta = staging = asm = None

        async for frame in stream:
            if pending_meta is None:
                pending_meta = json.loads(frame)
                dtype = jnp.dtype(pending_meta["dtype"])
                if "chunk_bytes" not in pending_meta:
                    # legacy donor: the whole blob rides the next frame
                    staging = asm = None
                    continue
                staging = KVStagingBuffer.for_byte_chunks(
                    pending_meta["shape"], dtype,
                    int(pending_meta["chunk_bytes"]),
                )
                asm = ChunkAssembler(staging.memoryview, staging.bounds)
                if asm.complete:  # zero-byte blob: no chunk frames follow
                    _store()
            elif asm is None:
                if jnp.dtype(pending_meta["dtype"]) == jnp.dtype(jnp.int8):
                    from ..engine.kv_cache import unpack_quant_blob_bytes

                    blob = unpack_quant_blob_bytes(
                        frame, pending_meta["shape"]
                    )
                else:
                    blob = np.frombuffer(
                        frame, jnp.dtype(pending_meta["dtype"])
                    ).reshape(pending_meta["shape"])
                offload.submit_put(
                    int(pending_meta["seq_hash"]),
                    blob,
                    BlockMeta.from_dict(pending_meta["meta"]),
                )
                fetched += 1
                pending_meta = None
            else:
                asm.add(frame)
                if asm.complete:
                    _store()
        if pending_meta is not None:
            # stream ended mid-block (donor died): the partial block is
            # dropped; everything already stored still onboards
            logger.warning(
                "donor stream ended mid-block for %x; partial block dropped",
                int(pending_meta.get("seq_hash", 0)),
            )
        self.onboarded_blocks += fetched
        if fetched:
            # barrier: the submitted puts must be resident before the
            # engine's admission-time tier lookup runs (off-loop wait; the
            # offload thread's queue is at most this request's blocks deep)
            import asyncio

            await asyncio.to_thread(offload.drain)
            logger.info(
                "onboarded %d prefix blocks from donor %x",
                fetched, int(donor["instance"]),
            )

    async def _onboard_remote(self, missing: List[int]) -> None:
        """Fetch missing prefix blocks from the G4 store into the host
        tier.  Fetches ride the kv-remote thread (futures awaited here);
        the chain cuts at the first miss -- the scheduler's prefix match
        stops at the first hole, so trailing blocks past a gap are
        useless."""
        import asyncio

        offload = self.engine.offload_engine
        remote = getattr(offload, "remote", None)
        if remote is None:
            return
        fetched = 0
        for h in missing:
            got = await asyncio.wrap_future(remote.fetch(int(h)))
            if got is None:
                self.failed_fetches += 1
                break
            blob, meta = got
            offload.submit_put(int(h), blob, meta)
            fetched += 1
        self.onboarded_blocks += fetched
        if fetched:
            await asyncio.to_thread(offload.drain)
            logger.info("onboarded %d prefix blocks from G4 store", fetched)
