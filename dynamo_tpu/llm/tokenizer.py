"""Tokenizer facade: one interface over HF ``tokenizers`` artifacts.

Reference parity: lib/llm/src/tokenizers.rs:83-92 (``Tokenizer`` facade over
HF tokenizers), :158-191 (``DecodeStream`` incremental decoding), and the
GGUF leg (gguf/gguf_tokenizer.rs -> ``llm/gguf.py``): a model dir carrying
a ``.gguf`` file (or a ``.gguf`` path itself) gets its tokenizer converted
from the GGUF metadata.  Either way the same Rust ``tokenizers`` core runs
underneath through its Python binding, so token ids are bit-identical with
the reference for the same artifact.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from tokenizers import Tokenizer as _HFTokenizer
from tokenizers.decoders import DecodeStream as _HFDecodeStream


class TokenizerError(RuntimeError):
    pass


class Tokenizer:
    """Encode/decode facade bound to one model's tokenizer artifact.

    Loads ``tokenizer.json`` (plus ``tokenizer_config.json`` for the chat
    template and special tokens) from a model directory or explicit file.
    """

    def __init__(
        self,
        hf: _HFTokenizer,
        *,
        chat_template: Optional[str] = None,
        eos_token: Optional[str] = None,
        bos_token: Optional[str] = None,
    ) -> None:
        self._hf = hf
        self.chat_template = chat_template
        self.eos_token = eos_token
        self.bos_token = bos_token

    # -- constructors --------------------------------------------------------

    @classmethod
    def from_model_dir(cls, path: str) -> "Tokenizer":
        tok_file = os.path.join(path, "tokenizer.json") if os.path.isdir(path) else path
        if not os.path.exists(tok_file) or tok_file.endswith(".gguf"):
            # GGUF fallback: tokenizer.json absent but a .gguf present (or
            # the path IS the gguf file) -- convert from GGUF metadata
            from .gguf import find_gguf_file, gguf_tokenizer

            gguf_path = find_gguf_file(path)
            if gguf_path is not None:
                hf, info = gguf_tokenizer(gguf_path)
                return cls(
                    hf,
                    chat_template=info.get("chat_template"),
                    eos_token=hf.id_to_token(info["eos_token_id"]),
                    bos_token=hf.id_to_token(info["bos_token_id"]),
                )
            raise TokenizerError(f"no tokenizer.json or .gguf under {path}")
        hf = _HFTokenizer.from_file(tok_file)
        chat_template = eos = bos = None
        cfg_file = os.path.join(path, "tokenizer_config.json")
        if os.path.exists(cfg_file):
            with open(cfg_file) as f:
                cfg = json.load(f)
            chat_template = cfg.get("chat_template")
            eos = _token_str(cfg.get("eos_token"))
            bos = _token_str(cfg.get("bos_token"))
        return cls(hf, chat_template=chat_template, eos_token=eos, bos_token=bos)

    @classmethod
    def from_file(cls, tokenizer_json: str) -> "Tokenizer":
        return cls(_HFTokenizer.from_file(tokenizer_json))

    @classmethod
    def from_blobs(cls, tokenizer_json: bytes, config: Optional[dict] = None) -> "Tokenizer":
        """Build from in-memory artifacts (model-card transport: the MDC
        carries tokenizer.json + tokenizer_config.json through the hub
        object store, no filesystem involved)."""
        hf = _HFTokenizer.from_str(
            tokenizer_json.decode()
            if isinstance(tokenizer_json, bytes)
            else tokenizer_json
        )
        cfg = config or {}
        return cls(
            hf,
            chat_template=cfg.get("chat_template"),
            eos_token=_token_str(cfg.get("eos_token")),
            bos_token=_token_str(cfg.get("bos_token")),
        )

    # -- special tokens ------------------------------------------------------

    @property
    def eos_token_ids(self) -> List[int]:
        if self.eos_token is None:
            return []
        tid = self._hf.token_to_id(self.eos_token)
        return [tid] if tid is not None else []

    def token_to_id(self, token: str) -> Optional[int]:
        return self._hf.token_to_id(token)

    @property
    def vocab_size(self) -> int:
        return self._hf.get_vocab_size()

    # -- encode/decode -------------------------------------------------------

    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]:
        return self._hf.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: List[int], skip_special_tokens: bool = True) -> str:
        return self._hf.decode(ids, skip_special_tokens=skip_special_tokens)

    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self._hf, skip_special_tokens=skip_special_tokens)


class DecodeStream:
    """Incremental detokenizer: feed token ids one at a time, get back the
    text delta each id completes (None while a multi-id glyph is pending).

    Reference: tokenizers.rs:158-191 -- same Rust DecodeStream underneath, so
    byte-fallback and multi-token unicode sequences flush identically.
    """

    def __init__(self, hf: _HFTokenizer, skip_special_tokens: bool = True) -> None:
        self._hf = hf
        self._stream = _HFDecodeStream(skip_special_tokens=skip_special_tokens)

    def step(self, token_id: int) -> Optional[str]:
        return self._stream.step(self._hf, token_id)


def _token_str(t) -> Optional[str]:
    """tokenizer_config.json encodes special tokens either as strings or as
    AddedToken dicts ({"content": ...})."""
    if t is None:
        return None
    if isinstance(t, str):
        return t
    if isinstance(t, dict):
        return t.get("content")
    return None
