"""Model Deployment Card (MDC): everything a frontend needs to serve a model.

Reference parity: lib/llm/src/model_card/model.rs:88 (ModelDeploymentCard:
model info, tokenizer kind, context length, kv block size), create.rs
(build from an HF checkout), and the NATS-object-store transport
(``move_from_nats`` in discovery/watcher.rs:193).  Here the card's tokenizer
artifacts travel through the hub object store: a worker publishes once under
``mdc/{slug}``, every frontend downloads on first sight of the model.

Worker-side registration (reference local_model.rs:27 ``attach`` +
discovery.rs ``MODEL_ROOT_PATH``): one kv entry ``models/{slug}/{lease:x}``
scoped to the worker's primary lease, so a dead worker's registration
disappears with its lease and the frontend watcher can react.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .tokenizer import Tokenizer

MODEL_ROOT = "models"  # kv prefix (reference discovery.rs MODEL_ROOT_PATH)
MDC_OBJ_PREFIX = "mdc"  # object-store namespace for card payloads


def slugify(name: str) -> str:
    """Key-safe model name (reference utils/slug.rs semantics)."""
    return name.replace("/", "--").replace(" ", "_").lower()


@dataclass
class ModelEntry:
    """The kv payload under models/{slug}/{lease:x} (reference
    discovery/model_entry.rs)."""

    name: str
    namespace: str
    component: str
    endpoint: str
    model_type: str = "backend"  # backend = token-level worker behind preproc
    # endpoint name (same component) serving pooled embeddings; "" = the
    # worker does not embed.  The frontend watcher builds the /v1/embeddings
    # pipeline iff set (reference ModelType::Embedding, openai.rs:212).
    embed_endpoint: str = ""

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__, sort_keys=True).encode()

    @classmethod
    def from_json(cls, blob: bytes) -> "ModelEntry":
        return cls(**json.loads(blob))


@dataclass
class ModelDeploymentCard:
    name: str
    context_length: int = 4096
    kv_block_size: int = 16
    tokenizer_json: bytes = b""
    tokenizer_config: Dict[str, Any] = field(default_factory=dict)

    @property
    def slug(self) -> str:
        return slugify(self.name)

    @property
    def mdcsum(self) -> str:
        h = hashlib.sha256()
        h.update(self.tokenizer_json)
        h.update(json.dumps(self.tokenizer_config, sort_keys=True).encode())
        return h.hexdigest()[:16]

    # -- build ---------------------------------------------------------------

    @classmethod
    def from_model_dir(
        cls,
        path: str,
        name: Optional[str] = None,
        kv_block_size: int = 16,
    ) -> "ModelDeploymentCard":
        tok_file = os.path.join(path, "tokenizer.json")
        if not os.path.exists(tok_file):
            raise FileNotFoundError(f"no tokenizer.json under {path}")
        with open(tok_file, "rb") as f:
            tok_blob = f.read()
        tok_cfg: Dict[str, Any] = {}
        cfg_file = os.path.join(path, "tokenizer_config.json")
        if os.path.exists(cfg_file):
            with open(cfg_file) as f:
                tok_cfg = json.load(f)
        context_length = 4096
        model_cfg_file = os.path.join(path, "config.json")
        if os.path.exists(model_cfg_file):
            with open(model_cfg_file) as f:
                mc = json.load(f)
            context_length = int(
                mc.get("max_position_embeddings") or context_length
            )
        return cls(
            name=name or os.path.basename(os.path.normpath(path)),
            context_length=context_length,
            kv_block_size=kv_block_size,
            tokenizer_json=tok_blob,
            tokenizer_config=tok_cfg,
        )

    def tokenizer(self) -> Tokenizer:
        return Tokenizer.from_blobs(self.tokenizer_json, self.tokenizer_config)

    # -- hub transport -------------------------------------------------------

    def to_blob(self) -> bytes:
        return json.dumps(
            {
                "name": self.name,
                "context_length": self.context_length,
                "kv_block_size": self.kv_block_size,
                "tokenizer_json": self.tokenizer_json.decode(),
                "tokenizer_config": self.tokenizer_config,
                "mdcsum": self.mdcsum,
            }
        ).encode()

    @classmethod
    def from_blob(cls, blob: bytes) -> "ModelDeploymentCard":
        d = json.loads(blob)
        return cls(
            name=d["name"],
            context_length=d["context_length"],
            kv_block_size=d["kv_block_size"],
            tokenizer_json=d["tokenizer_json"].encode(),
            tokenizer_config=d.get("tokenizer_config") or {},
        )

    async def publish(self, hub) -> str:
        """Upload the card to the hub object store; returns the object name."""
        obj = f"{MDC_OBJ_PREFIX}/{self.slug}"
        await hub.obj_put(obj, self.to_blob())
        return obj

    @classmethod
    async def download(cls, hub, name: str) -> Optional["ModelDeploymentCard"]:
        blob = await hub.obj_get(f"{MDC_OBJ_PREFIX}/{slugify(name)}")
        return cls.from_blob(blob) if blob is not None else None


async def register_llm(
    runtime,
    endpoint,
    model_path: str,
    model_name: Optional[str] = None,
    model_type: str = "backend",
    kv_block_size: int = 16,
    embed_endpoint: str = "",
) -> ModelDeploymentCard:
    """Worker-side model registration (reference bindings lib.rs:98-160
    ``register_llm``): publish the MDC blob, then create the lease-scoped
    ``models/{slug}/{lease:x}`` entry pointing at this endpoint."""
    card = ModelDeploymentCard.from_model_dir(
        model_path, name=model_name, kv_block_size=kv_block_size
    )
    await card.publish(runtime.hub)
    entry = ModelEntry(
        name=card.name,
        namespace=endpoint.namespace,
        component=endpoint.component,
        endpoint=endpoint.name,
        model_type=model_type,
        embed_endpoint=embed_endpoint,
    )
    lease = runtime.primary_lease
    key = f"{MODEL_ROOT}/{card.slug}/{lease:x}"
    created = await runtime.hub.kv_create(key, entry.to_json(), lease=lease)
    if not created:
        await runtime.hub.kv_put(key, entry.to_json(), lease=lease)
    return card
