"""Perplexity evaluation over a loaded checkpoint (``dynamo-tpu eval``).

The round-4 verdict's ask: every quality claim rested on tiny random-init
cosines; this harness scores any real checkpoint (bf16 or int8) on real
text through the SAME forward the serving path runs (transformer +
lm_logits over the paged-KV prefill attention), so quantization and
loader regressions surface as a perplexity delta, not a silent quality
drop.  Reference capability: the delegated engines' accuracy flows
(vLLM lm-eval docs); here it is first-party.

Method: the token stream splits into independent windows of ``window``
tokens (no overlapping stride); each window's teacher-forced NLL is
summed over positions 1..len-1.  Deterministic, standard, and exactly
reproducible against a torch reference.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..engine import attention as att
from ..engine.config import ModelConfig
from ..engine.model import Params, lm_logits, transformer


@partial(jax.jit, static_argnames=("cfg",))
def window_nll(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [1, W] window (0-padded)
    length: jax.Array,  # [] valid tokens in the window
) -> jax.Array:
    """Sum of -log p(t_i | t_<i) over positions 1..length-1 (f32 scalar).

    Runs the serving trunk verbatim (same attention dispatch the prefill
    path uses) over a scratch KV the call discards."""
    B, W = tokens.shape
    page = 16
    n_pages = W // page + 2  # + trash page 0 + tail slack
    kv = jnp.zeros(
        (cfg.num_layers, 2, n_pages, page, cfg.num_kv_heads, cfg.head_dim),
        jnp.dtype(cfg.dtype),
    )
    page_table = jnp.arange(1, 1 + (W + page - 1) // page, dtype=jnp.int32)[
        None, :
    ]
    positions = jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32), (B, W))
    lens = jnp.full((B,), length, jnp.int32)

    def attn_fn(q, k, v, kv_pages, layer):
        out = att.prefill_attention_dispatch(
            q, k, v, lens, cfg.sliding_window or 0
        )
        new_kv = att.write_prefill_kv(kv_pages, k, v, page_table, layer)
        return out, new_kv

    hidden, _ = transformer(params, cfg, tokens, positions, kv, attn_fn)
    logits = lm_logits(params, cfg, hidden)  # [1, W, V] f32
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    targets = tokens[:, 1:]
    tok_lp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = jnp.arange(W - 1)[None, :] < (length - 1)
    return -jnp.sum(jnp.where(mask, tok_lp, 0.0)).astype(jnp.float32)


def evaluate_perplexity(
    params: Params,
    cfg: ModelConfig,
    token_ids: List[int],
    window: int = 512,
) -> Dict[str, float]:
    """Windowed perplexity of ``token_ids`` under the model."""
    # window_nll's KV scatter pages the buffer in 16-token pages: round the
    # window DOWN to a page multiple (floor 16) so any --window value works
    window = max(16, (min(window, cfg.max_position) // 16) * 16)
    total_nll = 0.0
    total_tokens = 0
    for start in range(0, len(token_ids), window):
        chunk = token_ids[start : start + window]
        if len(chunk) < 2:
            continue
        buf = np.zeros((1, window), np.int32)
        buf[0, : len(chunk)] = chunk
        nll = float(
            window_nll(
                params, cfg, jnp.asarray(buf), jnp.int32(len(chunk))
            )
        )
        total_nll += nll
        total_tokens += len(chunk) - 1
    if total_tokens == 0:
        raise ValueError("need at least 2 tokens to score")
    avg = total_nll / total_tokens
    return {
        "perplexity": math.exp(avg),
        "avg_nll": avg,
        "tokens_scored": total_tokens,
    }
