"""OpenAIPreprocessor: OpenAI requests -> token-level requests, and engine
deltas -> OpenAI chunks on the way back.

Reference parity: lib/llm/src/preprocessor.rs:64-110 (template render +
tokenize + sampling-defaults application, ``formatted_prompt`` / ``token_ids``
annotations) and the chat-template engine under preprocessor/prompt/
(minijinja there, jinja2 here -- both execute the HF ``chat_template``
dialect: ``raise_exception``, ``tojson``, sandboxed).
"""

from __future__ import annotations

import time
from typing import Any, AsyncIterator, Dict, List, Optional, Union

import jinja2
import jinja2.sandbox

from ..protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    SpeculationOptions,
    StopConditions,
)
from ..protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    OpenAIError,
    chat_chunk,
    completion_chunk,
    new_response_id,
    usage_block,
)
from ..runtime.engine import Annotated, AsyncEngine, Context, as_response_stream
from ..runtime.pipeline import Operator
from .tokenizer import Tokenizer

# Fallback template when the tokenizer artifact carries none: the simple
# role-tagged layout (matches the reference's default for template-less
# models rather than failing the request).
DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|{{ message['role'] }}|>\n{{ message['content'] }}\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>\n{% endif %}"
)


def _raise_exception(message: str) -> None:
    raise jinja2.exceptions.TemplateError(message)


class PromptFormatter:
    """Renders the HF ``chat_template`` for a message list."""

    def __init__(self, tokenizer: Tokenizer) -> None:
        self._env = jinja2.sandbox.ImmutableSandboxedEnvironment(
            trim_blocks=True, lstrip_blocks=True
        )
        self._env.globals["raise_exception"] = _raise_exception
        self._env.globals["strftime_now"] = lambda fmt: time.strftime(fmt)
        template = tokenizer.chat_template or DEFAULT_CHAT_TEMPLATE
        self._template = self._env.from_string(template)
        self._bos = tokenizer.bos_token or ""
        self._eos = tokenizer.eos_token or ""

    def render(self, messages: List[Dict[str, Any]]) -> str:
        try:
            return self._template.render(
                messages=messages,
                add_generation_prompt=True,
                bos_token=self._bos,
                eos_token=self._eos,
            )
        except jinja2.exceptions.TemplateError as e:
            raise OpenAIError(f"chat template failed: {e}") from e


class OpenAIPreprocessor(Operator):
    """Forward: OpenAI request -> PreprocessedRequest.  Backward: backend
    deltas -> OpenAI chunk dicts (still wrapped in Annotated envelopes).

    The downstream engine yields dicts shaped like BackendOutput: ``text``
    (delta), ``token_ids``, ``finish_reason``.
    """

    def __init__(self, model_name: str, tokenizer: Tokenizer) -> None:
        self.model_name = model_name
        self.tokenizer = tokenizer
        self.formatter = PromptFormatter(tokenizer)

    # -- forward translation -------------------------------------------------

    def preprocess(
        self, req: Union[ChatCompletionRequest, CompletionRequest]
    ) -> PreprocessedRequest:
        if isinstance(req, ChatCompletionRequest):
            prompt = self.formatter.render(req.messages)
            token_ids = self.tokenizer.encode(prompt)
        elif isinstance(req.prompt, list):
            prompt = None
            token_ids = list(req.prompt)
        else:
            prompt = req.prompt
            token_ids = self.tokenizer.encode(prompt)
        s = req.sampling
        out = PreprocessedRequest(
            token_ids=token_ids,
            stop_conditions=StopConditions(
                max_tokens=s.max_tokens,
                stop=s.stop,
                min_tokens=s.min_tokens,
                ignore_eos=s.ignore_eos,
            ),
            sampling_options=SamplingOptions(
                temperature=s.temperature,
                top_p=s.top_p,
                top_k=s.top_k,
                frequency_penalty=s.frequency_penalty,
                presence_penalty=s.presence_penalty,
                repetition_penalty=s.repetition_penalty,
                seed=s.seed,
                logprobs=s.logprobs,
            ),
            eos_token_ids=self.tokenizer.eos_token_ids,
        )
        spec = getattr(req, "speculation", None)
        if spec:
            out.speculation = SpeculationOptions(
                enabled=spec.get("enabled", True),
                num_draft_tokens=spec.get("num_draft_tokens", 4),
                drafter=spec.get("drafter", "ngram"),
            )
        if getattr(req, "echo", False) and s.logprobs is not None:
            # legacy OpenAI echo+logprobs: the engine's verify-scoring path
            # serves per-position PROMPT logprobs alongside the completion
            out.prompt_logprobs = s.logprobs
        out.annotations = list(getattr(req, "annotations", []) or [])
        out._formatted_prompt = prompt  # for the formatted_prompt annotation
        return out

    def _format_logprobs(
        self, data: Dict[str, Any], is_chat: bool, text_off: int
    ) -> Dict[str, Any]:
        """Engine logprob payload -> OpenAI response structures.

        Chat: ``{"content": [{token, logprob, bytes, top_logprobs}]}``;
        completions: ``{tokens, token_logprobs, top_logprobs, text_offset}``
        (reference aggregator shapes, openai/completions/aggregator.rs:43).
        Token strings come from single-id detokenization; ``text_offset``
        is the offset of this chunk's first token within the emitted
        completion text (per-token offsets inside a multi-token chunk are
        approximated from the token strings' lengths -- the stop jail can
        hold back text, so exact alignment is not reconstructible in a
        stream)."""
        ids = data.get("token_ids") or []
        lps = data.get("logprobs") or []
        tops = data.get("top_logprobs")
        tok_str = [self.tokenizer.decode([t]) for t in ids]

        def top_entries(i: int):
            if tops is None or i >= len(tops):
                return None
            return [
                (self.tokenizer.decode([int(tid)]), float(tlp))
                for tid, tlp in tops[i]
            ]

        if is_chat:
            content = []
            for i, (t, lp) in enumerate(zip(tok_str, lps)):
                entry: Dict[str, Any] = {
                    "token": t,
                    "logprob": lp,
                    "bytes": list(t.encode("utf-8")),
                }
                te = top_entries(i)
                if te is not None:
                    entry["top_logprobs"] = [
                        {
                            "token": s,
                            "logprob": l,
                            "bytes": list(s.encode("utf-8")),
                        }
                        for s, l in te
                    ]
                content.append(entry)
            return {"content": content}
        offsets, off = [], text_off
        for t in tok_str:
            offsets.append(off)
            off += len(t)
        def top_map(i: int) -> Dict[str, float]:
            return self._first_wins_map(top_entries(i) or [])

        return {
            "tokens": tok_str,
            "token_logprobs": list(lps),
            "top_logprobs": (
                [top_map(i) for i in range(len(ids))]
                if tops is not None
                else None
            ),
            "text_offset": offsets,
        }

    @staticmethod
    def _first_wins_map(
        pairs, limit: Optional[int] = None
    ) -> Dict[str, float]:
        """Probability-sorted ``(token_string, logprob)`` pairs -> the
        OpenAI top_logprobs map.  Two token ids can decode to the same
        string, and the later (lower-probability) alternative must not
        overwrite the earlier one -- the ONE dedup rule shared by the
        completion and prompt logprob blocks."""
        out: Dict[str, float] = {}
        for s, l in pairs:
            if limit is not None and len(out) >= limit:
                break
            if s not in out:
                out[s] = float(l)
        return out

    def _format_prompt_logprobs(
        self, entries: List[Any], want: int
    ) -> Dict[str, Any]:
        """Engine prompt-logprob entries -> the completions logprobs block
        covering the echoed prompt.  Entries are ``[token_id, logprob|None,
        top|None]`` per prompt position (position 0 carries None, the
        OpenAI prompt-logprobs shape); offsets start at 0 because the echo
        text leads the response."""
        tokens: List[str] = []
        lps: List[Any] = []
        tops: List[Any] = []
        offsets: List[int] = []
        off = 0
        for tid, lp, top in entries:
            s = self.tokenizer.decode([int(tid)])
            tokens.append(s)
            lps.append(lp)
            if top is None or want <= 0:
                tops.append(None)
            else:
                tops.append(
                    self._first_wins_map(
                        (
                            (self.tokenizer.decode([int(alt_id)]), alt_lp)
                            for alt_id, alt_lp in top
                        ),
                        limit=want,
                    )
                )
            offsets.append(off)
            off += len(s)
        return {
            "tokens": tokens,
            "token_logprobs": lps,
            "top_logprobs": tops if want > 0 else None,
            "text_offset": offsets,
        }

    # -- Operator ------------------------------------------------------------

    async def generate(
        self, request: Context, next: AsyncEngine
    ) -> AsyncIterator[Annotated]:
        req = request.data
        is_chat = isinstance(req, ChatCompletionRequest)
        pre = self.preprocess(req)
        stream = await as_response_stream(next, request.replace(pre.to_dict()))

        rid = new_response_id("chatcmpl" if is_chat else "cmpl")
        created = int(time.time())
        model = self.model_name

        async def gen() -> AsyncIterator[Annotated]:
            # request-level annotations ride the stream ahead of data
            # (reference preprocessor.rs:61-62)
            if "formatted_prompt" in pre.annotations and pre._formatted_prompt:
                yield Annotated.from_annotation(
                    "formatted_prompt", pre._formatted_prompt
                )
            if "token_ids" in pre.annotations:
                yield Annotated.from_annotation("token_ids", pre.token_ids)
            if is_chat:
                yield Annotated.from_data(
                    chat_chunk(rid, model, created, role="assistant", content="")
                )
            completion_tokens = 0
            finish: Optional[str] = None
            text_off = 0  # running offset into the emitted completion text
            spec_stats = None  # engine-reported acceptance (finish item)
            pending_echo: Optional[str] = None
            if not is_chat and getattr(req, "echo", False):
                # OpenAI completions echo: the prompt text leads the
                # completion (its length counts into text_offset)
                prompt_text = (
                    req.prompt
                    if isinstance(req.prompt, str)
                    else self.tokenizer.decode(list(req.prompt))
                )
                if prompt_text and pre.prompt_logprobs is not None:
                    # echo+logprobs: hold the echo chunk until the engine's
                    # first item delivers the prompt logprobs that belong
                    # on it (engines without the scoring path degrade to a
                    # plain echo)
                    pending_echo = prompt_text
                elif prompt_text:
                    yield Annotated.from_data(
                        completion_chunk(
                            rid, model, created, text=prompt_text
                        )
                    )
                    text_off = len(prompt_text)
            async for item in stream:
                if not isinstance(item, Annotated):
                    item = Annotated.from_data(item)
                if item.is_error():
                    yield item
                    return
                data = item.data
                if data is None:
                    continue
                if data.get("spec") is not None:
                    spec_stats = data["spec"]
                if pending_echo is not None:
                    plp = data.get("prompt_logprobs")
                    lp_block = (
                        self._format_prompt_logprobs(
                            plp, pre.prompt_logprobs or 0
                        )
                        if plp
                        else None
                    )
                    yield Annotated.from_data(
                        completion_chunk(
                            rid, model, created, text=pending_echo,
                            logprobs=lp_block,
                        )
                    )
                    text_off = len(pending_echo)
                    pending_echo = None
                completion_tokens += len(data.get("token_ids") or [])
                text = data.get("text")
                fr = data.get("finish_reason")
                if fr:
                    from ..protocols.common import FinishReason

                    finish = FinishReason(fr).to_openai()
                # a token whose incremental detok produced no text yet (e.g.
                # a byte-level partial) must still ship its logprobs
                has_lp = (
                    data.get("logprobs") is not None
                    and data.get("token_ids")
                )
                if text or has_lp:
                    lp = (
                        self._format_logprobs(data, is_chat, text_off)
                        if has_lp
                        else None
                    )
                    text_off += len(text or "")
                    if is_chat:
                        yield Annotated.from_data(
                            chat_chunk(
                                rid, model, created, content=text or "",
                                logprobs=lp,
                            )
                        )
                    else:
                        yield Annotated.from_data(
                            completion_chunk(
                                rid, model, created, text=text or "",
                                logprobs=lp,
                            )
                        )
            if pending_echo is not None:
                # the engine produced no data items at all; still echo
                yield Annotated.from_data(
                    completion_chunk(rid, model, created, text=pending_echo)
                )
            final = (
                chat_chunk(rid, model, created, finish_reason=finish or "stop")
                if is_chat
                else completion_chunk(
                    rid, model, created, finish_reason=finish or "stop"
                )
            )
            final["usage"] = usage_block(len(pre.token_ids), completion_tokens)
            if spec_stats is not None:
                # per-choice acceptance observability: the usage extension
                # mirrors the engine's spec stats (tracing carries the same
                # numbers as the request span's spec_accept_rate attr)
                final["usage"]["speculation"] = spec_stats
            yield Annotated.from_data(final)

        return gen()
