"""GGUF tokenizer support: metadata parsing + HF-tokenizers conversion.

Reference ``lib/llm/src/gguf`` (gguf_metadata.rs, gguf_tokenizer.rs):
llama.cpp-ecosystem models ship as one ``.gguf`` file whose metadata embeds
the tokenizer (tokens, scores/merges, special ids).  The reference parses
the metadata and converts to a ``tokenizers`` object -- ``llama``-model
files become Unigram (SentencePiece semantics: byte fallback, ``▁`` word
boundaries), ``gpt2``-model files become byte-level BPE.  Same two
conversions here, feeding the standard `llm.tokenizer.Tokenizer` facade:
``--model-path model.gguf`` (or a dir containing one) gets its tokenizer
from the GGUF metadata.

Weights stay on the safetensors path: GGUF weight blocks are mostly
llama.cpp quantization formats (Q4_K & co) whose TPU story is a separate
dequantization design, documented as out of scope -- the reference
likewise hands GGUF *inference* to its engines and only reads tokenizer +
config metadata itself (SURVEY.md 2.2).
"""

from __future__ import annotations

import logging
import os
import struct
from typing import Any, BinaryIO, Dict, Optional, Tuple

logger = logging.getLogger("dynamo.gguf")

GGUF_MAGIC = 0x46554747  # "GGUF" little-endian

# metadata value types (gguf spec / gguf_metadata.rs)
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32 = 0, 1, 2, 3, 4, 5
_T_F32, _T_BOOL, _T_STRING, _T_ARRAY, _T_U64, _T_I64, _T_F64 = (
    6, 7, 8, 9, 10, 11, 12,
)

_SCALAR_FMT = {
    _T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
    _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_U64: "<Q",
    _T_I64: "<q", _T_F64: "<d",
}


def _read_scalar(f: BinaryIO, vtype: int) -> Any:
    if vtype == _T_BOOL:
        return struct.unpack("<B", f.read(1))[0] != 0
    if vtype == _T_STRING:
        (n,) = struct.unpack("<Q", f.read(8))
        return f.read(n).decode("utf-8", errors="replace")
    fmt = _SCALAR_FMT.get(vtype)
    if fmt is None:
        raise ValueError(f"unsupported GGUF value type {vtype}")
    return struct.unpack(fmt, f.read(struct.calcsize(fmt)))[0]


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype == _T_ARRAY:
        (etype,) = struct.unpack("<I", f.read(4))
        (count,) = struct.unpack("<Q", f.read(8))
        return [_read_value(f, etype) for _ in range(count)]
    return _read_scalar(f, vtype)


def read_gguf_metadata(path: str) -> Dict[str, Any]:
    """Parse a GGUF file's metadata key/value section (tensors skipped)."""
    with open(path, "rb") as f:
        magic, version = struct.unpack("<II", f.read(8))
        if magic != GGUF_MAGIC:
            raise ValueError(f"{path}: not a GGUF file (magic {magic:#x})")
        if version < 2:
            raise ValueError(f"{path}: GGUF version {version} unsupported")
        _tensor_count, kv_count = struct.unpack("<QQ", f.read(16))
        meta: Dict[str, Any] = {}
        for _ in range(kv_count):
            (klen,) = struct.unpack("<Q", f.read(8))
            key = f.read(klen).decode("utf-8", errors="replace")
            (vtype,) = struct.unpack("<I", f.read(4))
            meta[key] = _read_value(f, vtype)
        return meta


def find_gguf_file(model_path: str) -> Optional[str]:
    """``model.gguf`` itself, or the single ``.gguf`` inside a directory."""
    if model_path.endswith(".gguf") and os.path.isfile(model_path):
        return model_path
    if os.path.isdir(model_path):
        ggufs = sorted(
            f for f in os.listdir(model_path) if f.endswith(".gguf")
        )
        if ggufs:
            return os.path.join(model_path, ggufs[0])
    return None


def gguf_tokenizer(path: str):
    """Build a ``tokenizers.Tokenizer`` from GGUF metadata.

    Returns ``(tokenizer, info)`` where info carries the special ids the
    facade needs (bos/eos/add_bos).  Conversion mirrors
    gguf_tokenizer.rs: ``llama``/``replit`` -> Unigram with SentencePiece
    normalizer/decoder chains; ``gpt2`` -> byte-level BPE."""
    from tokenizers import AddedToken, Tokenizer, decoders, normalizers
    from tokenizers import models as tok_models
    from tokenizers import pre_tokenizers

    meta = read_gguf_metadata(path)

    def g(key: str, required: bool = False) -> Any:
        v = meta.get(f"tokenizer.ggml.{key}")
        if v is None and required:
            raise ValueError(f"{path}: missing tokenizer.ggml.{key}")
        return v

    model = g("model", required=True)
    tokens = g("tokens", required=True)
    bos = g("bos_token_id", required=True)
    eos = g("eos_token_id", required=True)
    unk = g("unknown_token_id")

    if model in ("llama", "replit"):
        scores = g("scores")
        if scores is None:
            raise ValueError(
                f"{path}: `llama` unigram tokenizer requires "
                "tokenizer.ggml.scores"
            )
        unk_id = int(unk) if unk is not None else 0
        tok = Tokenizer(
            tok_models.Unigram(
                [(t, float(s)) for t, s in zip(tokens, scores)],
                unk_id=unk_id,
                byte_fallback=True,
            )
        )
        tok.normalizer = normalizers.Sequence(
            [normalizers.Prepend("▁"), normalizers.Replace(" ", "▁")]
        )
        tok.decoder = decoders.Sequence(
            [
                decoders.Replace("▁", " "),
                decoders.ByteFallback(),
                decoders.Fuse(),
                decoders.Strip(" ", 1, 0),
            ]
        )
    elif model == "gpt2":
        merges_raw = g("merges")
        if merges_raw is None:
            raise ValueError(f"{path}: BPE tokenizer requires merges")
        merges = []
        for m in merges_raw:
            a, _, b = m.partition(" ")
            merges.append((a, b))
        vocab = {t: i for i, t in enumerate(tokens)}
        tok = Tokenizer(
            tok_models.BPE(
                vocab, merges,
                unk_token=(tokens[int(unk)] if unk is not None else None),
            )
        )
        tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        tok.decoder = decoders.ByteLevel()
    else:
        raise ValueError(f"{path}: tokenizer model {model!r} not supported")

    specials = [tokens[int(bos)], tokens[int(eos)]]
    if unk is not None:
        specials.append(tokens[int(unk)])
    tok.add_special_tokens([AddedToken(s, special=True) for s in specials])

    # llama.cpp convention: SPM ("llama") tokenizers default to add_bos=true
    # when the key is absent; BPE defaults to false
    default_add_bos = model in ("llama", "replit")
    add_bos = bool(meta.get("tokenizer.ggml.add_bos_token", default_add_bos))
    if add_bos:
        # llama-family semantics: encode(add_special_tokens=True) prepends
        # BOS (llama.cpp/HF GGUF conversion installs the same
        # post-processor; without it prompt ids silently lose their BOS)
        from tokenizers import processors

        bos_tok = tokens[int(bos)]
        tok.post_processor = processors.TemplateProcessing(
            single=f"{bos_tok} $A",
            pair=f"{bos_tok} $A {bos_tok} $B",
            special_tokens=[(bos_tok, int(bos))],
        )

    info = {
        "bos_token_id": int(bos),
        "eos_token_id": int(eos),
        "unk_token_id": int(unk) if unk is not None else None,
        "add_bos_token": add_bos,
        # chat-tuned GGUFs embed their template in standard metadata
        "chat_template": meta.get("tokenizer.chat_template"),
        "model": model,
    }
    logger.info(
        "gguf tokenizer: model=%s tokens=%d bos=%d eos=%d",
        model, len(tokens), int(bos), int(eos),
    )
    return tok, info
