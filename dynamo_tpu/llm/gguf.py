"""GGUF support: metadata + tokenizer conversion + quantized weight loading.

Reference ``lib/llm/src/gguf`` (gguf_metadata.rs, gguf_tokenizer.rs):
llama.cpp-ecosystem models ship as one ``.gguf`` file whose metadata embeds
the tokenizer (tokens, scores/merges, special ids).  The reference parses
the metadata and converts to a ``tokenizers`` object -- ``llama``-model
files become Unigram (SentencePiece semantics: byte fallback, ``▁`` word
boundaries), ``gpt2``-model files become byte-level BPE.  Same two
conversions here, feeding the standard `llm.tokenizer.Tokenizer` facade:
``--model-path model.gguf`` (or a dir containing one) gets its tokenizer
from the GGUF metadata.

WEIGHTS load first-party too (the reference serves GGUF checkpoints via
llamacpp/mistralrs delegation; here the engine consumes them directly):
F32/F16/BF16 plus the ubiquitous block formats Q8_0 and Q4_0 dequantize
on load into the engine dtype (llama architecture; q/k rows un-permute
from llama.cpp's interleaved-rope layout back to the HF convention the
engine's RoPE uses).  K-quants (Q4_K & co) remain out of scope --
re-export those via llama.cpp to Q8_0, or use safetensors.
"""

from __future__ import annotations

import logging
import os
import struct
from typing import Any, BinaryIO, Dict, Optional, Tuple

logger = logging.getLogger("dynamo.gguf")

GGUF_MAGIC = 0x46554747  # "GGUF" little-endian

# metadata value types (gguf spec / gguf_metadata.rs)
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32 = 0, 1, 2, 3, 4, 5
_T_F32, _T_BOOL, _T_STRING, _T_ARRAY, _T_U64, _T_I64, _T_F64 = (
    6, 7, 8, 9, 10, 11, 12,
)

_SCALAR_FMT = {
    _T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
    _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_U64: "<Q",
    _T_I64: "<q", _T_F64: "<d",
}


def _read_scalar(f: BinaryIO, vtype: int) -> Any:
    if vtype == _T_BOOL:
        return struct.unpack("<B", f.read(1))[0] != 0
    if vtype == _T_STRING:
        (n,) = struct.unpack("<Q", f.read(8))
        return f.read(n).decode("utf-8", errors="replace")
    fmt = _SCALAR_FMT.get(vtype)
    if fmt is None:
        raise ValueError(f"unsupported GGUF value type {vtype}")
    return struct.unpack(fmt, f.read(struct.calcsize(fmt)))[0]


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype == _T_ARRAY:
        (etype,) = struct.unpack("<I", f.read(4))
        (count,) = struct.unpack("<Q", f.read(8))
        return [_read_value(f, etype) for _ in range(count)]
    return _read_scalar(f, vtype)


def _read_header(f: BinaryIO, path: str) -> Tuple[int, Dict[str, Any]]:
    """Magic/version check + the metadata KV section.  Returns
    ``(tensor_count, metadata)`` with ``f`` positioned at the tensor-info
    section -- the single parser behind both readers."""
    magic, version = struct.unpack("<II", f.read(8))
    if magic != GGUF_MAGIC:
        raise ValueError(f"{path}: not a GGUF file (magic {magic:#x})")
    if version < 2:
        raise ValueError(f"{path}: GGUF version {version} unsupported")
    tensor_count, kv_count = struct.unpack("<QQ", f.read(16))
    meta: Dict[str, Any] = {}
    for _ in range(kv_count):
        (klen,) = struct.unpack("<Q", f.read(8))
        key = f.read(klen).decode("utf-8", errors="replace")
        (vtype,) = struct.unpack("<I", f.read(4))
        meta[key] = _read_value(f, vtype)
    return tensor_count, meta


def read_gguf_metadata(path: str) -> Dict[str, Any]:
    """Parse a GGUF file's metadata key/value section (tensors skipped)."""
    with open(path, "rb") as f:
        return _read_header(f, path)[1]


def find_gguf_file(model_path: str) -> Optional[str]:
    """``model.gguf`` itself, or the single ``.gguf`` inside a directory."""
    if model_path.endswith(".gguf") and os.path.isfile(model_path):
        return model_path
    if os.path.isdir(model_path):
        ggufs = sorted(
            f for f in os.listdir(model_path) if f.endswith(".gguf")
        )
        if ggufs:
            return os.path.join(model_path, ggufs[0])
    return None


def gguf_tokenizer(path: str):
    """Build a ``tokenizers.Tokenizer`` from GGUF metadata.

    Returns ``(tokenizer, info)`` where info carries the special ids the
    facade needs (bos/eos/add_bos).  Conversion mirrors
    gguf_tokenizer.rs: ``llama``/``replit`` -> Unigram with SentencePiece
    normalizer/decoder chains; ``gpt2`` -> byte-level BPE."""
    from tokenizers import AddedToken, Tokenizer, decoders, normalizers
    from tokenizers import models as tok_models
    from tokenizers import pre_tokenizers

    meta = read_gguf_metadata(path)

    def g(key: str, required: bool = False) -> Any:
        v = meta.get(f"tokenizer.ggml.{key}")
        if v is None and required:
            raise ValueError(f"{path}: missing tokenizer.ggml.{key}")
        return v

    model = g("model", required=True)
    tokens = g("tokens", required=True)
    bos = g("bos_token_id", required=True)
    eos = g("eos_token_id", required=True)
    unk = g("unknown_token_id")

    if model in ("llama", "replit"):
        scores = g("scores")
        if scores is None:
            raise ValueError(
                f"{path}: `llama` unigram tokenizer requires "
                "tokenizer.ggml.scores"
            )
        unk_id = int(unk) if unk is not None else 0
        tok = Tokenizer(
            tok_models.Unigram(
                [(t, float(s)) for t, s in zip(tokens, scores)],
                unk_id=unk_id,
                byte_fallback=True,
            )
        )
        tok.normalizer = normalizers.Sequence(
            [normalizers.Prepend("▁"), normalizers.Replace(" ", "▁")]
        )
        tok.decoder = decoders.Sequence(
            [
                decoders.Replace("▁", " "),
                decoders.ByteFallback(),
                decoders.Fuse(),
                decoders.Strip(" ", 1, 0),
            ]
        )
    elif model == "gpt2":
        merges_raw = g("merges")
        if merges_raw is None:
            raise ValueError(f"{path}: BPE tokenizer requires merges")
        merges = []
        for m in merges_raw:
            a, _, b = m.partition(" ")
            merges.append((a, b))
        vocab = {t: i for i, t in enumerate(tokens)}
        tok = Tokenizer(
            tok_models.BPE(
                vocab, merges,
                unk_token=(tokens[int(unk)] if unk is not None else None),
            )
        )
        tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        tok.decoder = decoders.ByteLevel()
    else:
        raise ValueError(f"{path}: tokenizer model {model!r} not supported")

    specials = [tokens[int(bos)], tokens[int(eos)]]
    if unk is not None:
        specials.append(tokens[int(unk)])
    tok.add_special_tokens([AddedToken(s, special=True) for s in specials])

    # llama.cpp convention: SPM ("llama") tokenizers default to add_bos=true
    # when the key is absent; BPE defaults to false
    default_add_bos = model in ("llama", "replit")
    add_bos = bool(meta.get("tokenizer.ggml.add_bos_token", default_add_bos))
    if add_bos:
        # llama-family semantics: encode(add_special_tokens=True) prepends
        # BOS (llama.cpp/HF GGUF conversion installs the same
        # post-processor; without it prompt ids silently lose their BOS)
        from tokenizers import processors

        bos_tok = tokens[int(bos)]
        tok.post_processor = processors.TemplateProcessing(
            single=f"{bos_tok} $A",
            pair=f"{bos_tok} $A {bos_tok} $B",
            special_tokens=[(bos_tok, int(bos))],
        )

    info = {
        "bos_token_id": int(bos),
        "eos_token_id": int(eos),
        "unk_token_id": int(unk) if unk is not None else None,
        "add_bos_token": add_bos,
        # chat-tuned GGUFs embed their template in standard metadata
        "chat_template": meta.get("tokenizer.chat_template"),
        "model": model,
    }
    logger.info(
        "gguf tokenizer: model=%s tokens=%d bos=%d eos=%d",
        model, len(tokens), int(bos), int(eos),
    )
    return tok, info


# ---------------------------------------------------------------------------
# Quantized weight loading (llama architecture)
# ---------------------------------------------------------------------------

# ggml tensor types (ggml.h)
GGML_F32, GGML_F16, GGML_Q4_0, GGML_Q8_0, GGML_BF16 = 0, 1, 2, 8, 30

_GGML_BLOCK = {  # type -> (elements per block, bytes per block)
    GGML_Q4_0: (32, 18),  # f16 scale + 16 nibble bytes
    GGML_Q8_0: (32, 34),  # f16 scale + 32 int8
}


def read_gguf_tensors(path: str):
    """Parse header + tensor-info section.

    Returns ``(metadata, tensors, data_start)`` where tensors maps name ->
    ``(ggml_type, numpy_shape, offset)`` (offset relative to data_start;
    numpy shape is the reversed ggml ``ne`` -- ggml lists the contiguous
    dimension first)."""
    with open(path, "rb") as f:
        tensor_count, meta = _read_header(f, path)
        tensors: Dict[str, Tuple[int, Tuple[int, ...], int]] = {}
        for _ in range(tensor_count):
            (nlen,) = struct.unpack("<Q", f.read(8))
            name = f.read(nlen).decode("utf-8", errors="replace")
            (n_dims,) = struct.unpack("<I", f.read(4))
            dims = struct.unpack(f"<{n_dims}Q", f.read(8 * n_dims))
            gtype, offset = struct.unpack("<IQ", f.read(4 + 8))
            tensors[name] = (gtype, tuple(reversed(dims)), offset)
        align = int(meta.get("general.alignment", 32) or 32)
        pos = f.tell()
        data_start = (pos + align - 1) // align * align
        return meta, tensors, data_start


def dequantize_ggml(buf: bytes, gtype: int, shape: Tuple[int, ...]):
    """Raw tensor bytes -> float numpy array of ``shape``."""
    import numpy as np

    n = 1
    for d in shape:
        n *= d
    if gtype == GGML_F32:
        return np.frombuffer(buf, np.float32, n).reshape(shape)
    if gtype == GGML_F16:
        return np.frombuffer(buf, np.float16, n).reshape(shape)
    if gtype == GGML_BF16:
        u = np.frombuffer(buf, np.uint16, n).astype(np.uint32) << 16
        return u.view(np.float32).reshape(shape)
    if gtype == GGML_Q8_0:
        per, nbytes = _GGML_BLOCK[gtype]
        blocks = n // per
        raw = np.frombuffer(buf, np.uint8, blocks * nbytes).reshape(
            blocks, nbytes
        )
        d = raw[:, :2].copy().view(np.float16).astype(np.float32)  # [B,1]
        q = raw[:, 2:].view(np.int8).astype(np.float32)  # [B,32]
        return (q * d).reshape(shape)
    if gtype == GGML_Q4_0:
        per, nbytes = _GGML_BLOCK[gtype]
        blocks = n // per
        raw = np.frombuffer(buf, np.uint8, blocks * nbytes).reshape(
            blocks, nbytes
        )
        d = raw[:, :2].copy().view(np.float16).astype(np.float32)  # [B,1]
        qs = raw[:, 2:]  # [B,16] nibble pairs
        lo = (qs & 0x0F).astype(np.int8) - 8
        hi = (qs >> 4).astype(np.int8) - 8
        # llama.cpp layout: byte j holds elements j (low) and j+16 (high)
        vals = np.concatenate([lo, hi], axis=1).astype(np.float32)  # [B,32]
        return (vals * d).reshape(shape)
    raise ValueError(
        f"unsupported ggml tensor type {gtype} (supported: F32/F16/BF16/"
        f"Q8_0/Q4_0; re-export K-quants via llama.cpp or use safetensors)"
    )


def _unpermute_rope(w, n_head: int):
    """Invert convert_hf_to_gguf's q/k permutation (interleaved-rope rows
    back to HF rotate_half order).  ``w`` is [out, in]."""
    out, inn = w.shape
    return (
        w.reshape(n_head, out // n_head // 2, 2, inn)
        .swapaxes(1, 2)
        .reshape(out, inn)
    )


def _require_llama_arch(meta: Dict[str, Any], path: str) -> None:
    """First-party GGUF weights are llama-only: other architectures may
    share the blk.N tensor naming but NOT llama.cpp's q/k rope permutation
    -- loading them would silently scramble attention."""
    arch = meta.get("general.architecture", "llama")
    if arch != "llama":
        raise ValueError(
            f"{path}: GGUF architecture {arch!r} unsupported for "
            f"first-party weights (llama only); use safetensors"
        )


class _GgufHFView:
    """Lazy GGUF tensor mapping presented under HF names, so the standard
    ``engine.weights.assemble_params`` consumes GGUF files unchanged."""

    _STATIC = {
        "token_embd.weight": "model.embed_tokens.weight",
        "output_norm.weight": "model.norm.weight",
        "output.weight": "lm_head.weight",
    }
    _BLK = {
        "attn_q.weight": "self_attn.q_proj.weight",
        "attn_k.weight": "self_attn.k_proj.weight",
        "attn_v.weight": "self_attn.v_proj.weight",
        "attn_output.weight": "self_attn.o_proj.weight",
        "ffn_gate.weight": "mlp.gate_proj.weight",
        "ffn_up.weight": "mlp.up_proj.weight",
        "ffn_down.weight": "mlp.down_proj.weight",
        "attn_norm.weight": "input_layernorm.weight",
        "ffn_norm.weight": "post_attention_layernorm.weight",
    }

    def __init__(self, path: str, n_head: int, n_kv_head: int) -> None:
        self.path = path
        self.meta, self.tensors, self.data_start = read_gguf_tensors(path)
        _require_llama_arch(self.meta, path)
        self.n_head = n_head
        self.n_kv_head = n_kv_head
        self._by_hf: Dict[str, str] = {}
        for gname in self.tensors:
            hf = self._hf_name(gname)
            if hf is not None:
                self._by_hf[hf] = gname

    def _hf_name(self, gname: str) -> Optional[str]:
        if gname in self._STATIC:
            return self._STATIC[gname]
        if gname.startswith("blk."):
            _, idx, rest = gname.split(".", 2)
            mapped = self._BLK.get(rest)
            if mapped is not None:
                return f"model.layers.{idx}.{mapped}"
        return None

    def __contains__(self, hf_name: str) -> bool:
        return hf_name in self._by_hf

    def __getitem__(self, hf_name: str):
        import numpy as np

        gname = self._by_hf[hf_name]
        gtype, shape, offset = self.tensors[gname]
        n = 1
        for d in shape:
            n *= d
        if gtype in _GGML_BLOCK:
            per, nbytes = _GGML_BLOCK[gtype]
            size = n // per * nbytes
        else:
            size = n * {GGML_F32: 4, GGML_F16: 2, GGML_BF16: 2}.get(gtype, 4)
        with open(self.path, "rb") as f:
            f.seek(self.data_start + offset)
            buf = f.read(size)
        arr = dequantize_ggml(buf, gtype, shape)
        if hf_name.endswith("q_proj.weight"):
            arr = _unpermute_rope(np.ascontiguousarray(arr), self.n_head)
        elif hf_name.endswith("k_proj.weight"):
            arr = _unpermute_rope(np.ascontiguousarray(arr), self.n_kv_head)
        return arr


def gguf_model_config(path: str):
    """ModelConfig from GGUF metadata (llama architecture)."""
    from ..engine.config import ModelConfig

    meta, tensors, _ = read_gguf_tensors(path)
    _require_llama_arch(meta, path)
    p = "llama."
    n_head = int(meta[p + "attention.head_count"])
    n_kv = int(meta.get(p + "attention.head_count_kv", n_head))
    hidden = int(meta[p + "embedding_length"])
    vocab = meta.get(p + "vocab_size")
    if vocab is None:
        vocab = len(meta.get("tokenizer.ggml.tokens") or [])
    return ModelConfig(
        vocab_size=int(vocab),
        hidden_size=hidden,
        intermediate_size=int(meta[p + "feed_forward_length"]),
        num_layers=int(meta[p + "block_count"]),
        num_heads=n_head,
        num_kv_heads=n_kv,
        head_dim=int(
            meta.get(p + "attention.key_length", hidden // n_head)
        ),
        rope_theta=float(meta.get(p + "rope.freq_base", 10000.0)),
        rms_norm_eps=float(
            meta.get(p + "attention.layer_norm_rms_epsilon", 1e-5)
        ),
        max_position=int(meta.get(p + "context_length", 4096)),
        tie_word_embeddings="output.weight" not in tensors,
        dtype="bfloat16",
    )


def load_gguf_params(
    path: str,
    cfg,
    dtype: Any = None,
    shardings: Optional[Dict[str, Any]] = None,
):
    """Assemble the engine's parameter pytree straight from a GGUF file."""
    from ..engine.weights import assemble_params

    view = _GgufHFView(path, cfg.num_heads, cfg.num_kv_heads)
    import jax.numpy as jnp

    return assemble_params(
        view, cfg, jnp.dtype(dtype or cfg.dtype), shardings
    )
