"""Embedding serving: /v1/embeddings behind the same engine stack.

Reference parity: lib/llm/src/http/service/openai.rs:212 (the embeddings
route) and protocols/openai/embeddings.rs + its stream aggregator -- the
reference delegates the vectors to an embedding-capable engine; here the
first-party trunk doubles as the embedder (engine/step.py:embed_step:
mean-pooled, L2-normalized final hidden states).

One engine class serves both deployment shapes:

- **local** (``in=http out=jax``): ``embed_fn`` is ``JaxEngine.embed``.
- **distributed** (``in=http out=dyn``): the worker serves
  ``EmbeddingEngine`` over its endpoint; the frontend's watcher builds a
  second ``EmbeddingEngine`` whose ``embed_fn`` forwards the token batches
  through a PushRouter to that endpoint (``router_embedder``).

The wire protocol is one request item ``{"token_batches": [[...]]}`` and
one response item ``{"embeddings": [[...]], "prompt_tokens": N}`` -- the
request is tokenized at the frontend so workers stay text-free, the same
split as the generate path (preprocessor tokenizes, backend detokenizes).
"""

from __future__ import annotations

import hashlib
import math
from typing import Any, AsyncIterator, Awaitable, Callable, List, Optional

from ..protocols.openai import INVALID_MARK, EmbeddingRequest, OpenAIError
from ..runtime.engine import Annotated, AsyncEngine, Context, ResponseStream
from .tokenizer import Tokenizer

Embedder = Callable[[List[List[int]]], Awaitable[List[List[float]]]]


class EmbeddingEngine(AsyncEngine):
    """AsyncEngine for embedding requests.

    Accepts either an ``EmbeddingRequest`` (frontend: texts are tokenized
    here) or the wire dict ``{"token_batches": [[...]]}`` (worker side).
    Yields exactly one item: ``{"embeddings": [...], "prompt_tokens": N}``.
    """

    def __init__(
        self,
        embed_fn: Embedder,
        tokenizer: Optional[Tokenizer] = None,
        max_input_tokens: Optional[int] = None,
    ) -> None:
        """``max_input_tokens`` (the engine's max_seq_len / the card's
        context_length) turns over-long inputs into 400s at the frontend
        instead of engine-side ValueErrors surfacing as 500s."""
        self.embed_fn = embed_fn
        self.tokenizer = tokenizer
        self.max_input_tokens = max_input_tokens

    def _tokenize(self, req: EmbeddingRequest) -> List[List[int]]:
        if req.token_batches is not None:
            batches = req.token_batches
        elif self.tokenizer is None:
            raise OpenAIError(
                "text input requires a tokenizer (this endpoint accepts"
                " pre-tokenized input only)"
            )
        else:
            batches = [self.tokenizer.encode(t) for t in req.texts]
        for i, b in enumerate(batches):
            if not b:
                raise OpenAIError(f"input {i} tokenized to zero tokens")
            if self.max_input_tokens is not None and len(b) > self.max_input_tokens:
                raise OpenAIError(
                    f"input {i} has {len(b)} tokens, over the model's"
                    f" {self.max_input_tokens}-token limit"
                )
        return batches

    async def generate(self, request: Context[Any]) -> AsyncIterator[Annotated]:
        data = request.data
        try:
            if isinstance(data, EmbeddingRequest):
                batches = self._tokenize(data)
            elif isinstance(data, dict) and "token_batches" in data:
                batches = data["token_batches"]
                if not (
                    isinstance(batches, list)
                    and batches
                    and all(isinstance(b, list) and b for b in batches)
                ):
                    raise OpenAIError(
                        "'token_batches' must be non-empty token lists"
                    )
                if self.max_input_tokens is not None:
                    for i, b in enumerate(batches):
                        if len(b) > self.max_input_tokens:
                            raise OpenAIError(
                                f"input {i} has {len(b)} tokens, over the"
                                f" {self.max_input_tokens}-token limit"
                            )
            else:
                raise OpenAIError("expected an embedding request")
        except OpenAIError as e:
            # stable wire marker: the distributed leg (router_embedder) maps
            # prologue errors carrying it back to a client-facing 400; other
            # prologue failures (engine crash) stay 500s
            raise OpenAIError(f"{INVALID_MARK}{e}") from e

        ctx = request.ctx

        async def gen() -> AsyncIterator[Annotated]:
            vectors = await self.embed_fn(batches)
            if not ctx.is_stopped():
                yield Annotated.from_data(
                    {
                        "embeddings": vectors,
                        "prompt_tokens": sum(len(b) for b in batches),
                    }
                )

        return ResponseStream(ctx, gen())


def router_embedder(router) -> Embedder:
    """An ``embed_fn`` that forwards token batches to a remote worker's
    embedding endpoint through a PushRouter (the distributed leg)."""

    async def embed(batches: List[List[int]]) -> List[List[float]]:
        from ..runtime.transports.request_plane import RemoteError

        try:
            stream = await router.generate(
                Context.new({"token_batches": batches})
            )
        except RemoteError as e:
            # the worker's EmbeddingEngine marks validation failures
            # (INVALID_MARK) before they cross the wire as flat RemoteError
            # messages; map those back to OpenAIError so the frontend
            # answers 400 with the worker's real reason, and leave genuine
            # worker faults as 500s
            msg = str(e)
            if INVALID_MARK in msg:
                raise OpenAIError(
                    msg.split(INVALID_MARK, 1)[1] or "invalid request"
                ) from e
            raise
        async for item in stream:
            if item.is_error():
                msg = item.error_message() or "embedding worker error"
                if INVALID_MARK in msg:
                    raise OpenAIError(msg.split(INVALID_MARK, 1)[1]) from None
                raise RuntimeError(msg)
            data = item.data or {}
            if "embeddings" in data:
                return data["embeddings"]
        raise RuntimeError("embedding worker returned no vectors")

    return embed


def fake_embedder(dim: int = 32) -> Embedder:
    """Deterministic, content-dependent unit vectors with no model -- the
    echo/mocker leg for wiring tests (same role the echo engines play for
    the generate path)."""

    async def embed(batches: List[List[int]]) -> List[List[float]]:
        out: List[List[float]] = []
        for toks in batches:
            h = hashlib.sha256(
                b",".join(str(t).encode() for t in toks)
            ).digest()
            vals = []
            seed = h
            while len(vals) < dim:
                seed = hashlib.sha256(seed).digest()
                vals.extend(b / 255.0 - 0.5 for b in seed)
            v = vals[:dim]
            norm = math.sqrt(sum(x * x for x in v)) or 1.0
            out.append([x / norm for x in v])
        return out

    return embed
