"""Standalone cluster components: router service and metrics service.

Reference parity:

- ``components/router`` (src/main.rs:28-120): a KV-router behind its own
  endpoint -- callers send ``{"token_ids": [...]}`` and get back
  ``{"worker_id": ..., "overlap_blocks": ...}``, letting non-Python
  frontends (or remote processes) use KV-aware placement without
  embedding the index.
- ``components/metrics`` (src/lib.rs:145-340, main.rs:115-258): scrapes
  worker ``ForwardPassMetrics``, subscribes to ``kv-hit-rate`` events,
  and serves cluster-level Prometheus gauges with the same family names
  (``llm_kv_blocks_active`` etc.), so reference dashboards translate.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
from typing import Any, AsyncIterator, Optional

from ..runtime.metrics import MetricsRegistry
from ..runtime.component import Component, DistributedRuntime, Namespace
from ..runtime.engine import Annotated, Context, EngineFn, ResponseStream
from .kv_router.router import KV_HIT_RATE_SUBJECT, KvRouter
from .kv_router.scheduler import KvRouterConfig

logger = logging.getLogger("dynamo.components")

ROUTER_COMPONENT = "router"


class RouterService:
    """Serve KV-aware worker selection as its own endpoint."""

    def __init__(
        self,
        runtime: DistributedRuntime,
        namespace: str,
        worker_component: str = "backend",
        block_size: int = 16,
        config: Optional[KvRouterConfig] = None,
        index_shards: int = 1,
    ) -> None:
        self.runtime = runtime
        self.ns = runtime.namespace(namespace)
        self.router = KvRouter(
            self.ns,
            self.ns.component(worker_component),
            block_size=block_size,
            config=config,
            index_shards=index_shards,
        )

    async def start(self) -> None:
        await self.router.start()
        await (
            self.ns.component(ROUTER_COMPONENT)
            .endpoint("generate")
            .serve(EngineFn(self._handle))
        )

    async def stop(self) -> None:
        await self.router.stop()

    async def _handle(self, request: Context[Any]) -> AsyncIterator[Annotated]:
        data = request.data or {}
        tokens = data.get("token_ids") or []

        async def gen() -> AsyncIterator[Annotated]:
            try:
                worker_id, overlap = await self.router.find_best_match(tokens)
                yield Annotated.from_data(
                    {"worker_id": worker_id, "overlap_blocks": overlap}
                )
            except Exception as e:
                yield Annotated.from_error(f"router: {e}")

        return ResponseStream(request.ctx, gen())


class MetricsService:
    """Cluster metrics component: aggregate worker load, expose Prometheus.

    Gauges (reference components/metrics naming): ``llm_kv_blocks_active``,
    ``llm_kv_blocks_total``, ``llm_requests_active_slots``,
    ``llm_requests_total_slots``, ``llm_load_avg``, ``llm_load_std``,
    ``llm_kv_hit_rate`` (cumulative average of per-selection events).
    """

    def __init__(
        self,
        runtime: DistributedRuntime,
        namespace: str,
        worker_component: str = "backend",
        scrape_interval_s: float = 0.5,
    ) -> None:
        from .kv_router.metrics_aggregator import KvMetricsAggregator

        self.runtime = runtime
        self.ns = runtime.namespace(namespace)
        self.aggregator = KvMetricsAggregator(
            self.ns.component(worker_component), interval_s=scrape_interval_s
        )
        self._metrics = MetricsRegistry()
        self.registry = self._metrics.registry

        def g(name: str, doc: str):
            return self._metrics.gauge(name, doc, ["component"])

        self.kv_active = g("llm_kv_blocks_active", "active KV blocks")
        self.kv_total = g("llm_kv_blocks_total", "total KV blocks")
        self.slots_active = g("llm_requests_active_slots", "active request slots")
        self.slots_total = g("llm_requests_total_slots", "total request slots")
        self.load_avg = g("llm_load_avg", "average worker load (kv usage)")
        self.load_std = g("llm_load_std", "stddev of worker load")
        self.hit_rate = g("llm_kv_hit_rate", "avg overlap/isl across selections")
        self._hit_events = 0
        self._hit_sum = 0.0
        self._sub = None
        self._sub_task: Optional[asyncio.Task] = None
        self._component_label = worker_component

    async def start(self) -> None:
        await self.aggregator.start()
        self._sub = await self.ns.subscribe(KV_HIT_RATE_SUBJECT)
        self._sub_task = asyncio.create_task(
            self._consume_hit_rate(), name="metrics-hit-rate"
        )

    async def stop(self) -> None:
        if self._sub_task is not None:
            self._sub_task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._sub_task
        if self._sub is not None:
            await self._sub.close()
        await self.aggregator.stop()

    async def _consume_hit_rate(self) -> None:
        assert self._sub is not None
        async for _subject, payload in self._sub:
            try:
                ev = json.loads(payload)
                isl = max(int(ev.get("isl_blocks", 0)), 1)
                self._hit_events += 1
                self._hit_sum += int(ev.get("overlap_blocks", 0)) / isl
            except Exception:
                logger.debug("bad kv-hit-rate payload", exc_info=True)

    def render(self) -> tuple:
        """(payload, content_type) -- refresh gauges from the latest scrape
        and render the Prometheus text exposition."""
        eps = self.aggregator.endpoints
        label = self._component_label
        kv_active = kv_total = sa = st = 0
        loads = []
        for m in eps.endpoints.values():
            kv_active += m.kv_active_blocks
            kv_total += m.kv_total_blocks
            sa += m.request_active_slots
            st += m.request_total_slots
            loads.append(m.gpu_cache_usage_perc)
        self.kv_active.labels(label).set(kv_active)
        self.kv_total.labels(label).set(kv_total)
        self.slots_active.labels(label).set(sa)
        self.slots_total.labels(label).set(st)
        if loads:
            avg = sum(loads) / len(loads)
            var = sum((l - avg) ** 2 for l in loads) / len(loads)
            self.load_avg.labels(label).set(avg)
            self.load_std.labels(label).set(var ** 0.5)
        if self._hit_events:
            self.hit_rate.labels(label).set(self._hit_sum / self._hit_events)
        return self._metrics.render()

    async def serve_http(self, host: str = "127.0.0.1", port: int = 9091):
        """Serve ``GET /metrics`` (reference :9091); returns (host, port)."""

        async def handle(reader, writer):
            try:
                await reader.readuntil(b"\r\n\r\n")
                payload, ctype = self.render()
                head = (
                    "HTTP/1.1 200 OK\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    "Connection: close\r\n\r\n"
                )
                writer.write(head.encode() + payload)
                await writer.drain()
            except Exception:
                logger.debug("metrics scrape reply failed", exc_info=True)
            finally:
                with contextlib.suppress(Exception):
                    writer.close()
                    await writer.wait_closed()

        self._http = await asyncio.start_server(handle, host, port)
        addr = self._http.sockets[0].getsockname()
        return addr[0], addr[1]
