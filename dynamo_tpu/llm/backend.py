"""Backend operator: incremental detokenization + stop-string jail.

Reference parity: lib/llm/src/backend.rs:63-110 -- wraps the token-level
engine (``ExecutionContext``); on the response path it turns token ids into
text via a ``DecodeStream`` and enforces *string* stop conditions the engine
cannot see: text that could be the beginning of a stop sequence is jailed
(held back) until it either completes the stop sequence (request finishes
with STOP, jailed text dropped) or diverges (jail flushes downstream).
"""

from __future__ import annotations

import time
from typing import Any, AsyncIterator, Dict, List, Optional

from ..protocols.common import FinishReason, PreprocessedRequest
from ..runtime import profiling
from ..runtime.engine import Annotated, AsyncEngine, Context, as_response_stream
from ..runtime.pipeline import Operator
from .tokenizer import Tokenizer


class StopJail:
    """Holdback buffer for partial stop-sequence matches."""

    def __init__(self, stops: List[str]) -> None:
        self.stops = [s for s in stops if s]
        self.held = ""

    def push(self, delta: str) -> tuple[str, bool]:
        """Feed a text delta; returns ``(releasable_text, stopped)``.

        When a stop string completes inside the buffer, everything before its
        first occurrence is released and ``stopped`` is True (the stop string
        itself is never emitted, matching OpenAI semantics).
        """
        if not self.stops:
            return delta, False
        buf = self.held + delta
        cut = min(
            (i for i in (buf.find(s) for s in self.stops) if i >= 0),
            default=-1,
        )
        if cut >= 0:
            self.held = ""
            return buf[:cut], True
        # longest suffix of buf that is a proper prefix of any stop string
        jail = 0
        for s in self.stops:
            for k in range(min(len(s) - 1, len(buf)), 0, -1):
                if buf.endswith(s[:k]):
                    jail = max(jail, k)
                    break
        self.held = buf[len(buf) - jail :] if jail else ""
        return buf[: len(buf) - jail] if jail else buf, False

    def flush(self) -> str:
        """Stream ended without a stop match: release whatever is jailed."""
        out, self.held = self.held, ""
        return out


class Backend(Operator):
    """Forward: pass the token request through.  Backward: detokenize and
    apply the stop jail, yielding BackendOutput-shaped dicts
    (``text``/``token_ids``/``finish_reason``)."""

    def __init__(self, tokenizer: Tokenizer) -> None:
        self.tokenizer = tokenizer

    async def generate(
        self, request: Context, next: AsyncEngine
    ) -> AsyncIterator[Annotated]:
        data = request.data
        req = (
            PreprocessedRequest.from_dict(data) if isinstance(data, dict) else data
        )
        stream = await as_response_stream(next, request.replace(req.to_dict()))
        decoder = self.tokenizer.decode_stream()
        jail = StopJail(req.stop_conditions.stop or [])
        ctx = request.ctx

        async def gen() -> AsyncIterator[Annotated]:
            stopped = False
            async for item in stream:
                if not isinstance(item, Annotated):
                    item = Annotated.from_data(item)
                if item.is_error() or item.data is None:
                    yield item
                    continue
                data: Dict[str, Any] = dict(item.data)
                token_ids = data.get("token_ids") or []
                # tick-phase profiling: detok runs on frontend tasks, not
                # the engine loop, so it feeds the phase histogram
                # directly (one attribute check when disabled)
                prof = profiling.profiler
                t_detok = time.perf_counter() if prof.enabled else None
                pieces = [decoder.step(t) for t in token_ids]
                # push per piece so a stop string completing mid-chunk cuts
                # the chunk at the completing token: tokens decoded after the
                # stop (a coalesced decode block can carry many) must be
                # neither emitted nor counted toward usage
                text, hit, n_used = "", False, len(token_ids)
                if jail.stops:
                    for i, p in enumerate(pieces):
                        t, hit = jail.push(p) if p else ("", False)
                        text += t
                        if hit:
                            n_used = i + 1
                            break
                else:
                    text = "".join(p for p in pieces if p)
                if t_detok is not None:
                    # keyed on the start stamp, not a re-read of enabled:
                    # a live enable between the two would otherwise record
                    # perf_counter's absolute value as a duration
                    prof.observe_phase(
                        "detok", time.perf_counter() - t_detok
                    )
                if hit:
                    # stop string completed: emit the releasable prefix, end
                    # the request, and tell the engine to stop decoding
                    stopped = True
                    out = {
                        "token_ids": token_ids[:n_used],
                        "text": text or None,
                        "finish_reason": FinishReason.STOP.value,
                    }
                    # logprob lists stay aligned with the truncated tokens
                    if data.get("logprobs") is not None:
                        out["logprobs"] = data["logprobs"][:n_used]
                    if data.get("top_logprobs") is not None:
                        out["top_logprobs"] = data["top_logprobs"][:n_used]
                    yield Annotated.from_data(out)
                    ctx.stop_generating()
                    break
                data["text"] = text or None
                fr = data.get("finish_reason")
                if fr:
                    # natural end: flush any jailed text first
                    tail = jail.flush()
                    if tail:
                        data["text"] = (text or "") + tail
                yield Annotated.from_data(data)
                if fr:
                    stopped = True
                    break
            if not stopped:
                # engine stream ended without a finish marker (e.g. killed)
                tail = jail.flush()
                if tail:
                    yield Annotated.from_data({"token_ids": [], "text": tail})

        return gen()
