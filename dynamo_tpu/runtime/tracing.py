"""Request tracing: lightweight spans keyed by request id.

Parity target (SURVEY.md 5.1): the reference threads request ids through
every hop and hangs tracing/profiling off them (distributed_runtime
tracing features).  Here the request id already crosses the request plane
in every frame; this module adds the span layer: timed, named sections
attached to a request id, collected in a process-local ring buffer.

Enable with ``DYN_TRACE=1`` (or ``enable()``); disabled spans cost one
attribute check.  Spans log at DEBUG as they close, and the collector's
``get(request_id)`` / ``dump()`` feed tests and debug endpoints.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger("dynamo.trace")


@dataclass
class Span:
    name: str
    request_id: str
    start_s: float
    end_s: float = 0.0
    attrs: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration_ms(self) -> float:
        return (self.end_s - self.start_s) * 1e3

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "request_id": self.request_id,
            "start_s": round(self.start_s, 6),
            "duration_ms": round(self.duration_ms, 3),
            **({"attrs": self.attrs} if self.attrs else {}),
        }


class TraceCollector:
    """Ring buffer of completed spans (thread-safe)."""

    def __init__(self, capacity: int = 4096) -> None:
        self._spans: "collections.deque[Span]" = collections.deque(
            maxlen=capacity
        )
        self._lock = threading.Lock()
        self.enabled = os.environ.get("DYN_TRACE", "") not in ("", "0", "false")

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
        logger.debug(
            "span %s [%s] %.2fms", span.name, span.request_id, span.duration_ms
        )

    def get(self, request_id: str) -> List[Span]:
        with self._lock:
            return [s for s in self._spans if s.request_id == request_id]

    def dump(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [s.to_dict() for s in self._spans]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


collector = TraceCollector()


class span:
    """``with span("prefill", request_id, tokens=128): ...`` -- no-op when
    tracing is disabled.  Also usable around ``async`` sections (the timing
    covers wall time, which is what serving spans want)."""

    def __init__(self, name: str, request_id: str = "", **attrs: Any) -> None:
        self.name = name
        self.request_id = request_id
        self.attrs = attrs
        self._span: Optional[Span] = None

    def __enter__(self) -> "span":
        if collector.enabled:
            self._span = Span(
                name=self.name,
                request_id=self.request_id,
                start_s=time.monotonic(),
                attrs=self.attrs,
            )
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._span is not None:
            self._span.end_s = time.monotonic()
            if exc is not None:
                self._span.attrs["error"] = repr(exc)
            collector.record(self._span)
        return False

    def set(self, **attrs: Any) -> None:
        if self._span is not None:
            self._span.attrs.update(attrs)
