"""Distributed request tracing: linked spans keyed by request id.

Parity target (SURVEY.md 5.1): the reference threads request ids through
every hop and hangs tracing/profiling off them (distributed_runtime
tracing features).  The request id already crosses the request plane in
every frame; this module adds the span layer on top of it:

* every span carries a ``trace_id`` / ``span_id`` / ``parent_span_id``
  triple, so the spans of one request form a tree even when they were
  recorded by different processes;
* the *trace context* (trace id + the currently-open span's id) propagates
  across hops inside request-plane frame headers
  (``transports/codec.encode_trace_context``) and is re-opened as the
  parent of the remote ingress span (``component._IngressHandler``);
* a per-process :class:`TraceCollector` keeps completed spans in a ring
  buffer with a per-request-id index (``get(request_id)`` is O(spans of
  that request), not O(ring)) and exports Chrome-trace/Perfetto JSON
  (``export`` / :func:`chrome_trace`).

Enable with ``DYN_TRACE=1`` (or ``collector.enable()``); a disabled span
costs one attribute check and adds **nothing** to wire frames.  Spans log
at DEBUG as they close; ``get(request_id)`` / ``dump()`` / ``export()``
feed tests, the ``GET /trace/{request_id}`` endpoint, the per-component
``_trace`` scrape endpoint, and the ``dynamo-tpu trace`` CLI.
"""

from __future__ import annotations

import collections
import contextvars
import logging
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional

logger = logging.getLogger("dynamo.trace")

# Monotonic->wall offset captured once at import: spans time themselves on
# the monotonic clock (durations immune to wall-clock steps) and exported
# dicts shift to wall-clock seconds so spans recorded by different
# processes land on one shared timeline.
_MONO_TO_WALL = time.time() - time.monotonic()


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass(frozen=True)
class TraceContext:
    """What propagates across a hop: the trace, and the parent span."""

    trace_id: str
    span_id: str

    def to_wire(self) -> Dict[str, str]:
        return {"tid": self.trace_id, "sid": self.span_id}

    @classmethod
    def from_wire(cls, d: Any) -> Optional["TraceContext"]:
        if not isinstance(d, dict) or not d.get("tid"):
            return None
        return cls(trace_id=str(d["tid"]), span_id=str(d.get("sid") or ""))


@dataclass
class Span:
    name: str
    request_id: str
    start_s: float  # time.monotonic()
    end_s: float = 0.0
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str = ""
    component: str = ""
    attrs: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration_ms(self) -> float:
        return (self.end_s - self.start_s) * 1e3

    def to_dict(self) -> Dict[str, Any]:
        """Portable form: ``start_s`` is wall-clock so dicts from several
        processes assemble onto one timeline (the ``_trace`` scrape)."""
        out: Dict[str, Any] = {
            "name": self.name,
            "request_id": self.request_id,
            "start_s": round(self.start_s + _MONO_TO_WALL, 6),
            "duration_ms": round(self.duration_ms, 3),
        }
        if self.trace_id:
            out["trace_id"] = self.trace_id
            out["span_id"] = self.span_id
        if self.parent_span_id:
            out["parent_span_id"] = self.parent_span_id
        if self.component:
            out["component"] = self.component
        if self.attrs:
            out["attrs"] = self.attrs
        return out


class TraceCollector:
    """Ring buffer of completed spans plus a per-request-id index
    (thread-safe).  The index evicts in lockstep with the ring, so a
    ``/trace/{request_id}`` hit never scans all ``capacity`` spans."""

    def __init__(self, capacity: int = 4096, binding_capacity: int = 4096) -> None:
        self._spans: "collections.deque[Span]" = collections.deque()
        self._capacity = capacity
        # request_id -> that request's spans, in record order (FIFO like the
        # ring, so eviction always removes the list head)
        self._index: Dict[str, List[Span]] = {}
        # request_id -> the trace context engine-side spans should attach to
        # (executor threads have no ambient contextvar)
        self._bindings: "collections.OrderedDict[str, TraceContext]" = (
            collections.OrderedDict()
        )
        self._binding_capacity = binding_capacity
        self._lock = threading.Lock()
        self.enabled = os.environ.get("DYN_TRACE", "") not in ("", "0", "false")
        # default component tag stamped onto spans opened in this process
        # (set once at serve time, e.g. "dynamo/backend")
        self.component = ""

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def record(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) >= self._capacity:
                old = self._spans.popleft()
                lst = self._index.get(old.request_id)
                if lst:
                    lst.pop(0)
                    if not lst:
                        del self._index[old.request_id]
            self._spans.append(span)
            self._index.setdefault(span.request_id, []).append(span)
        logger.debug(
            "span %s [%s] %.2fms", span.name, span.request_id, span.duration_ms
        )

    def get(self, request_id: str) -> List[Span]:
        with self._lock:
            return list(self._index.get(request_id, ()))

    def dump(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [s.to_dict() for s in self._spans]

    def export(self, request_id: Optional[str] = None) -> Dict[str, Any]:
        """Chrome-trace/Perfetto JSON for one request (or everything)."""
        spans = self.get(request_id) if request_id else None
        if spans is not None:
            dicts = [s.to_dict() for s in spans]
        else:
            dicts = self.dump()
        return chrome_trace(dicts)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._index.clear()
            self._bindings.clear()

    # -- request-id -> trace-context bindings ------------------------------

    def bind(self, request_id: str, ctx: TraceContext) -> None:
        with self._lock:
            self._bindings[request_id] = ctx
            self._bindings.move_to_end(request_id)
            while len(self._bindings) > self._binding_capacity:
                self._bindings.popitem(last=False)

    def binding(self, request_id: str) -> Optional[TraceContext]:
        with self._lock:
            return self._bindings.get(request_id)


collector = TraceCollector()

# The currently-open span's context in this task tree; spans opened on
# executor threads fall back to the collector's request-id binding.
_current: "contextvars.ContextVar[Optional[TraceContext]]" = (
    contextvars.ContextVar("dyn_trace_ctx", default=None)
)


def current_context() -> Optional[TraceContext]:
    return _current.get()


def wire_context(request_id: str = "") -> Optional[Dict[str, str]]:
    """Header payload for an outgoing hop, or None (tracing disabled, or no
    active trace to continue).  The single call egress sites make -- one
    attribute check when tracing is off."""
    if not collector.enabled:
        return None
    ctx = _current.get()
    if ctx is None and request_id:
        ctx = collector.binding(request_id)
    return ctx.to_wire() if ctx is not None else None


class span:
    """``with span("prefill", request_id, tokens=128): ...`` -- no-op when
    tracing is disabled.  Also usable around ``async`` sections (the timing
    covers wall time, which is what serving spans want).

    Parent resolution, in order: the explicit ``parent`` TraceContext (a
    hop's decoded wire context), the task-local current span, the
    collector's request-id binding.  No parent at all roots a new trace.
    ``bind=True`` additionally binds the request id to this span's context,
    so spans opened later on other threads (the engine executor) link under
    it."""

    __slots__ = (
        "name", "request_id", "parent", "component", "bind", "attrs",
        "_span", "_token",
    )

    def __init__(
        self,
        name: str,
        request_id: str = "",
        parent: Optional[TraceContext] = None,
        component: Optional[str] = None,
        bind: bool = False,
        **attrs: Any,
    ) -> None:
        self.name = name
        self.request_id = request_id
        self.parent = parent
        self.component = component
        self.bind = bind
        self.attrs = attrs
        self._span: Optional[Span] = None
        self._token: Optional[contextvars.Token] = None

    def __enter__(self) -> "span":
        if not collector.enabled:
            return self
        parent = self.parent or _current.get()
        if parent is None and self.request_id:
            parent = collector.binding(self.request_id)
        trace_id = parent.trace_id if parent is not None else _new_id()
        span_id = _new_id()
        self._span = Span(
            name=self.name,
            request_id=self.request_id,
            start_s=time.monotonic(),
            trace_id=trace_id,
            span_id=span_id,
            parent_span_id=parent.span_id if parent is not None else "",
            component=(
                self.component if self.component is not None
                else collector.component
            ),
            attrs=self.attrs,
        )
        ctx = TraceContext(trace_id, span_id)
        self._token = _current.set(ctx)
        if self.bind and self.request_id:
            collector.bind(self.request_id, ctx)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            try:
                _current.reset(self._token)
            except ValueError:
                # manual enter/exit pairs may straddle task contexts (the
                # ingress span exits inside the response generator's task);
                # the var is task-local, so a failed reset leaks nothing
                pass
            self._token = None
        if self._span is not None:
            self._span.end_s = time.monotonic()
            if exc is not None:
                self._span.attrs["error"] = repr(exc)
            collector.record(self._span)
            self._span = None
        return False

    @property
    def context(self) -> Optional[TraceContext]:
        """The open span's context (None when tracing is disabled)."""
        if self._span is None:
            return None
        return TraceContext(self._span.trace_id, self._span.span_id)

    def set(self, **attrs: Any) -> None:
        if self._span is not None:
            self._span.attrs.update(attrs)


def chrome_trace(span_dicts: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome-trace ("Trace Event Format") JSON object from span dicts
    (``Span.to_dict`` output, possibly merged from several processes).
    Loads in chrome://tracing and ui.perfetto.dev: one pid per component,
    complete ("X") events in wall-clock microseconds, span/parent ids in
    ``args`` so the tree survives the export."""
    pids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for d in span_dicts:
        comp = str(d.get("component") or "process")
        pid = pids.setdefault(comp, len(pids) + 1)
        args: Dict[str, Any] = {
            "request_id": d.get("request_id", ""),
            "trace_id": d.get("trace_id", ""),
            "span_id": d.get("span_id", ""),
            "parent_span_id": d.get("parent_span_id", ""),
        }
        args.update(d.get("attrs") or {})
        events.append(
            {
                "name": d.get("name", ""),
                "cat": "dynamo",
                "ph": "X",
                "ts": round(float(d.get("start_s", 0.0)) * 1e6, 3),
                "dur": round(
                    max(float(d.get("duration_ms", 0.0)), 0.0) * 1e3, 3
                ),
                "pid": pid,
                "tid": 1,
                "args": args,
            }
        )
    for comp, pid in pids.items():
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 1,
                "args": {"name": comp},
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}
