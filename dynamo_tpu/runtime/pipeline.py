"""Pipeline graph: composable request/response processing stages.

The reference models pipelines as doubly-linked node graphs
(lib/runtime/src/pipeline/nodes.rs: Source/Sink/Operator/ServiceFrontend/
ServiceBackend with ``link()`` chaining).  In asyncio the same dataflow is
expressed directly: an :class:`Operator` transforms the request on the way
*forward* and the response stream on the way *backward*, and ``link`` folds a
chain of operators onto a terminal engine, producing one composed
:class:`~dynamo_tpu.runtime.engine.AsyncEngine`.

    frontend = link(OpenAIPreprocessor(...), Backend(...), push_router)
    stream = await frontend.generate(Context.new(request))

This keeps the reference's bidirectional-operator shape (preprocessor maps
OpenAI -> tokens forward and token deltas -> OpenAI chunks backward) without
the node/edge bookkeeping that tokio's ownership model required.
"""

from __future__ import annotations

from typing import AsyncIterator, Generic, TypeVar

from .engine import (
    AsyncEngine,
    Context,
    ResponseStream,
    as_response_stream,
    ensure_response_stream,
)

In = TypeVar("In")
Out = TypeVar("Out")
RespIn = TypeVar("RespIn")
RespOut = TypeVar("RespOut")


class Operator(Generic[In, Out, RespIn, RespOut]):
    """A bidirectional pipeline stage.

    Subclasses implement :meth:`generate`, receiving the inbound request and
    the downstream engine (``next``), and returning the outbound response
    stream.  Reference: the Operator trait in pipeline/nodes.rs; e.g.
    OpenAIPreprocessor (preprocessor.rs:64) is an operator from OpenAI requests
    to token requests.
    """

    async def generate(
        self, request: Context[In], next: AsyncEngine[Out, RespIn]
    ) -> AsyncIterator[RespOut]:
        raise NotImplementedError


class _Linked(Generic[In, RespOut]):
    """An Operator bound to its downstream engine: itself an AsyncEngine."""

    def __init__(self, op: Operator, next: AsyncEngine) -> None:
        self._op = op
        self._next = next

    async def generate(self, request: Context) -> AsyncIterator:
        return ensure_response_stream(
            request.ctx, await self._op.generate(request, self._next)
        )


def link(*stages) -> AsyncEngine:
    """Fold ``(op1, op2, ..., terminal_engine)`` into one engine.

    The last element must be an AsyncEngine (has ``generate(request)``); all
    preceding elements must be Operators.
    """
    if not stages:
        raise ValueError("link() requires at least a terminal engine")
    engine = stages[-1]
    if isinstance(engine, Operator):
        raise TypeError("last stage of link() must be a terminal AsyncEngine")
    for op in reversed(stages[:-1]):
        if not isinstance(op, Operator):
            raise TypeError(f"intermediate stage {op!r} must be an Operator")
        engine = _Linked(op, engine)
    return engine


class MapOperator(Operator[In, Out, RespIn, RespOut]):
    """Operator from two plain functions: request map + response map."""

    def __init__(self, fwd, bwd) -> None:
        self._fwd = fwd
        self._bwd = bwd

    async def generate(self, request: Context, next: AsyncEngine):
        mapped = request.map(self._fwd)
        stream = await as_response_stream(next, mapped)

        async def gen():
            async for item in stream:
                yield self._bwd(item)

        return gen()
