"""Runtime utilities: critical tasks, object pool, logging config.

Parity targets (SURVEY.md 2.1 Utils row):

- ``CriticalTaskExecutionHandle`` -- reference runtime/src/utils/task.rs:42.
  A background task whose failure must not be swallowed: an unhandled
  exception (not cancellation) invokes ``on_failure`` -- typically the
  runtime's shutdown -- so a dead keepalive/watcher loop takes the process
  down loudly instead of leaving a zombie worker registered in the hub.
- ``Pool`` -- reference runtime/src/utils/pool.rs:23,111,197.  A bounded
  async reusable-object pool (codec scratch buffers, client connections):
  ``acquire`` hands out an idle object or builds one up to ``max_size``,
  then blocks; releasing returns the object for reuse.
- ``configure_logging`` -- reference lib/runtime logging config (DYN_LOG
  env filter), plus a JSONL mode for log aggregation pipelines.
- ``log_throttled`` -- rate-limited logging for hot paths: a per-token or
  per-request failure site logs at most once per interval per key (with a
  suppressed-hit count), so a production fault is diagnosable without a
  log flood feeding back into the latency it reports on.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import sys
import time
from typing import Any, Awaitable, Callable, Generic, Optional, TypeVar

logger = logging.getLogger("dynamo.runtime")

T = TypeVar("T")


class CriticalTaskExecutionHandle:
    """Run a coroutine whose failure is fatal to its owner.

    ``on_failure(exc)`` fires exactly once, from the task's own loop, when
    the coroutine raises anything but ``asyncio.CancelledError``.  Normal
    return and cancellation are clean exits.
    """

    def __init__(
        self,
        coro: Awaitable[Any],
        on_failure: Callable[[BaseException], Any],
        name: str = "critical-task",
    ) -> None:
        self.name = name
        self._on_failure = on_failure
        # held until the guard first runs: a cancel() that lands before the
        # guard task is ever scheduled must close the inner coroutine, or
        # it is garbage-collected un-awaited ("coroutine ... was never
        # awaited" at interpreter shutdown)
        self._pending_coro: Optional[Any] = coro
        self._task = asyncio.ensure_future(self._guard(coro))

    async def _guard(self, coro: Awaitable[Any]) -> Any:
        self._pending_coro = None
        try:
            return await coro
        except asyncio.CancelledError:
            raise
        except BaseException as e:  # noqa: BLE001 -- the whole point
            logger.error("critical task %r failed: %s", self.name, e)
            try:
                result = self._on_failure(e)
                if asyncio.iscoroutine(result):
                    await result
            except Exception:
                logger.exception("on_failure handler for %r failed", self.name)
            raise

    def done(self) -> bool:
        return self._task.done()

    def cancel(self) -> None:
        """Non-blocking, drop-in for asyncio.Task.cancel()."""
        coro = self._pending_coro
        if coro is not None and asyncio.iscoroutine(coro):
            self._pending_coro = None
            coro.close()
        self._task.cancel()

    async def wait_stopped(self) -> None:
        with contextlib.suppress(asyncio.CancelledError, Exception):
            await self._task

    def __await__(self):
        return self._task.__await__()


class Pool(Generic[T]):
    """Bounded async pool of reusable objects.

    ``factory`` builds a new object when the pool is empty and fewer than
    ``max_size`` exist; beyond that, ``acquire`` waits for a release.  Use
    ``async with pool.handle() as obj`` for scoped acquire/release.
    """

    def __init__(
        self,
        factory: Callable[[], T],
        max_size: int = 16,
    ) -> None:
        if max_size < 1:
            raise ValueError("max_size must be >= 1")
        self._factory = factory
        self._max = max_size
        self._idle: list = []
        self._created = 0
        self._waiters: asyncio.Queue = asyncio.Queue()
        self._sem = asyncio.Semaphore(max_size)

    @property
    def size(self) -> int:
        """Objects in existence (idle + acquired)."""
        return self._created

    @property
    def idle(self) -> int:
        return len(self._idle)

    async def acquire(self) -> T:
        await self._sem.acquire()
        if self._idle:
            return self._idle.pop()
        obj = self._factory()
        if asyncio.iscoroutine(obj):
            obj = await obj
        self._created += 1
        return obj

    def release(self, obj: T) -> None:
        self._idle.append(obj)
        self._sem.release()

    def handle(self):
        pool = self

        class _Handle:
            async def __aenter__(self):
                self.obj = await pool.acquire()
                return self.obj

            async def __aexit__(self, *exc):
                pool.release(self.obj)
                return False

        return _Handle()


# key -> [last-emit monotonic time, hits suppressed since]
_THROTTLE: dict = {}


def log_throttled(
    log: logging.Logger,
    key: str,
    msg: str,
    *args: Any,
    level: int = logging.WARNING,
    interval_s: float = 5.0,
    exc_info: bool = False,
) -> None:
    """Log at most once per ``interval_s`` seconds per ``key``.

    Suppressed hits are counted and reported on the next emitted record,
    so the log stays honest about failure volume without flooding.  GIL
    atomicity is sufficient here: a racing duplicate emission or an
    off-by-one suppressed count is harmless for diagnostics.
    """
    now = time.monotonic()
    st = _THROTTLE.get(key)
    if st is not None and now - st[0] < interval_s:
        st[1] += 1
        return
    suppressed = st[1] if st is not None else 0
    _THROTTLE[key] = [now, 0]
    if suppressed:
        msg = f"{msg} [{suppressed} similar suppressed]"
    log.log(level, msg, *args, exc_info=exc_info)


def reset_throttle() -> None:
    """Tests only: forget throttle history."""
    _THROTTLE.clear()


class _JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(time.time(), 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info and record.exc_info[0] is not None:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry)


def configure_logging(
    default_level: str = "INFO", stream=None
) -> None:
    """Apply the ``DYN_LOG`` filter spec and optional JSONL mode.

    ``DYN_LOG`` grammar (reference ``DYN_LOG`` / env_logger style):
    comma-separated ``[logger=]level`` terms -- e.g.
    ``DYN_LOG=debug`` (root), ``DYN_LOG=warn,dynamo.engine=debug``.
    ``DYN_LOG_JSONL=1`` switches the handler to one-JSON-object-per-line.
    """
    spec = os.environ.get("DYN_LOG", "")
    jsonl = os.environ.get("DYN_LOG_JSONL", "") not in ("", "0", "false")

    root_level = default_level.upper()
    per_logger = {}
    for term in filter(None, (t.strip() for t in spec.split(","))):
        if "=" in term:
            name, _, lvl = term.partition("=")
            per_logger[name.strip()] = lvl.strip().upper()
        else:
            root_level = term.upper()
    alias = {"WARN": "WARNING", "ERR": "ERROR", "TRACE": "DEBUG"}
    root_level = alias.get(root_level, root_level)

    handler = logging.StreamHandler(stream or sys.stderr)
    if jsonl:
        handler.setFormatter(_JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(name)s %(levelname)s %(message)s"
            )
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, root_level, logging.INFO))
    for name, lvl in per_logger.items():
        lvl = alias.get(lvl, lvl)
        logging.getLogger(name).setLevel(getattr(logging, lvl, logging.INFO))
