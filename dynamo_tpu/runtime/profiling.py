"""Tick-phase profiler + flight recorder: the host-side performance plane.

BENCH_r05 showed the *host* tick loop -- not the device -- bounding serving
speed (5.7 dispatches/s against 183 decode_steps/s), and nothing measured
how a tick's wall time splits across scheduling, batch assembly, dispatch
enqueue, device wait, commit, detokenization, and stream fanout.  This
module is that measurement:

* :class:`TickProfiler` -- per-tick phase accounting on
  ``time.perf_counter_ns`` marks.  The engine's tick loop opens a
  :class:`TickRecord` per iteration and attributes elapsed time to named
  phases (``plan``, ``assemble``, ``dispatch``, ``device_wait``,
  ``commit``, ``fanout``, ``onboard``; off-loop contributors like the
  Backend's ``detok`` feed the same histogram via :meth:`observe_phase`).
  Completed records land in a bounded ring and feed
  ``dynamo_tick_phase_seconds{phase}`` histograms, a
  ``dynamo_tick_host_occupancy`` gauge (host time / tick wall), and
  ``dynamo_tick_dispatch_gap_seconds`` -- the host-observed gap between
  the previous dispatch's results landing and the next dispatch being
  enqueued, the exact quantity ROADMAP item 2 ("attack the host-side
  tick loop") optimizes.

* :class:`FlightRecorder` -- on-demand snapshots of the last-N tick
  records, recent SLO violations, and registered component state (engine
  queue/KV occupancy), taken at failure edges (deadline expiry, worker
  loss, breaker open) so chaos postmortems read one JSON blob instead of
  log archaeology.  Served at ``GET /debug/flightrec``.

Overhead discipline (the ``FaultInjector`` pattern): disabled profiling is
one attribute check per site --

    tick = profiler.begin_tick() if profiler.enabled else None
    ...
    if tick is not None:
        tick.mark("plan")

Enable with ``DYN_TICK_PROFILE=1`` (or ``profiler.enable()``, or
``POST /profile/ticks {"enabled": true}`` on a live frontend).  Ring
capacity: ``DYN_TICK_RING`` (default 1024 ticks).

Export: tick records convert to the same span-dict shape
``runtime/tracing.py`` speaks, so :func:`chrome_trace` merges phase
lanes with the PR-3 request span tree into one Chrome-trace/Perfetto
timeline (``GET /profile/ticks``, ``python -m dynamo_tpu profile``).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from . import tracing

# Phase-duration buckets: a tick phase spans ~10us (a no-op plan pass) to
# ~100ms+ (a huge prefill's device wait on a tunneled chip).
PHASE_BUCKETS = (
    1e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

# The tick phases the engine marks, in canonical display order.  "other"
# absorbs unattributed slivers so a record's phases always sum to its wall.
PHASES = (
    "onboard",     # deliveries / swap-ins / prefetch + offload driving
    "plan",        # scheduler plan, admission, capacity, lane revival
    "assemble",    # host-side batch assembly (packed ragged layout, arrays)
    "dispatch",    # device enqueue (jitted call issue) + dispatch bookkeeping
    "device_wait", # blocked on device results (the one designed sync point)
    "commit",      # host commit walk (token unpack, stop rules, events)
    "fanout",      # stream fanout: per-request queue puts
    "detok",       # incremental detokenization (off-loop: Backend operator)
    "other",       # unattributed tick remainder
)


@dataclass
class TickRecord:
    """One completed tick of an engine loop."""

    idx: int
    start_s: float  # time.monotonic()
    wall_s: float = 0.0
    phases: Dict[str, float] = field(default_factory=dict)
    dispatches: Dict[str, int] = field(default_factory=dict)
    # host-observed dispatch gap(s) closed this tick: seconds between the
    # previous dispatch's results materializing on host and the next
    # dispatch being enqueued (upper bound on true device idle)
    gap_s: float = 0.0
    n_gaps: int = 0

    @property
    def host_s(self) -> float:
        return max(self.wall_s - self.phases.get("device_wait", 0.0), 0.0)

    @property
    def host_occupancy(self) -> float:
        return min(self.host_s / self.wall_s, 1.0) if self.wall_s > 0 else 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "idx": self.idx,
            "start_s": round(self.start_s + tracing._MONO_TO_WALL, 6),
            "wall_ms": round(self.wall_s * 1e3, 4),
            "host_occupancy": round(self.host_occupancy, 4),
            "phases_ms": {
                k: round(v * 1e3, 4) for k, v in self.phases.items()
            },
            "dispatches": dict(self.dispatches),
            "gap_ms": round(self.gap_s * 1e3, 4),
            "n_gaps": self.n_gaps,
        }

    def to_span_dicts(self) -> List[Dict[str, Any]]:
        """Span-dict form (``tracing.Span.to_dict`` shape) so tick phases
        merge with the request span tree in one Chrome-trace export: the
        tick itself is a parent span, each phase a sequential child laid
        out in canonical phase order."""
        base = self.start_s + tracing._MONO_TO_WALL
        tid = f"tick-{self.idx}"
        out: List[Dict[str, Any]] = [
            {
                "name": "tick",
                "request_id": tid,
                "start_s": round(base, 6),
                "duration_ms": round(self.wall_s * 1e3, 4),
                "component": "engine.tick",
                "attrs": {
                    "dispatches": dict(self.dispatches),
                    "host_occupancy": round(self.host_occupancy, 4),
                },
            }
        ]
        off = 0.0
        for name in PHASES:
            dur = self.phases.get(name, 0.0)
            if dur <= 0.0:
                continue
            out.append(
                {
                    "name": name,
                    "request_id": tid,
                    "start_s": round(base + off, 6),
                    "duration_ms": round(dur * 1e3, 4),
                    "component": "engine.tick",
                }
            )
            off += dur
        return out


class _Tick:
    """One in-progress tick: phase marks accumulate elapsed time since the
    previous mark.  Produced by :meth:`TickProfiler.begin_tick`; closed by
    :meth:`TickProfiler.finish_tick` (or dropped via ``discard``)."""

    __slots__ = ("profiler", "record", "_last_ns", "_start_ns", "discarded")

    def __init__(self, profiler: "TickProfiler", idx: int) -> None:
        self.profiler = profiler
        self.record = TickRecord(idx=idx, start_s=time.monotonic())
        self._start_ns = time.perf_counter_ns()
        self._last_ns = self._start_ns
        self.discarded = False

    def mark(self, phase: str) -> None:
        """Attribute time since the previous mark (or tick start) to
        ``phase``.  Phases may repeat; durations accumulate."""
        now = time.perf_counter_ns()
        phases = self.record.phases
        phases[phase] = phases.get(phase, 0.0) + (now - self._last_ns) * 1e-9
        self._last_ns = now

    def note_dispatch(self, kind: str) -> None:
        """A device dispatch was just enqueued: count it and close the
        dispatch gap against the most recent results-ready stamp."""
        d = self.record.dispatches
        d[kind] = d.get(kind, 0) + 1
        prof = self.profiler
        ready = prof._last_ready
        if ready is not None:
            prof._last_ready = None
            gap = max(time.monotonic() - ready, 0.0)
            self.record.gap_s += gap
            self.record.n_gaps += 1
            prof._observe_gap(gap)

    def note_zero_gap(self) -> None:
        """Results landed while ANOTHER dispatch was already queued on
        device (the async pipeline's steady state): the device-idle gap
        this sample represents is zero by construction, so record it as
        such -- the gap_p50 series stays honest instead of timing a
        ready->enqueue interval the device never idled through."""
        self.record.n_gaps += 1
        prof = self.profiler
        prof._last_ready = None
        prof._observe_gap(0.0)

    def discard(self) -> None:
        self.discarded = True


class TickProfiler:
    """Process-wide tick-phase profiler (module instance: :data:`profiler`).

    Thread model: one tick is driven by one engine loop at a time (the
    loop awaits every executor hop before the next mark), so ``_Tick`` is
    lock-free; the completed-record ring takes a lock (HTTP readers)."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        if capacity is None:
            try:
                capacity = int(os.environ.get("DYN_TICK_RING", "1024"))
            except ValueError:
                capacity = 1024
        self.capacity = max(capacity, 8)
        self.enabled = os.environ.get("DYN_TICK_PROFILE", "") not in (
            "", "0", "false",
        )
        self._ring: "collections.deque[TickRecord]" = collections.deque(
            maxlen=self.capacity
        )
        self._idx = 0
        self._lock = threading.Lock()
        # monotonic stamp of the most recent "previous dispatch's results
        # are on host" event; consumed by the next dispatch enqueue
        self._last_ready: Optional[float] = None
        # per-entry XLA compile events (fed by runtime.compile_sentry);
        # cleared with the ring so bench legs read per-leg counts
        self._compiles: Dict[str, int] = {}

    # -- lifecycle ---------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._compiles.clear()
        self._last_ready = None

    # -- tick accounting ---------------------------------------------------

    def begin_tick(self) -> _Tick:
        self._idx += 1
        return _Tick(self, self._idx)

    def finish_tick(self, tick: _Tick) -> None:
        """Close a tick: trailing time becomes ``other``; empty ticks
        (no dispatch, no device wait) are dropped so stall-poll loops do
        not flood the ring with no-op records."""
        if tick.discarded:
            return
        tick.mark("other")
        rec = tick.record
        rec.wall_s = (time.perf_counter_ns() - tick._start_ns) * 1e-9
        if not rec.dispatches and "device_wait" not in rec.phases:
            return
        with self._lock:
            self._ring.append(rec)
        self._observe_record(rec)

    def note_compile_event(self, entry: str) -> None:
        """One XLA compilation attributed to ``entry`` (compile_sentry
        calls this on every event so tick summaries price recompiles next
        to the phases they stall)."""
        if not self.enabled:
            return
        with self._lock:
            self._compiles[entry] = self._compiles.get(entry, 0) + 1

    def note_results_ready(self) -> None:
        """The pending dispatch's outputs just materialized on host: from
        here until the next enqueue, the device has nothing new from us."""
        self._last_ready = time.monotonic()

    def observe_phase(self, phase: str, seconds: float) -> None:
        """Off-tick contribution (e.g. the Backend's detok loop runs on
        frontend tasks, not the engine loop): feeds the phase histogram
        only, never a tick record."""
        self._phase_hist().labels(phase).observe(max(seconds, 0.0))

    # -- metrics (lazy: respects metrics.set_default in tests) -------------

    def _phase_hist(self):
        from . import metrics as rtm

        return rtm.default_registry().histogram(
            "dynamo_tick_phase_seconds",
            "Host tick-loop time per phase",
            ["phase"],
            buckets=PHASE_BUCKETS,
        )

    def _observe_gap(self, gap_s: float) -> None:
        from . import metrics as rtm

        rtm.default_registry().histogram(
            "dynamo_tick_dispatch_gap_seconds",
            "Host-observed gap between a dispatch's results landing and "
            "the next dispatch being enqueued (upper bound on device idle)",
            buckets=PHASE_BUCKETS,
        ).observe(max(gap_s, 0.0))

    def _observe_record(self, rec: TickRecord) -> None:
        from . import metrics as rtm

        reg = rtm.default_registry()
        hist = self._phase_hist()
        for name, dur in rec.phases.items():
            hist.labels(name).observe(max(dur, 0.0))
        reg.histogram(
            "dynamo_tick_wall_seconds",
            "Engine tick wall time",
            buckets=PHASE_BUCKETS,
        ).observe(max(rec.wall_s, 0.0))
        reg.gauge(
            "dynamo_tick_host_occupancy",
            "Fraction of the last tick's wall spent on host work "
            "(1 - device_wait/wall); ~1.0 means the host bounds serving",
        ).set(rec.host_occupancy)
        reg.counter(
            "dynamo_ticks_total", "Engine ticks profiled"
        ).inc()

    # -- read side ---------------------------------------------------------

    def records(self, last: Optional[int] = None) -> List[TickRecord]:
        with self._lock:
            recs = list(self._ring)
        return recs[-last:] if last else recs

    def recent_host_occupancy(self, last: int = 32) -> Optional[float]:
        """Mean host occupancy over the last ``last`` completed ticks, or
        ``None`` when nothing has been profiled (disabled profiler, cold
        ring).  The adaptive multi-step decode controller's signal
        (engine ``_multistep_plan_k``): a host-bound loop (occupancy near
        1) is exactly the condition K amortizes, so the controller jumps
        straight to its ceiling instead of ramping."""
        recs = self.records(last)
        if not recs:
            return None
        return sum(r.host_occupancy for r in recs) / len(recs)

    def summary(self) -> Dict[str, Any]:
        """Aggregate over the ring: per-phase totals + fractions of host
        time, mean host occupancy, dispatch-gap percentiles, tick count.
        The bench's serving line prints the top-3 phases from here."""
        recs = self.records()
        with self._lock:
            compiles = dict(self._compiles)
        totals: Dict[str, float] = {}
        gaps: List[float] = []
        wall = host = 0.0
        disp = 0
        for r in recs:
            for k, v in r.phases.items():
                totals[k] = totals.get(k, 0.0) + v
            if r.n_gaps:
                gaps.append(r.gap_s / r.n_gaps)
            wall += r.wall_s
            host += r.host_s
            disp += sum(r.dispatches.values())
        host_phases = sorted(
            (
                (k, v) for k, v in totals.items()
                if k not in ("device_wait", "other")
            ),
            key=lambda kv: kv[1],
            reverse=True,
        )
        gaps.sort()

        def pct(p: float) -> Optional[float]:
            if not gaps:
                return None
            i = min(int(p * len(gaps)), len(gaps) - 1)
            return round(gaps[i] * 1e3, 3)

        return {
            "ticks": len(recs),
            "dispatches": disp,
            "wall_s": round(wall, 6),
            "host_s": round(host, 6),
            "host_occupancy": round(host / wall, 4) if wall else None,
            "phase_totals_s": {
                k: round(v, 6) for k, v in sorted(totals.items())
            },
            "top_phases": [
                [k, round(v, 6)] for k, v in host_phases
            ],
            "gap_p50_ms": pct(0.50),
            "gap_p95_ms": pct(0.95),
            "compile_events": dict(sorted(compiles.items())),
        }

    def chrome_trace(
        self, span_dicts: Optional[List[Dict[str, Any]]] = None
    ) -> Dict[str, Any]:
        """Chrome-trace JSON of the tick ring, merged with request spans
        when given (``tracing.collector.dump()``): phases land on an
        ``engine.tick`` process row next to the span tree's components."""
        dicts: List[Dict[str, Any]] = list(span_dicts or [])
        for rec in self.records():
            dicts.extend(rec.to_span_dicts())
        return tracing.chrome_trace(dicts)


profiler = TickProfiler()


async def capture_device_trace(
    duration_s: float, log_dir: Optional[str] = None
) -> Dict[str, Any]:
    """Bounded-duration ``jax.profiler`` device trace (``POST
    /profile/device``).  Degrades gracefully: on CPU-only stacks (or with
    jax absent / a capture already running) it returns ``ok=False`` with
    the reason instead of raising -- profiling must never take a serving
    process down."""
    import asyncio

    duration_s = min(max(float(duration_s), 0.05), 30.0)
    if log_dir is None:
        log_dir = os.path.join(
            os.environ.get("TMPDIR", "/tmp"),
            f"dynamo-device-trace-{int(time.time())}",
        )
    try:
        import jax

        jax.profiler.start_trace(log_dir)
    except Exception as e:
        return {"ok": False, "error": f"device trace unavailable: {e}"}
    try:
        await asyncio.sleep(duration_s)
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            return {"ok": False, "error": f"stop_trace failed: {e}"}
    return {"ok": True, "log_dir": log_dir, "duration_s": duration_s}


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded snapshots of "what was the system doing" at failure edges.

    Components register state providers (``add_provider``); a trigger site
    calls :meth:`snapshot` with a reason and gets back a snapshot id it can
    attach to the error frame / span / 504 body.  Snapshots keep the last
    ``tick_window`` tick records and the SLO plane's recent violations, so
    a chaos postmortem starts from one ``GET /debug/flightrec/{id}``.

    Per-reason throttling (``min_interval_s``) bounds snapshot work under
    mass failure (a deadline storm must not turn the recorder into the
    next bottleneck): a throttled trigger reuses the previous snapshot id.
    """

    def __init__(
        self,
        capacity: int = 16,
        tick_window: int = 64,
        min_interval_s: float = 0.25,
    ) -> None:
        self.capacity = capacity
        self.tick_window = tick_window
        self.min_interval_s = min_interval_s
        self._snaps: "collections.OrderedDict[str, Dict[str, Any]]" = (
            collections.OrderedDict()
        )
        self._providers: Dict[str, Callable[[], Any]] = {}
        self._last_by_reason: Dict[str, tuple] = {}  # reason -> (t, id)
        self._lock = threading.Lock()
        self._seq = 0

    def add_provider(self, name: str, fn: Callable[[], Any]) -> str:
        """Register a state provider; returns the key it landed under.
        A taken name gets a ``#N`` suffix instead of clobbering -- two
        colocated engines (disagg prefill+decode in one process) must
        both appear in snapshots."""
        with self._lock:
            key = name
            n = 1
            while key in self._providers and self._providers[key] != fn:
                n += 1
                key = f"{name}#{n}"
            self._providers[key] = fn
            return key

    def remove_provider(self, name: str, fn: Optional[Callable] = None) -> None:
        with self._lock:
            # equality, not identity: each bound-method access mints a new
            # object, and a second engine's provider must not be evicted
            # by the first engine's stop()
            if fn is None or self._providers.get(name) == fn:
                self._providers.pop(name, None)

    def snapshot(self, reason: str, **extra: Any) -> str:
        """Take (or, throttled, reuse) a snapshot; returns its id."""
        now = time.monotonic()
        with self._lock:
            last = self._last_by_reason.get(reason)
            if last is not None and now - last[0] < self.min_interval_s:
                return last[1]
            self._seq += 1
            snap_id = f"fr-{self._seq:04d}"
            providers = dict(self._providers)
            self._last_by_reason[reason] = (now, snap_id)
        from . import slo

        state: Dict[str, Any] = {}
        for name, fn in providers.items():
            try:
                state[name] = fn()
            except Exception as e:  # a dying component must not block the dump
                state[name] = {"error": repr(e)}
        snap = {
            "id": snap_id,
            "reason": reason,
            "ts": time.time(),
            # promoted so /flightrec rows link straight to /trace/{id}
            # (call sites pass request_id=...; trace_id aliases it)
            "trace_id": extra.get("trace_id") or extra.get("request_id"),
            "extra": extra,
            "ticks": [
                r.to_dict() for r in profiler.records(self.tick_window)
            ],
            "slo_violations": slo.tracker.recent_violations(),
            "state": state,
        }
        with self._lock:
            self._snaps[snap_id] = snap
            while len(self._snaps) > self.capacity:
                self._snaps.popitem(last=False)
        return snap_id

    def get(self, snap_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._snaps.get(snap_id)

    def list(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {
                    "id": s["id"],
                    "reason": s["reason"],
                    "ts": s["ts"],
                    "trace_id": s.get("trace_id"),
                    "extra": s["extra"],
                }
                for s in self._snaps.values()
            ]

    def clear(self) -> None:
        with self._lock:
            self._snaps.clear()
            self._last_by_reason.clear()


flight_recorder = FlightRecorder()
