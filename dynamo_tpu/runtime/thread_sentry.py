"""Runtime complement of dynalint's thread-role model (DT014-DT016).

The static analyzer (``analysis/threads.py``) proves race-freedom only
relative to a *declared* role model: the tick coroutine is serialized with
the device executor, ``to_host`` runs only on the kv-offload thread, the
WAL writer owns the journal handle.  This module makes those declarations
checkable at runtime: armed with ``DYN_THREAD_SENTRY=1``, the engine's
hottest shared structures assert their confinement on every touch, so a
manifest entry that drifts from reality fails a test instead of silently
mis-scoping the race scan.

Overhead discipline (the FaultInjector pattern): disarmed, every site is
one module-global bool check; ``thread_confined`` returns the function
object untouched, so jits/partials/pickling are unaffected.

Usage::

    from ..runtime import thread_sentry

    def _commit_all(self, ...):
        thread_sentry.assert_role("tick", what="JaxEngine._commit_all")

or, pinning the static role AND asserting at runtime in one place::

    @thread_confined("kv-offload")
    def _store_evict(self, ...): ...

``thread_confined`` doubles as dynalint's justification mechanism: the
analyzer reads the decorator syntactically and pins the function (or every
method of a decorated class) to the named role instead of whatever
propagation inferred.  The special role ``"handoff"`` marks per-request
value classes whose instances cross domains only through ownership
transfer (admission, queue put) -- never shared live.
"""

from __future__ import annotations

import asyncio
import functools
import os
import threading
from typing import Any, Callable, Tuple, TypeVar

ENV_VAR = "DYN_THREAD_SENTRY"

_ARMED = os.environ.get(ENV_VAR, "").strip().lower() not in (
    "", "0", "false", "no", "off",
)

F = TypeVar("F", bound=Callable)

THREAD_CONFINED_ATTR = "__dynalint_thread_role__"

# role -> thread-name prefixes allowed to execute it.  The executor roles
# are keyed by their pools' thread_name_prefix (the same mapping
# analysis/threads.py EXECUTOR_PREFIX_ROLES inverts).
ROLE_THREAD_PREFIXES = {
    "tick": ("jax-engine",),
    "kv-offload": ("kv-offload",),
    "kv-remote": ("kv-remote",),
    "hub-io": ("hub-journal",),
    "recorder-io": ("recorder-io",),
    "planner-log": ("planner-log",),
    "kv-index-shard": ("kv-index-shard",),
}

# roles satisfied by running on an event-loop thread.  "tick" is included:
# the tick domain is the executor thread PLUS the tick coroutine, which
# are await-serialized -- exactly the contract DT014 relies on.
LOOP_RESIDENT_ROLES = ("tick-coro", "fanout-worker", "event-loop", "tick")

# the anonymous default-executor / to_thread pool
_WORKER_PREFIXES = ("asyncio_", "ThreadPoolExecutor")


class ThreadConfinementError(AssertionError):
    """A declared thread-role confinement was violated at runtime."""


def armed() -> bool:
    return _ARMED


def arm(on: bool = True) -> None:
    """Flip the sentry for tests.  Inline ``assert_role`` sites react
    immediately; ``thread_confined`` wrappers are bound at import time, so
    subprocess tests set ``DYN_THREAD_SENTRY=1`` in the environment."""
    global _ARMED
    _ARMED = on


def _on_event_loop() -> bool:
    try:
        asyncio.get_running_loop()
        return True
    except RuntimeError:
        return False


def _role_matches(role: str, thread_name: str) -> bool:
    # auto-minted roles are NAMED AFTER their executor's
    # thread_name_prefix (analysis/threads.py), so an unlisted role
    # matches threads carrying its own name as prefix -- naming the
    # executor is the whole declaration, on both sides
    for prefix in ROLE_THREAD_PREFIXES.get(role, (role,)):
        if thread_name.startswith(prefix):
            return True
    if role in LOOP_RESIDENT_ROLES and _on_event_loop():
        return True
    if role == "worker" and thread_name.startswith(_WORKER_PREFIXES):
        return True
    if role == "handoff":
        return True  # ownership-transfer classes: any single owner
    return False


def assert_role(*roles: str, what: str = "") -> None:
    """Assert the current thread may execute code confined to any of
    ``roles``.  No-op unless armed (one bool check)."""
    if not _ARMED:
        return
    name = threading.current_thread().name
    for role in roles:
        if _role_matches(role, name):
            return
    raise ThreadConfinementError(
        f"{what or 'confined code'} declared roles {sorted(roles)} but ran "
        f"on thread {name!r} (loop_running={_on_event_loop()}); the "
        "thread-role manifest (analysis/threads.py) and reality disagree"
    )


def thread_confined(role: str) -> Callable[[F], F]:
    """Pin ``role`` on a function or class for dynalint DT014, and (when
    the sentry is armed at import) assert it on every call.

    The decorator tags and returns the SAME object when disarmed -- safe
    around jit/partial/pickle like ``hot_path``.  On a class it only tags
    (methods assert individually if they need to)."""

    def deco(obj: Any) -> Any:
        try:
            setattr(obj, THREAD_CONFINED_ATTR, role)
        except (AttributeError, TypeError):
            pass
        if not _ARMED or isinstance(obj, type):
            return obj

        roles: Tuple[str, ...] = tuple(
            r.strip() for r in role.split(",") if r.strip()
        )

        @functools.wraps(obj)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            assert_role(*roles, what=getattr(obj, "__qualname__", repr(obj)))
            return obj(*args, **kwargs)

        return wrapper

    return deco
