"""Live SLO attainment plane: production TTFT/ITL/E2E vs declared targets.

``planner/profile_sla.py`` measures SLOs *pre-deployment*; nothing measured
them *in production* -- the planner scaled on load (KV utilization, queue
depth) while the thing a deployment actually promises is latency
attainment.  This module closes that gap:

* targets come from one env grammar::

      DYN_SLO=ttft=300ms,itl=40ms,e2e=30s[,window=60s]

  (kinds: ``ttft``, ``itl``, ``e2e``; units ``us``/``ms``/``s``, bare
  numbers are seconds; ``window`` sets the rolling attainment window);

* the HTTP frontend's :class:`~dynamo_tpu.http.metrics.InflightGuard`
  records each request's TTFT / per-token ITL / E2E against the targets,
  maintaining rolling-window attainment gauges
  ``dynamo_slo_attainment{kind}`` and violation counters
  ``dynamo_slo_violations{kind,cause}`` (causes: ``queue``, ``service``,
  ``deadline``, ``shed``);

* the engine decomposes each request's first token into queue-wait
  (arrival -> admission) vs service time (admission -> first commit) via
  :meth:`SloTracker.note_first_token`, so a TTFT miss is attributed to
  the *queue* (scale out / shed earlier) or to *service* (the engine is
  too slow) -- the distinction an autoscaler acts on;

* ``planner.registry_metrics_source()`` reads the attainment gauges into
  ``ForwardPassMetrics``, so the planner sees attainment, not just load,
  and the flight recorder snapshots :meth:`recent_violations` at failure
  edges.

Overhead discipline: with no targets armed the tracker is disabled and
every site pays one attribute check (``if slo.tracker.enabled:``).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

KINDS = ("ttft", "itl", "e2e")
CAUSES = ("queue", "service", "deadline", "shed")

_UNITS = {"us": 1e-6, "ms": 1e-3, "s": 1.0}


class SloSpecError(ValueError):
    """Malformed ``DYN_SLO`` spec (unknown kind, bad duration)."""


def _parse_duration(raw: str, key: str) -> float:
    raw = raw.strip()
    for suffix, scale in _UNITS.items():
        if raw.endswith(suffix) and raw != suffix:
            num = raw[: -len(suffix)]
            break
    else:
        num, scale = raw, 1.0
    try:
        val = float(num) * scale
    except ValueError as e:
        raise SloSpecError(f"bad duration {raw!r} for {key}") from e
    if val <= 0:
        raise SloSpecError(f"duration for {key} must be > 0, got {raw!r}")
    return val


def parse_slo_spec(spec: str) -> Tuple[Dict[str, float], Optional[float]]:
    """``"ttft=300ms,itl=40ms,e2e=30s,window=60s"`` ->
    ``({"ttft": 0.3, "itl": 0.04, "e2e": 30.0}, 60.0)``."""
    targets: Dict[str, float] = {}
    window: Optional[float] = None
    for clause in filter(None, (c.strip() for c in spec.split(","))):
        key, sep, raw = clause.partition("=")
        key = key.strip().lower()
        if not sep:
            raise SloSpecError(f"malformed clause {clause!r}")
        if key == "window":
            window = _parse_duration(raw, key)
        elif key in KINDS:
            targets[key] = _parse_duration(raw, key)
        else:
            raise SloSpecError(
                f"unknown SLO kind {key!r} (known: {', '.join(KINDS)})"
            )
    return targets, window


def attainment_of(values_s, target_s: float) -> Optional[float]:
    """Fraction of ``values_s`` meeting ``target_s`` (None when empty);
    the pure helper bench scenarios stamp per-bucket attainment with."""
    vals = list(values_s)
    if not vals:
        return None
    return sum(1 for v in vals if v <= target_s) / len(vals)


class SloTracker:
    """Rolling-window SLO attainment over declared targets.

    Thread model: recorded from frontend tasks and the engine loop; one
    lock guards the windows/splits (sub-microsecond critical sections,
    called per request / per stream chunk, never per device step)."""

    def __init__(
        self,
        targets: Optional[Dict[str, float]] = None,
        window_s: float = 60.0,
        split_capacity: int = 4096,
        violation_capacity: int = 256,
    ) -> None:
        self.targets: Dict[str, float] = dict(targets or {})
        self.window_s = window_s
        self.enabled = bool(self.targets)
        self._windows: Dict[str, "collections.deque"] = {
            k: collections.deque() for k in KINDS
        }
        # request_id -> (queue_s, service_s): the engine's first-token
        # decomposition, consumed when the frontend classifies a TTFT miss
        self._splits: "collections.OrderedDict[str, Tuple[float, float]]" = (
            collections.OrderedDict()
        )
        self._split_capacity = split_capacity
        self._violations: "collections.deque" = collections.deque(
            maxlen=violation_capacity
        )
        # cumulative (kind, cause) violation counts since arm time: the
        # in-process twin of the dynamo_slo_violations counter family,
        # readable without walking the prometheus exposition -- metric
        # sources (planner, telemetry snapshots) diff consecutive reads
        # to attribute fresh misses to queue vs service
        self._counts: Dict[Tuple[str, str], int] = {}
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls) -> "SloTracker":
        spec = os.environ.get("DYN_SLO", "")
        if not spec.strip():
            return cls()
        targets, window = parse_slo_spec(spec)
        return cls(targets, window_s=window or 60.0)

    def configure(
        self, spec: str, *, window_s: Optional[float] = None
    ) -> None:
        """Arm (or re-arm) from a ``DYN_SLO`` grammar string."""
        targets, window = parse_slo_spec(spec)
        with self._lock:
            self.targets = targets
            if window is not None:
                self.window_s = window
            elif window_s is not None:
                self.window_s = window_s
            for q in self._windows.values():
                q.clear()
            self._violations.clear()
            self._counts.clear()
        self.enabled = bool(targets)
        if self.enabled:
            reg = self._reg()
            gauge = reg.gauge(
                "dynamo_slo_target_seconds",
                "Declared SLO target per kind (DYN_SLO grammar)",
                ["kind"],
            )
            for kind, target in targets.items():
                gauge.labels(kind).set(target)

    def disable(self) -> None:
        self.enabled = False
        with self._lock:
            self.targets = {}
            for q in self._windows.values():
                q.clear()
            self._splits.clear()
            self._violations.clear()
            self._counts.clear()

    # -- engine-side decomposition -----------------------------------------

    def note_first_token(
        self, request_id: str, queue_s: float, service_s: float
    ) -> None:
        """The engine's first-token stamp decomposition for one request:
        queue-wait (arrival -> admission) vs service (admission -> first
        token commit).  Consulted when the frontend classifies a TTFT
        miss; evicted FIFO past capacity."""
        with self._lock:
            self._splits[request_id] = (max(queue_s, 0.0), max(service_s, 0.0))
            while len(self._splits) > self._split_capacity:
                self._splits.popitem(last=False)

    def split(self, request_id: str) -> Optional[Tuple[float, float]]:
        with self._lock:
            return self._splits.get(request_id)

    # -- frontend recording -------------------------------------------------

    def record_ttft(self, request_id: str, seconds: float) -> None:
        target = self.targets.get("ttft")
        if target is None:
            return
        ok = seconds <= target
        self._push("ttft", ok)
        if not ok:
            split = self.split(request_id)
            cause = (
                "queue"
                if split is not None and split[0] >= split[1]
                else "service"
            )
            self._violation("ttft", cause, request_id, seconds)

    def record_itl(self, seconds: float) -> None:
        target = self.targets.get("itl")
        if target is None:
            return
        ok = seconds <= target
        self._push("itl", ok)
        if not ok:
            self._violation("itl", "service", "", seconds)

    def record_e2e(self, request_id: str, seconds: float) -> None:
        target = self.targets.get("e2e")
        if target is None:
            return
        ok = seconds <= target
        self._push("e2e", ok)
        if not ok:
            self._violation("e2e", "service", request_id, seconds)

    def record_deadline(self, request_id: str, seconds: float = 0.0) -> None:
        """A request's deadline budget expired (HTTP 504): an E2E miss
        with an unambiguous cause, counted even with no e2e target set."""
        if "e2e" in self.targets:
            self._push("e2e", False)
        self._violation("e2e", "deadline", request_id, seconds)

    def record_shed(self, request_id: str = "") -> None:
        """Admission control rejected the request before any work: the
        request's SLO is missed by definition of never running."""
        if "e2e" in self.targets:
            self._push("e2e", False)
        self._violation("e2e", "shed", request_id, 0.0)

    # -- read side ----------------------------------------------------------

    def attainment(self, kind: str) -> Optional[float]:
        """Rolling-window attainment for ``kind`` (None = no samples)."""
        with self._lock:
            q = self._windows[kind]
            self._evict(q)
            if not q:
                return None
            return sum(1 for _, ok in q if ok) / len(q)

    def refresh_gauges(self) -> None:
        """Re-derive every attainment gauge from the current window.

        ``_push`` only updates a gauge on new samples, so after traffic
        drains the last value would otherwise export forever -- an idle
        instance stuck reporting an incident-era 0.2 keeps phantom SLO
        pressure on the planner.  Read paths (``/metrics``,
        ``registry_metrics_source``) call this; an aged-out window reads
        as fully attained, matching the no-samples default consumers
        apply."""
        if not self.enabled:
            return
        gauge = self._reg().gauge(
            "dynamo_slo_attainment",
            "Rolling-window SLO attainment (fraction of requests meeting "
            "the DYN_SLO target) per kind",
            ["kind"],
        )
        for kind in self.targets:
            with self._lock:
                q = self._windows[kind]
                self._evict(q)
                att = (
                    sum(1 for _, ok in q if ok) / len(q) if q else 1.0
                )
            gauge.labels(kind).set(att)

    def recent_violations(self, last: int = 64) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._violations)[-last:]

    def violation_count(self, kind: str, cause: str) -> int:
        """Cumulative violations of ``kind`` attributed to ``cause`` since
        arm time (the readable twin of ``dynamo_slo_violations``)."""
        with self._lock:
            return self._counts.get((kind, cause), 0)

    # -- internals ----------------------------------------------------------

    def _evict(self, q: "collections.deque") -> None:
        horizon = time.monotonic() - self.window_s
        while q and q[0][0] < horizon:
            q.popleft()

    def _push(self, kind: str, ok: bool) -> None:
        with self._lock:
            q = self._windows[kind]
            q.append((time.monotonic(), ok))
            self._evict(q)
            att = sum(1 for _, o in q if o) / len(q)
        self._reg().gauge(
            "dynamo_slo_attainment",
            "Rolling-window SLO attainment (fraction of requests meeting "
            "the DYN_SLO target) per kind",
            ["kind"],
        ).labels(kind).set(att)

    def _violation(
        self, kind: str, cause: str, request_id: str, seconds: float
    ) -> None:
        with self._lock:
            self._violations.append(
                {
                    "ts": time.time(),
                    "kind": kind,
                    "cause": cause,
                    "request_id": request_id,
                    # the request id IS the trace id -- carried explicitly
                    # so a violation row is one hop from GET /trace/{id}
                    "trace_id": request_id or None,
                    "trace": f"/trace/{request_id}" if request_id else None,
                    "value_s": round(seconds, 6),
                }
            )
            self._counts[(kind, cause)] = (
                self._counts.get((kind, cause), 0) + 1
            )
        self._reg().counter(
            "dynamo_slo_violations",
            "SLO violations by kind and cause (queue = waited too long "
            "for admission, service = the engine was too slow, deadline = "
            "budget expired, shed = rejected by admission control)",
            ["kind", "cause"],
        ).labels(kind, cause).inc()

    @staticmethod
    def _reg():
        # lazy: respects metrics.set_default (test registries)
        from . import metrics as rtm

        return rtm.default_registry()


tracker = SloTracker.from_env()
