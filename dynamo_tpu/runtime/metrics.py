"""Process-wide engine/runtime metrics registry.

The HTTP layer already had Prometheus coverage (``http/metrics.py``); this
module extends it inward: the engine, scheduler, KV cache, disaggregated
transfer plane, and KV router all register their series through one
lightweight facade so (a) metric families are minted in exactly one place
-- dynalint DT007 rejects inline ``Counter(...)`` construction anywhere
else -- and (b) tests can run many engines per process against private
registries, the same pattern ``ServiceMetrics`` established.

Usage::

    from dynamo_tpu.runtime import metrics as rtm

    reg = rtm.default_registry()            # or MetricsRegistry() in tests
    hits = reg.counter("dynamo_engine_prefix_hit_tokens",
                       "Prompt tokens served from the prefix cache")
    hits.inc(128)

``counter``/``gauge``/``histogram`` are get-or-create: asking twice for
the same family name returns the same object, so several engines in one
process share series instead of tripping prometheus_client's duplicate
registration error.  The full metric-name catalog lives in README
"Observability".
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Sequence, Tuple

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client.exposition import CONTENT_TYPE_LATEST

# Engine decode/prefill dispatch->commit latency: sub-ms on an idle CPU
# mocker up to seconds for huge prefills on a tunneled TPU.
STEP_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0,
)
# Disagg KV export/upload legs (multi-MB device->host->wire moves).
TRANSFER_LATENCY_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
# Unit-interval ratios (overlap ratio, utilization distributions).
RATIO_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99, 1.0)


class _LabeledView:
    """``generate_latest`` target that merges a registry's default labels
    into every rendered sample.

    Families are minted unlabeled (or with their own dynamic labels); the
    identity labels are a render-time concern, so ``sample()`` readers and
    label-less in-process consumers never see them.  Explicit per-sample
    labels win on collision.
    """

    def __init__(self, registry: CollectorRegistry, labels: Dict[str, str]):
        self._registry = registry
        self._labels = labels

    def collect(self):
        from prometheus_client.metrics_core import Metric

        for m in self._registry.collect():
            out = Metric(m.name, m.documentation, m.type, getattr(m, "unit", ""))
            for s in m.samples:
                merged = dict(self._labels)
                merged.update(s.labels)
                out.samples.append(s._replace(labels=merged))
            yield out


class MetricsRegistry:
    """Get-or-create facade over a private ``CollectorRegistry``."""

    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        self._families: Dict[str, Any] = {}
        self._lock = threading.Lock()
        # identity labels stamped onto every rendered sample (worker_id,
        # role): multi-worker Prometheus scrapes and fleet-observatory
        # rollups stop colliding on identical series names.  Empty dict =
        # exact legacy exposition.
        self.default_labels: Dict[str, str] = {}

    def set_default_labels(self, **labels: Any) -> None:
        """Replace the render-time identity label set (None values drop
        the key)."""
        with self._lock:
            self.default_labels = {
                k: str(v) for k, v in labels.items() if v is not None
            }

    def _get_or_create(
        self,
        cls,
        name: str,
        documentation: str,
        labelnames: Sequence[str],
        **kwargs: Any,
    ):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(
                    name,
                    documentation,
                    tuple(labelnames),
                    registry=self.registry,
                    **kwargs,
                )
                self._families[name] = fam
            return fam

    def counter(
        self, name: str, documentation: str, labelnames: Sequence[str] = ()
    ) -> Counter:
        return self._get_or_create(Counter, name, documentation, labelnames)

    def gauge(
        self, name: str, documentation: str, labelnames: Sequence[str] = ()
    ) -> Gauge:
        return self._get_or_create(Gauge, name, documentation, labelnames)

    def histogram(
        self,
        name: str,
        documentation: str,
        labelnames: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ) -> Histogram:
        kwargs: Dict[str, Any] = {}
        if buckets is not None:
            kwargs["buckets"] = tuple(buckets)
        return self._get_or_create(
            Histogram, name, documentation, labelnames, **kwargs
        )

    def render(self) -> Tuple[bytes, str]:
        if self.default_labels:
            view = _LabeledView(self.registry, dict(self.default_labels))
            return generate_latest(view), CONTENT_TYPE_LATEST
        return generate_latest(self.registry), CONTENT_TYPE_LATEST

    def sample(
        self, name: str, labels: Optional[Dict[str, str]] = None
    ) -> Optional[float]:
        """Current value of one series, or None if it does not exist yet.

        Counters resolve their ``_total`` sample, histograms their
        ``_sum``; gauges read directly.  This is the read path consumers
        like the planner use instead of ad-hoc plumbing -- it walks the
        exposition output, so it works for any family without touching
        prometheus_client internals."""
        want = dict(labels or {})
        candidates = (name, name + "_total", name + "_sum")
        for metric in self.registry.collect():
            if metric.name != name:
                continue
            for s in metric.samples:
                if s.name in candidates and dict(s.labels) == want:
                    return float(s.value)
        return None


class EngineMetrics:
    """Registry-backed engine/scheduler counters and gauges.

    Shared by the JAX engine and the mocker (which must stay JAX-free, so
    the class lives here rather than under ``engine/``): chip-free stacks
    expose the same series real serving does.  The engine updates it at its
    existing synchronization points -- the dispatch->commit cycle and the
    scheduler's admission pass -- so the hot loop pays a handful of gauge
    sets per *device block*, never per token.  Family catalog with labels:
    README "Observability".
    """

    def __init__(
        self,
        registry: Optional["MetricsRegistry"] = None,
        max_slots: int = 0,
    ) -> None:
        reg = registry or default_registry()
        self.registry = reg
        self.step_latency = reg.histogram(
            "dynamo_engine_step_latency_seconds",
            "Engine device-dispatch to host-commit latency",
            ["kind"],
            buckets=STEP_LATENCY_BUCKETS,
        )
        self.occupancy = reg.gauge(
            "dynamo_engine_batch_occupancy",
            "Decode lanes currently holding a slot",
        )
        self.slots = reg.gauge(
            "dynamo_engine_batch_slots",
            "Configured decode batch lanes (max_batch_size)",
        )
        self.queue_depth = reg.gauge(
            "dynamo_engine_prefill_queue_depth",
            "Requests waiting for admission into the decode batch",
        )
        self.kv_used = reg.gauge(
            "dynamo_engine_kv_pages_used", "KV cache pages in use"
        )
        self.kv_total = reg.gauge(
            "dynamo_engine_kv_pages_total", "KV cache pages available"
        )
        self.kv_util = reg.gauge(
            "dynamo_engine_kv_utilization",
            "KV cache page utilization (used/total, 0..1)",
        )
        self.prefix_hits = reg.counter(
            "dynamo_engine_prefix_hit_tokens",
            "Prompt tokens whose KV was reused from the prefix cache",
        )
        self.prefix_lookups = reg.counter(
            "dynamo_engine_prefix_lookup_tokens",
            "Prompt tokens checked against the prefix cache",
        )
        self.tokens = reg.counter(
            "dynamo_engine_tokens_generated",
            "Output tokens committed by the engine",
        )
        self.preemptions = reg.counter(
            "dynamo_engine_preemptions",
            "Sequences preempted for KV-page capacity",
        )
        # dispatch accounting: every device launch the tick loop pays, by
        # kind (prefill / decode_block / unified / verify / chunk /
        # prompt_score).
        # dispatches/s vs decode steps/s is the mixed-batching health ratio
        # the bench tracks every round (ROADMAP item 2).
        self.dispatches = reg.counter(
            "dynamo_engine_dispatches_total",
            "Device dispatches issued by the engine tick loop",
            ["kind"],
        )
        # mixed-batch occupancy: how full each unified ragged dispatch ran
        # (decode lanes riding alongside how many packed prefill tokens)
        self.mixed_decode_lanes = reg.histogram(
            "dynamo_engine_mixed_batch_decode_lanes",
            "Decode lanes per unified mixed-batch dispatch",
            buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128),
        )
        self.mixed_prefill_tokens = reg.histogram(
            "dynamo_engine_mixed_batch_prefill_tokens",
            "Prefill tokens packed into a unified mixed-batch dispatch",
            buckets=(0, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096),
        )
        # fresh-token accounting per unified dispatch (ISSUE 10): `used`
        # counts real rows (decode lanes + packed prefill tokens),
        # `dispatched` the rows the executable actually ran, `rectangle`
        # the rows the lane-rectangle layout would have run -- the
        # padded-token fractions the long-context bench reports are
        # 1 - used/dispatched and 1 - used/rectangle
        self.mixed_tokens = reg.counter(
            "dynamo_engine_mixed_tokens",
            "Fresh-token rows per unified mixed dispatch by accounting kind",
            ["kind"],  # used | dispatched | rectangle
        )
        # packed-shape budget (ISSUE 13 satellite): active (Np, s_max)
        # executable pairs the packed unified step may dispatch -- bounded
        # by engine/bucketing.PackedShapeBudget's LRU/merge pass
        self.executable_shapes = reg.gauge(
            "dynamo_engine_executable_shapes",
            "Active packed-dispatch (Np, s_max) executable shape pairs",
        )
        # multi-step decode (ISSUE 16): decode iterations fused into the
        # last packed dispatch -- 1 = single-step (pressure or disabled),
        # up to multistep_max_k when the adaptive controller opens up
        self.multistep_k = reg.gauge(
            "dynamo_engine_multistep_k",
            "Decode steps fused into the last packed unified dispatch",
        )
        if max_slots:
            self.slots.set(max_slots)

    # -- update points (cheap; called per tick / per commit, not per token)

    def observe_sched(self, waiting: int, active: int) -> None:
        self.queue_depth.set(waiting)
        self.occupancy.set(active)

    def observe_step(self, kind: str, seconds: float) -> None:
        self.step_latency.labels(kind).observe(max(seconds, 0.0))

    def observe_dispatch(self, kind: str) -> None:
        self.dispatches.labels(kind).inc()

    def observe_mixed(self, decode_lanes: int, prefill_tokens: int) -> None:
        self.mixed_decode_lanes.observe(decode_lanes)
        self.mixed_prefill_tokens.observe(prefill_tokens)

    def observe_mixed_tokens(
        self, used: int, dispatched: int, rectangle: int
    ) -> None:
        self.mixed_tokens.labels("used").inc(used)
        self.mixed_tokens.labels("dispatched").inc(dispatched)
        self.mixed_tokens.labels("rectangle").inc(rectangle)

    def observe_kv(self, used: int, total: int) -> None:
        self.kv_used.set(used)
        self.kv_total.set(total)
        self.kv_util.set(used / total if total else 0.0)

    def observe_executable_shapes(self, n: int) -> None:
        self.executable_shapes.set(n)

    def observe_multistep_k(self, k: int) -> None:
        self.multistep_k.set(k)


class OffloadMetrics:
    """Registry-backed multi-tier KV offload plane series (G2 host / G3
    disk / swap records): transfer volume + latency per tier, occupancy,
    tiered prefix hits, preemption kinds, and the chaos-visible failure
    counters.  Minted here (DT007) and updated only from the offload
    thread or the engine's existing commit points -- never per token.
    Catalog: README "Multi-tier KV cache (KVBM)".
    """

    def __init__(self, registry: Optional["MetricsRegistry"] = None) -> None:
        reg = registry or default_registry()
        self.registry = reg
        self.offload_bytes = reg.counter(
            "dynamo_kv_offload_bytes",
            "KV bytes demoted out of HBM (eviction snapshots, swap-outs)",
            ["tier"],  # host | swap
        )
        self.offload_latency = reg.histogram(
            "dynamo_kv_offload_seconds",
            "Device->host materialize + tier store latency per blob",
            ["tier"],
            buckets=TRANSFER_LATENCY_BUCKETS,
        )
        self.onboard_bytes = reg.counter(
            "dynamo_kv_onboard_bytes",
            "KV bytes restored into HBM pages (prefix onboards, swap-ins)",
            ["tier"],  # prefix | swap
        )
        self.onboard_latency = reg.histogram(
            "dynamo_kv_onboard_seconds",
            "Host->device scatter latency per onboarded blob",
            ["tier"],
            buckets=TRANSFER_LATENCY_BUCKETS,
        )
        self.tier_blocks = reg.gauge(
            "dynamo_kv_tier_blocks",
            "Blocks resident per offload tier (swap = budget blocks in use)",
            ["tier"],  # host | disk | swap
        )
        self.tier_hits = reg.counter(
            "dynamo_kv_tier_prefix_hits",
            "Prefix-block lookups served from an offload tier",
            ["tier"],  # host | disk
        )
        self.tier_promotes = reg.counter(
            "dynamo_kv_tier_promotes",
            "Blocks promoted up a tier ahead of use (disk->host ring via "
            "prefetch or lookup-triggered promote); deliberately not a "
            "hit -- warmth counts only lookups actually served",
            ["tier"],  # disk
        )
        self.preemptions = reg.counter(
            "dynamo_kv_preemptions",
            "Capacity preemptions by recovery kind",
            ["kind"],  # swap | recompute
        )
        self.swap_events = reg.counter(
            "dynamo_kv_swap_events",
            "Swap-plane transitions (out = parked, in = restored)",
            ["event"],  # out | in
        )
        self.swap_fallbacks = reg.counter(
            "dynamo_kv_swap_fallbacks",
            "Swap attempts that fell back to recompute, by cause",
            ["cause"],  # budget | copy_fail | truncate
        )
        self.onboard_fallbacks = reg.counter(
            "dynamo_kv_onboard_fallbacks",
            "Prefix onboards abandoned (the admission recomputed the "
            "prefix in place), by cause",
            ["cause"],  # truncate
        )
        self.copy_fails = reg.counter(
            "dynamo_kv_offload_copy_failures",
            "Offload materializations dropped (I/O errors or injected "
            "offload.copy_fail faults)",
        )
        # queue-side prefetch (ISSUE 10): tracked walks that stage
        # offloaded prefix chains toward host RAM during queue wait
        self.prefetch_issued = reg.counter(
            "dynamo_kv_prefetch_issued_blocks",
            "Prefix blocks requested by tracked queue-side prefetch walks",
        )
        self.prefetch_hits = reg.counter(
            "dynamo_kv_prefetch_hits",
            "Prefetch-staged blocks found host-resident and consumed at "
            "admission (the onboard scatter never waited on a disk read)",
        )
        self.prefetch_wasted = reg.counter(
            "dynamo_kv_prefetch_wasted_bytes",
            "Bytes prefetch-staged but never consumed (request cancelled "
            "before admission, or the admission matched elsewhere)",
        )
        self.prefetch_overlap = reg.histogram(
            "dynamo_kv_prefetch_overlap_ratio",
            "Fraction of each tracked prefetch walk that overlapped queue "
            "wait instead of the TTFT critical path (1.0 = fully hidden)",
            buckets=RATIO_BUCKETS,
        )

    def record_offload(self, tier: str, nbytes: int, seconds: float) -> None:
        self.offload_bytes.labels(tier).inc(nbytes)
        self.offload_latency.labels(tier).observe(max(seconds, 0.0))

    def record_onboard(self, tier: str, nbytes: int, seconds: float) -> None:
        self.onboard_bytes.labels(tier).inc(nbytes)
        self.onboard_latency.labels(tier).observe(max(seconds, 0.0))


class RemoteKVMetrics:
    """Registry-backed G4 remote-tier series (``dynamo_kv_g4_*``): the
    fleet-shared store's transfer volume/latency per direction, local
    residency knowledge, and the chaos-visible fetch failure causes.
    Updated only from the kv-remote thread.  Catalog: README "Fleet KV
    economy"."""

    def __init__(self, registry: Optional["MetricsRegistry"] = None) -> None:
        reg = registry or default_registry()
        self.registry = reg
        self.bytes = reg.counter(
            "dynamo_kv_g4_bytes",
            "KV frame bytes moved against the G4 fleet store, by direction",
            ["op"],  # store | fetch
        )
        self.latency = reg.histogram(
            "dynamo_kv_g4_seconds",
            "G4 store round-trip latency per blob frame, by direction",
            ["op"],
            buckets=TRANSFER_LATENCY_BUCKETS,
        )
        self.blocks = reg.gauge(
            "dynamo_kv_g4_blocks",
            "Blocks this worker knows to be resident in the G4 store "
            "(own publications + merged fleet adverts)",
        )
        self.fetch_failures = reg.counter(
            "dynamo_kv_g4_fetch_failures",
            "G4 fetches that fell back to recompute, by cause",
            ["cause"],  # fetch_fail | missing | blob_corrupt
        )

    def record_store(self, nbytes: int, seconds: float) -> None:
        self.bytes.labels("store").inc(nbytes)
        self.latency.labels("store").observe(max(seconds, 0.0))

    def record_fetch(self, nbytes: int, seconds: float) -> None:
        self.bytes.labels("fetch").inc(nbytes)
        self.latency.labels("fetch").observe(max(seconds, 0.0))


class SpecMetrics:
    """Registry-backed speculative-decoding series (``dynamo_spec_*``).

    Updated only at the engine's existing commit points (per verify
    dispatch, never per token).  ``accept_rate`` is the engine-lifetime
    running ratio -- per-request rates ride the OpenAI usage extension and
    the request span's ``spec_accept_rate`` attr instead.  Catalog: README
    "Speculative decoding".
    """

    def __init__(self, registry: Optional["MetricsRegistry"] = None) -> None:
        reg = registry or default_registry()
        self.registry = reg
        self.drafted = reg.counter(
            "dynamo_spec_drafted_tokens",
            "Draft tokens proposed and dispatched for verification",
            ["drafter"],
        )
        self.accepted = reg.counter(
            "dynamo_spec_accepted_tokens",
            "Draft tokens accepted by the verify step",
            ["drafter"],
        )
        self.verify_steps = reg.counter(
            "dynamo_spec_verify_steps",
            "Batched multi-token verify passes (standalone or folded)",
        )
        self.folded_steps = reg.counter(
            "dynamo_spec_folded_verify_steps",
            "Verify column groups folded into packed unified dispatches "
            "(ISSUE 15: no standalone verify dispatch was paid for these)",
        )
        self.auto_disabled = reg.counter(
            "dynamo_spec_auto_disabled_requests",
            "Requests whose speculation auto-disabled on low acceptance",
        )
        self.enabled_frac = reg.gauge(
            "dynamo_spec_enabled_frac",
            "Fraction of spec-armed requests still drafting "
            "(1 - auto_disabled/armed)",
        )
        self.requests = reg.counter(
            "dynamo_spec_requests",
            "Requests that ran with speculation armed",
        )
        self.accept_rate = reg.gauge(
            "dynamo_spec_accept_rate",
            "Engine-lifetime draft acceptance rate (accepted/drafted)",
        )
        self.draft_latency = reg.histogram(
            "dynamo_spec_draft_seconds",
            "Host-side drafting time per verify dispatch (all lanes)",
            buckets=STEP_LATENCY_BUCKETS,
        )
        self.verify_latency = reg.histogram(
            "dynamo_spec_verify_seconds",
            "Verify dispatch->commit latency",
            buckets=STEP_LATENCY_BUCKETS,
        )


_default = MetricsRegistry()
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    return _default


def set_default(reg: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-wide registry (tests); returns the previous one."""
    global _default
    with _default_lock:
        prev = _default
        _default = reg
        return prev


def render_default() -> Tuple[bytes, str]:
    return _default.render()


def set_worker_identity(
    worker_id: Optional[Any] = None, role: Optional[str] = None
) -> None:
    """Stamp this process's worker identity onto the default registry's
    rendered exposition (and keep it across test-time ``set_default``
    swaps is the caller's concern -- workers set it once at startup)."""
    labels: Dict[str, Any] = {}
    if worker_id is not None:
        labels["worker_id"] = str(worker_id)
    if role:
        labels["role"] = str(role)
    default_registry().set_default_labels(**labels)


def worker_identity() -> Dict[str, str]:
    return dict(default_registry().default_labels)
