"""Deterministic, seed-driven fault injection: the chaos plane.

Production recovery code that cannot be exercised deterministically is
untested code; this module gives every failure path a named *injection
site* that tests (and operators, in staging) drive from one env knob:

    DYN_FAULTS="seed=42;engine.crash_before_first_token=1:max=1"

Spec grammar (clauses separated by ``;``):

    seed=<int>                     -- PRNG seed (default 0)
    <site>=<prob>[:<k>=<v>]...     -- arm a site

with per-site fields:

    max=<n>      fire at most n times (default unlimited)
    after=<k>    skip the first k *matching* evaluations
    delay=<s>    seconds of injected latency (delay-type sites)
    match=<sub>  only evaluations whose key contains <sub> draw at all

Determinism: each site draws from its own ``random.Random(f"{seed}/{site}")``
stream, so the schedule depends only on (seed, per-site evaluation order)
-- unrelated traffic on *other* sites cannot perturb it, and filtered
(non-``match``-ing) evaluations do not advance the stream.  The same
``DYN_FAULTS`` string therefore reproduces the identical fault schedule
run after run; :meth:`FaultInjector.schedule` returns the fired log for
tests to compare.

Overhead discipline (same as tracing): disabled injection is one
attribute check at every site --

    if faults.injector.enabled and faults.injector.should_fire(SITE):
        ...

Site catalog (README "Failure model & fault injection"):

    hub.frame_drop                  drop an incoming hub frame (client pump)
    hub.frame_delay                 delay an incoming hub frame
    req.stream_abort                server aborts a response stream mid-flight
                                    (error frame to the caller)
    engine.crash_before_first_token worker connection dies before any
                                    response item (the failover-retryable
                                    window)
    engine.crash_after_first_token  worker connection dies mid-stream
    disagg.enqueue_fail             remote-prefill enqueue raises (drives the
                                    circuit breaker)
    disagg.chunk_truncate           KV upload stops after the first chunk
    disagg.slow_export              injected latency before the KV upload
    offload.copy_fail               an offload-tier materialize is dropped
                                    (eviction snapshot lost = later cache
                                    miss; swap snapshot lost = resume falls
                                    back to recompute)
    onboard.truncate                a tier onboard aborts before the device
                                    scatter (prefix onboards recompute the
                                    prefix; swap-ins recompute the sequence)
    spec.draft_corrupt              a speculative drafter's proposal is
                                    corrupted before dispatch; the verify
                                    accept walk must reject it (output
                                    unchanged, only acceptance rate drops)
    worker.slow                     injected per-step latency in a worker's
                                    tick loop (``delay=`` seconds added to
                                    each fired simulated step; ``match=``
                                    on ``worker-<id>`` targets one worker)
                                    -- makes straggler detection/quarantine
                                    drivable from DYN_FAULTS
    worker.kill                     a whole worker process dies mid-run
                                    (evaluated by fleet chaos drivers --
                                    the SLO rig -- per kill opportunity,
                                    keyed ``worker-<id>``)
"""

from __future__ import annotations

import asyncio
import os
import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

SITES = frozenset(
    {
        "hub.frame_drop",
        "hub.frame_delay",
        "req.stream_abort",
        "engine.crash_before_first_token",
        "engine.crash_after_first_token",
        "disagg.enqueue_fail",
        "disagg.chunk_truncate",
        "disagg.slow_export",
        "offload.copy_fail",
        "onboard.truncate",
        "remote.fetch_fail",
        "remote.blob_corrupt",
        "spec.draft_corrupt",
        "worker.slow",
        "worker.kill",
    }
)


class InjectedFault(RuntimeError):
    """Raised by crash-type injection sites; never caught as a normal
    application error -- transports translate it into the transport-level
    failure it simulates (a dropped connection)."""


class FaultSpecError(ValueError):
    """Malformed ``DYN_FAULTS`` spec (unknown site, bad field)."""


@dataclass
class _SiteSpec:
    prob: float
    max_fires: Optional[int] = None
    after: int = 0
    delay_s: float = 0.0
    match: Optional[str] = None
    # runtime state
    fires: int = 0
    evals: int = 0
    rng: Any = None


@dataclass
class _Fired:
    site: str
    draw: int  # which matching evaluation of the site fired (0-based)
    key: str

    def to_dict(self) -> Dict[str, Any]:
        return {"site": self.site, "draw": self.draw, "key": self.key}


class FaultInjector:
    """Per-process injector; the module-level :data:`injector` is the one
    every site consults.  ``enabled`` is False unless a spec armed at
    least one site, so un-chaos'd processes pay one attribute check."""

    def __init__(self, spec: Optional[str] = None) -> None:
        self.enabled = False
        self.seed = 0
        self._sites: Dict[str, _SiteSpec] = {}
        self._fired: List[_Fired] = []
        if spec is None:
            spec = os.environ.get("DYN_FAULTS", "")
        if spec:
            self.configure(spec)

    # -- configuration -----------------------------------------------------

    def configure(self, spec: str) -> None:
        """Parse and arm a ``DYN_FAULTS`` spec (replaces any prior one)."""
        seed = 0
        sites: Dict[str, _SiteSpec] = {}
        for clause in filter(None, (c.strip() for c in spec.split(";"))):
            name, sep, rest = clause.partition("=")
            name = name.strip()
            if not sep:
                raise FaultSpecError(f"malformed clause {clause!r}")
            if name == "seed":
                try:
                    seed = int(rest)
                except ValueError as e:
                    raise FaultSpecError(f"bad seed {rest!r}") from e
                continue
            if name not in SITES:
                raise FaultSpecError(
                    f"unknown fault site {name!r} (known: {sorted(SITES)})"
                )
            fields = rest.split(":")
            try:
                site = _SiteSpec(prob=float(fields[0]))
            except ValueError as e:
                raise FaultSpecError(
                    f"bad probability {fields[0]!r} for site {name}"
                ) from e
            for f in fields[1:]:
                k, ksep, v = f.partition("=")
                if not ksep:
                    raise FaultSpecError(f"malformed field {f!r} in {clause!r}")
                try:
                    if k == "max":
                        site.max_fires = int(v)
                    elif k == "after":
                        site.after = int(v)
                    elif k == "delay":
                        site.delay_s = float(v)
                    elif k == "match":
                        site.match = v
                    else:
                        raise FaultSpecError(
                            f"unknown field {k!r} in {clause!r}"
                        )
                except ValueError as e:
                    raise FaultSpecError(f"bad value {v!r} for {k}") from e
            sites[name] = site
        self.seed = seed
        self._sites = sites
        self._fired = []
        for name, site in sites.items():
            site.rng = random.Random(f"{seed}/{name}")
        self.enabled = bool(sites)

    def disable(self) -> None:
        """Disarm everything (tests' teardown path)."""
        self.enabled = False
        self._sites = {}
        self._fired = []

    # -- evaluation --------------------------------------------------------

    def should_fire(self, site: str, key: str = "") -> bool:
        """One evaluation of ``site``.  Draws from the site's private PRNG
        stream; returns True when the fault fires.  ``key`` (a subject,
        request id, ...) is consulted by ``match=`` filters -- filtered
        evaluations do not draw, so unrelated traffic cannot shift the
        schedule."""
        spec = self._sites.get(site)
        if spec is None:
            return False
        if spec.match is not None and spec.match not in key:
            return False
        draw = spec.evals
        spec.evals += 1
        if draw < spec.after:
            return False
        if spec.max_fires is not None and spec.fires >= spec.max_fires:
            return False
        if spec.rng.random() >= spec.prob:
            return False
        spec.fires += 1
        self._fired.append(_Fired(site=site, draw=draw, key=key))
        self._record_fire(site)
        return True

    def delay_s(self, site: str) -> float:
        spec = self._sites.get(site)
        return spec.delay_s if spec is not None else 0.0

    async def maybe_delay(self, site: str, key: str = "") -> bool:
        """Delay-type convenience: sleep the site's ``delay`` when it
        fires.  Returns whether it fired."""
        if self.should_fire(site, key):
            await asyncio.sleep(self.delay_s(site))
            return True
        return False

    # -- introspection -----------------------------------------------------

    def schedule(self) -> List[Dict[str, Any]]:
        """The fired-injection log, in order -- the determinism surface:
        identical specs must produce identical schedules."""
        return [f.to_dict() for f in self._fired]

    def fire_count(self, site: str) -> int:
        spec = self._sites.get(site)
        return spec.fires if spec is not None else 0

    def _record_fire(self, site: str) -> None:
        # lazy import: the injector must stay importable from the deepest
        # transport modules without dragging prometheus into their import
        from . import metrics as rtm

        rtm.default_registry().counter(
            "dynamo_faults_injected",
            "Faults fired by the injection plane",
            ["site"],
        ).labels(site).inc()


injector = FaultInjector()
