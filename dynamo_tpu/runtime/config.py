"""RuntimeConfig + the Worker.execute application harness.

Reference parity: lib/runtime RuntimeConfig (the ``DYN_*`` env surface)
and ``Worker::execute`` (runtime/src/worker.rs) -- the standard way an
application hosts the distributed runtime: build it from config, hand it
to the app's async main, install signal handling, and guarantee a clean
shutdown on exit, signal, or failure.

The full DYN_* surface in one place:

=====================  =====================================================
DYN_HUB_ADDRESS        hub ``host:port`` (default 127.0.0.1:6650)
DYN_BIND_HOST          data-plane bind address (default 0.0.0.0)
DYN_ADVERTISE_HOST     address other hosts reach this worker at
DYN_LEASE_TTL          primary lease TTL seconds (default 5)
DYN_LOG                log filter spec (``level`` / ``logger=level,...``)
DYN_LOG_JSONL          1 = one-JSON-object-per-line logs
DYN_TRACE              1 = collect request spans (runtime.tracing)
DYN_NUM_NODES          multi-host world size (parallel.multihost)
DYN_NODE_RANK          this host's rank
DYN_LEADER_ADDR        jax.distributed coordinator ``host:port``
DYN_PALLAS_DECODE      1/0 = force the Pallas decode kernel on/off
=====================  =====================================================
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import signal
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

from .component import DistributedRuntime
from .utils import configure_logging

logger = logging.getLogger("dynamo.runtime")


@dataclass
class RuntimeConfig:
    """Everything the runtime reads from the environment, in one struct."""

    hub_address: str = "127.0.0.1:6650"
    bind_host: str = "0.0.0.0"
    advertise_host: Optional[str] = None
    lease_ttl_s: float = 5.0
    log_spec: str = ""
    log_jsonl: bool = False
    trace: bool = False
    num_nodes: int = 1
    node_rank: int = 0
    leader_addr: str = ""

    @classmethod
    def from_env(cls) -> "RuntimeConfig":
        env = os.environ
        return cls(
            hub_address=env.get("DYN_HUB_ADDRESS", "127.0.0.1:6650"),
            bind_host=env.get("DYN_BIND_HOST", "0.0.0.0"),
            advertise_host=env.get("DYN_ADVERTISE_HOST") or None,
            lease_ttl_s=float(env.get("DYN_LEASE_TTL", "5")),
            log_spec=env.get("DYN_LOG", ""),
            log_jsonl=env.get("DYN_LOG_JSONL", "") not in ("", "0", "false"),
            trace=env.get("DYN_TRACE", "") not in ("", "0", "false"),
            num_nodes=int(env.get("DYN_NUM_NODES", "1")),
            node_rank=int(env.get("DYN_NODE_RANK", "0")),
            leader_addr=env.get("DYN_LEADER_ADDR", ""),
        )


class Worker:
    """Application harness (reference Worker::execute).

    ``Worker(cfg).execute(app)`` runs ``app(runtime)`` with:

    - logging configured from the DYN_LOG spec,
    - a connected ``DistributedRuntime`` (fails fast if the hub is down),
    - SIGINT/SIGTERM triggering runtime shutdown (``app`` sees the
      runtime's shutdown event and should exit),
    - guaranteed runtime shutdown afterwards, success or failure.
    """

    def __init__(self, config: Optional[RuntimeConfig] = None) -> None:
        self.config = config or RuntimeConfig.from_env()

    def execute(self, app: Callable[[DistributedRuntime], Awaitable[Any]]) -> Any:
        return asyncio.run(self.execute_async(app))

    async def execute_async(
        self, app: Callable[[DistributedRuntime], Awaitable[Any]]
    ) -> Any:
        cfg = self.config
        configure_logging()
        if cfg.trace:
            from . import tracing

            tracing.collector.enable()
        runtime = await DistributedRuntime.detached(
            cfg.hub_address, lease_ttl=cfg.lease_ttl_s
        )
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            with contextlib.suppress(NotImplementedError):
                loop.add_signal_handler(sig, runtime._shutdown.set)
        try:
            return await app(runtime)
        finally:
            await runtime.shutdown()
