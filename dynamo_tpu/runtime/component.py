"""Component model: DistributedRuntime -> Namespace -> Component -> Endpoint.

Reference parity: lib/runtime/src/component.rs (naming hierarchy, instance
registration under ``instances/{ns}/{comp}/{ep}:{lease_hex}``), endpoint.rs
(serving = register subject handler + etcd instance key under the primary
lease), client.rs (prefix watch -> live instance list).  The TPU build keeps
the identical keyspace and subject naming so operational tooling translates
1:1, but both planes ride the first-party hub / data plane instead of
etcd + NATS.

Serving an endpoint:

    rt = await DistributedRuntime.detached(hub_addr)        # or .static()
    ep = rt.namespace("dynamo").component("backend").endpoint("generate")
    await ep.serve(my_engine)          # my_engine: AsyncEngine[dict, Annotated]

Calling it:

    client = await ep.client()
    router = PushRouter(client, RouterMode.ROUND_ROBIN)
    stream = await router.generate(Context.new({"prompt": ...}))
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import random
import socket
import time
from dataclasses import dataclass, field
from enum import Enum
from functools import partial
from typing import Any, AsyncIterator, Dict, List, Optional, Set

from .engine import (
    DEADLINE_EXCEEDED_MSG,
    Annotated,
    AsyncEngine,
    AsyncEngineContext,
    Context,
    DeadlineExceededError,
    EngineFn,
    ResponseStream,
    ensure_response_stream,
)
from . import tracing
from .transports.client import HubClient, StaticHub, WatchHandle
from .transports.codec import decode_trace_context
from .transports.request_plane import (
    DataPlaneClient,
    DataPlaneServer,
    RemoteError,
    WorkerLostError,
)

logger = logging.getLogger("dynamo.runtime")

INSTANCE_ROOT_PATH = "instances"  # reference: component.rs:64


@dataclass(frozen=True)
class Instance:
    """A live serving instance of an endpoint (reference component.rs:84-96)."""

    namespace: str
    component: str
    endpoint: str
    instance_id: int  # lease id; unique per process lifetime
    host: str
    port: int
    subject: str

    @property
    def etcd_key(self) -> str:
        return (
            f"{INSTANCE_ROOT_PATH}/{self.namespace}/{self.component}/"
            f"{self.endpoint}:{self.instance_id:x}"
        )

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_json(cls, blob: bytes) -> "Instance":
        return cls(**json.loads(blob))


def _advertise_host() -> str:
    host = os.environ.get("DYN_ADVERTISE_HOST")
    if host:
        return host
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


class DistributedRuntime:
    """Cluster handle: hub client + shared data plane + primary lease.

    Reference: lib/runtime/src/distributed.rs.  ``static_mode`` (no hub
    server, in-process state) mirrors distributed.rs:85.
    """

    def __init__(self, hub, static_mode: bool) -> None:
        self.hub = hub
        self.static_mode = static_mode
        self.primary_lease: int = 0
        self.data_server = DataPlaneServer(host=os.environ.get("DYN_BIND_HOST", "0.0.0.0"))
        self.data_client = DataPlaneClient()
        self._data_server_started = False
        # Local engine registry: subject -> engine, for zero-copy in-process
        # dispatch when caller and worker share an event loop.
        self.local_engines: Dict[str, AsyncEngine] = {}
        # Per-endpoint service stats ("{ns}/{comp}/{ep}" -> EndpointStats);
        # served by the auto-registered per-component ``_stats`` endpoint
        # (the NATS $SRV.STATS equivalent, SURVEY.md 2.1 row 15)
        self.endpoint_stats: Dict[str, "EndpointStats"] = {}
        self._stats_served: set = set()
        self._shutdown = asyncio.Event()
        # every instance this process registered (drain deregisters them)
        self.served: List[Instance] = []
        self.draining = False

    # -- constructors ------------------------------------------------------

    @classmethod
    async def detached(
        cls,
        hub_addr: Optional[str] = None,
        lease_ttl: float = 5.0,
        reconnect_window: Optional[float] = None,
    ) -> "DistributedRuntime":
        """Connect to a hub (``host:port``; env ``DYN_HUB_ADDRESS``).

        ``reconnect_window`` > 0 lets the client ride out a hub restart
        (durable hub: leases + keys are restored, the client reconnects and
        resumes keepalives/watches).  None reads ``DYN_HUB_RECONNECT``
        seconds (default 0 = loss is fatal, the pre-durability behavior)."""
        addr = hub_addr or os.environ.get("DYN_HUB_ADDRESS", "127.0.0.1:6650")
        host, _, port = addr.rpartition(":")
        if reconnect_window is None:
            reconnect_window = float(os.environ.get("DYN_HUB_RECONNECT", "0"))
        hub = await HubClient(
            host or "127.0.0.1", int(port), reconnect_window=reconnect_window
        ).connect()
        rt = cls(hub, static_mode=False)
        rt.primary_lease = await hub.lease_grant(ttl=lease_ttl)
        return rt

    @classmethod
    async def static(cls, hub: Optional[StaticHub] = None) -> "DistributedRuntime":
        rt = cls(hub or StaticHub(), static_mode=True)
        rt.primary_lease = await rt.hub.lease_grant()
        return rt

    # -- lifecycle ---------------------------------------------------------

    async def ensure_data_server(self) -> None:
        if not self._data_server_started:
            self.data_server.advertise_host = (
                "127.0.0.1" if self.static_mode else _advertise_host()
            )
            await self.data_server.start()
            self._data_server_started = True

    async def shutdown(self) -> None:
        self._shutdown.set()
        with contextlib.suppress(Exception):
            if self.primary_lease and not self.static_mode:
                await self.hub.lease_revoke(self.primary_lease)
        await self.data_client.close()
        if self._data_server_started:
            await self.data_server.stop()
        await self.hub.close()

    async def wait_for_shutdown(self) -> None:
        """Block until shutdown is requested (signal handler, hub loss, or
        an explicit ``shutdown()``) -- the app-harness idle state."""
        await self._shutdown.wait()

    def request_shutdown(self) -> None:
        self._shutdown.set()

    def inflight_requests(self) -> int:
        """Requests currently being served by this process's endpoints."""
        return sum(s.in_flight for s in self.endpoint_stats.values())

    async def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful worker drain: deregister every served instance from
        discovery (watching routers drop it from selection), stop accepting
        new dispatches (a stale client's request gets a retryable
        no-handler error, which its failover sends elsewhere), then wait
        for in-flight requests to finish.  Returns True when the drain
        completed cleanly within ``timeout_s``.

        SIGTERM (supervisor scale-down, kubernetes preStop) is the
        intended trigger: drain, then exit -- no request is dropped by a
        planned shutdown."""
        if self.draining:
            return True
        self.draining = True
        logger.info(
            "draining: deregistering %d instances, %d requests in flight",
            len(self.served), self.inflight_requests(),
        )
        for inst in self.served:
            with contextlib.suppress(Exception):
                await self.hub.kv_delete(inst.etcd_key)
            self.data_server.unregister(inst.subject)
            self.local_engines.pop(inst.subject, None)
        deadline = asyncio.get_running_loop().time() + timeout_s
        while self.inflight_requests() > 0:
            if asyncio.get_running_loop().time() >= deadline:
                logger.warning(
                    "drain timed out with %d requests still in flight",
                    self.inflight_requests(),
                )
                self._count_drain(clean=False)
                return False
            await asyncio.sleep(0.02)
        self._count_drain(clean=True)
        logger.info("drain complete")
        return True

    @staticmethod
    def _count_drain(clean: bool) -> None:
        from . import metrics as rtm

        rtm.default_registry().counter(
            "dynamo_worker_drains",
            "Graceful worker drains by outcome",
            ["outcome"],
        ).labels("clean" if clean else "timeout").inc()

    async def drain_and_shutdown(self, timeout_s: float = 30.0) -> None:
        await self.drain(timeout_s)
        self.request_shutdown()

    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, name)


@dataclass
class Namespace:
    runtime: DistributedRuntime
    name: str

    def component(self, name: str) -> "Component":
        return Component(self.runtime, self.name, name)

    def event_subject(self, topic: str) -> str:
        """Events ride ``{ns}.events.{topic}`` (reference traits/events.rs)."""
        return f"{self.name}.events.{topic}"

    async def publish(self, topic: str, payload: Dict[str, Any]) -> None:
        await self.runtime.hub.publish(
            self.event_subject(topic), json.dumps(payload).encode()
        )

    async def subscribe(self, topic: str):
        return await self.runtime.hub.subscribe(self.event_subject(topic))


@dataclass
class Component:
    runtime: DistributedRuntime
    namespace: str
    name: str

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self.runtime, self.namespace, self.name, name)

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.name}"

    async def scrape_trace(
        self, request_id: Optional[str] = None, timeout_s: float = 2.0
    ) -> List[Dict[str, Any]]:
        """Collect trace spans from every live instance of this component
        (the trace analog of :meth:`scrape_stats`, consumed by the
        ``dynamo-tpu trace`` CLI): each instance's ``_trace`` endpoint
        returns its collector's spans for ``request_id`` (or its whole
        ring); the merged span dicts assemble into one cross-process
        timeline (``tracing.chrome_trace``)."""
        ep = self.endpoint(TRACE_ENDPOINT)
        client = await ep.client()
        try:
            with contextlib.suppress(TimeoutError):
                await client.wait_for_instances(timeout_s)
            spans: List[Dict[str, Any]] = []

            async def one(instance_id: int):
                router = PushRouter(client)
                stream = await router.direct(
                    Context.new({"request_id": request_id}), instance_id
                )
                async for item in stream:
                    if isinstance(item, Annotated) and item.data is not None:
                        return item.data
                return None

            ids = [i.instance_id for i in client.instances]
            results = await asyncio.gather(
                *(asyncio.wait_for(one(i), timeout_s) for i in ids),
                return_exceptions=True,
            )
            for r in results:
                if isinstance(r, dict):
                    spans.extend(r.get("spans") or [])
            return spans
        finally:
            await client.close()

    async def scrape_stats(self, timeout_s: float = 2.0) -> List[Dict[str, Any]]:
        """Request service stats from every live instance of this component
        (the ``$SRV.STATS`` scatter-gather, reference component.rs:284).

        Returns one dict per responding instance:
        ``{"instance": id, "endpoints": {path: {requests, errors, ...}}}``;
        wedged instances are skipped after ``timeout_s``."""
        ep = self.endpoint(STATS_ENDPOINT)
        client = await ep.client()
        try:
            out: List[Dict[str, Any]] = []

            async def one(instance_id: int):
                router = PushRouter(client)
                stream = await router.direct(
                    Context.new(None), instance_id
                )
                async for item in stream:
                    if isinstance(item, Annotated) and item.data is not None:
                        return {"instance": instance_id, **item.data}
                return None

            ids = [i.instance_id for i in client.instances]
            results = await asyncio.gather(
                *(asyncio.wait_for(one(i), timeout_s) for i in ids),
                return_exceptions=True,
            )
            for r in results:
                if isinstance(r, dict):
                    out.append(r)
            return out
        finally:
            await client.close()


@dataclass
class Endpoint:
    runtime: DistributedRuntime
    namespace: str
    component: str
    name: str

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.name}"

    @property
    def instance_prefix(self) -> str:
        return (
            f"{INSTANCE_ROOT_PATH}/{self.namespace}/{self.component}/{self.name}:"
        )

    def subject_for(self, instance_id: int) -> str:
        # Reference subject shape: "{ns}_{comp}.{ep}-{lease_hex}"
        return f"{self.namespace}_{self.component}.{self.name}-{instance_id:x}"

    async def _register(self, register_subject) -> Instance:
        """Shared registration: bind the subject on the data-plane server
        (via ``register_subject(subject)``) and write the instance key under
        the runtime's primary lease: lease loss removes the key, and every
        watching client drops the instance — identical liveness semantics to
        reference endpoint.rs:115-134."""
        rt = self.runtime
        await rt.ensure_data_server()
        instance_id = rt.primary_lease
        subject = self.subject_for(instance_id)
        host, port = rt.data_server.address
        instance = Instance(
            namespace=self.namespace,
            component=self.component,
            endpoint=self.name,
            instance_id=instance_id,
            host=host,
            port=port,
            subject=subject,
        )
        register_subject(subject)
        created = await rt.hub.kv_create(
            instance.etcd_key, instance.to_json(), lease=rt.primary_lease
        )
        if not created:
            await rt.hub.kv_put(
                instance.etcd_key, instance.to_json(), lease=rt.primary_lease
            )
        rt.served.append(instance)
        logger.info("serving %s as instance %x at %s:%d",
                    self.path, instance_id, host, port)
        return instance

    async def serve(
        self,
        engine: AsyncEngine,
        *,
        metrics_handler=None,
    ) -> Instance:
        """Serve ``engine`` on this endpoint."""
        rt = self.runtime
        comp_path = f"{self.namespace}/{self.component}"
        stats = rt.endpoint_stats.setdefault(self.path, EndpointStats())
        handler = _IngressHandler(
            engine,
            stats,
            component=comp_path,
            # the reserved scrape endpoints must not trace themselves: a
            # dashboard polling _trace/_stats would churn the very span
            # ring it is reading
            traced=self.name not in (STATS_ENDPOINT, TRACE_ENDPOINT),
        )

        def register(subject: str) -> None:
            rt.data_server.register(subject, handler)
            rt.local_engines[subject] = engine

        instance = await self._register(register)
        # process-level component tag for spans opened off the ingress task
        # (engine executor threads); first-served component names the process
        if not tracing.collector.component:
            tracing.collector.component = comp_path
        # auto-serve the component's $SRV.STATS equivalent + trace scrape once
        if (
            self.name not in (STATS_ENDPOINT, TRACE_ENDPOINT)
            and comp_path not in rt._stats_served
        ):
            rt._stats_served.add(comp_path)
            await Endpoint(
                rt, self.namespace, self.component, STATS_ENDPOINT
            ).serve(EngineFn(partial(_stats_handler, rt, self.namespace)))
            await Endpoint(
                rt, self.namespace, self.component, TRACE_ENDPOINT
            ).serve(EngineFn(_trace_handler))
        return instance

    async def serve_raw(self, handler) -> Instance:
        """Serve a raw streaming byte handler (upload-capable) on this
        endpoint.  Same discovery/lease semantics as :meth:`serve`, but the
        handler receives ``(hdr, chunks: AsyncIterator[bytes], ctx)`` and
        yields raw response payloads -- no JSON envelope.  This is the bulk
        data path (disagg KV delivery); the reference's equivalent capability
        is the NIXL transfer plane (block_manager/storage/nixl.rs)."""
        rt = self.runtime
        return await self._register(
            lambda subject: rt.data_server.register_raw(subject, handler)
        )

    async def client(self) -> "Client":
        c = Client(self)
        await c.start()
        return c


STATS_ENDPOINT = "_stats"  # reserved; the $SRV.STATS-equivalent endpoint
TRACE_ENDPOINT = "_trace"  # reserved; per-component trace-span scrape


@dataclass
class EndpointStats:
    """Per-endpoint service counters (reference: NATS micro endpoint stats
    surfaced via $SRV.STATS; service.rs stats handler)."""

    requests: int = 0
    errors: int = 0
    in_flight: int = 0
    processing_ms_total: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        avg = self.processing_ms_total / self.requests if self.requests else 0.0
        return {
            "num_requests": self.requests,
            "num_errors": self.errors,
            "in_flight": self.in_flight,
            "processing_ms_total": round(self.processing_ms_total, 3),
            "average_processing_ms": round(avg, 3),
        }


async def _stats_handler(rt, namespace, request):
    """One-item stream with every endpoint's counters in this process."""
    del namespace, request

    async def gen():
        yield Annotated.from_data(
            {
                "endpoints": {
                    path: s.to_dict() for path, s in rt.endpoint_stats.items()
                }
            }
        )

    return gen()


async def _trace_handler(request):
    """One-item stream with this process's spans for a request id (request
    data ``{"request_id": ...}``; no id returns the whole ring) -- the
    per-component scrape behind ``Component.scrape_trace`` and the
    ``dynamo-tpu trace`` CLI."""
    data = request.data if isinstance(request.data, dict) else None
    rid = (data or {}).get("request_id")
    if rid:
        spans = [s.to_dict() for s in tracing.collector.get(rid)]
    else:
        spans = tracing.collector.dump()

    async def gen():
        yield Annotated.from_data(
            {"component": tracing.collector.component, "spans": spans}
        )

    return gen()


class _NullSpan:
    """Stateless stand-in for untraced ingress paths (shared instance)."""

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    def set(self, **attrs: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _IngressHandler:
    """Byte-level ingress: JSON payload -> Context -> engine -> JSON items.

    Reference: Ingress::handle_payload (network/ingress/push_handler.rs:25) —
    rebuild the Context with the caller's request id so cancellation and
    tracing stay end-to-end.
    """

    def __init__(
        self,
        engine: AsyncEngine,
        stats: Optional[EndpointStats] = None,
        component: str = "",
        traced: bool = True,
    ) -> None:
        self.engine = engine
        self.stats = stats
        self.component = component
        self.traced = traced

    async def __call__(
        self, hdr: Dict[str, Any], payload: bytes, ctx: AsyncEngineContext
    ) -> AsyncIterator[bytes]:
        data = json.loads(payload) if payload else None
        request = Context(data=data, ctx=ctx, metadata=hdr.get("meta") or {})
        stats = self.stats
        t0 = time.monotonic()
        # Ingress span: child of the caller's egress span (trace context
        # decoded from the frame header), opened BEFORE the engine runs so
        # everything the engine dispatches downstream -- nested egress hops,
        # executor-thread engine spans (via the request-id binding) -- links
        # under it.  Manually paired: it closes when the stream ends.
        if self.traced:
            parent = None
            if tracing.collector.enabled:
                parent = tracing.TraceContext.from_wire(
                    decode_trace_context(hdr)
                )
            sp = tracing.span(
                "ingress",
                request.id,
                parent=parent,
                component=self.component or None,
                bind=True,
                subject=hdr.get("subject", ""),
            )
        else:
            sp = _NULL_SPAN
        sp.__enter__()
        if stats is not None:
            stats.requests += 1
            stats.in_flight += 1
        try:
            stream = await self.engine.generate(request)
        except BaseException as exc:
            if stats is not None:
                stats.errors += 1
                stats.in_flight -= 1
                stats.processing_ms_total += (time.monotonic() - t0) * 1e3
            sp.__exit__(type(exc), exc, exc.__traceback__)
            raise

        async def gen() -> AsyncIterator[bytes]:
            # Wire contract: every item is an Annotated envelope.  Engines may
            # yield Annotated (signals/errors) or raw payloads (wrapped here).
            failed = False
            n_items = 0
            try:
                async for item in stream:
                    if not isinstance(item, Annotated):
                        item = Annotated.from_data(item)
                    if item.is_error():
                        failed = True
                    n_items += 1
                    yield json.dumps(item.to_dict()).encode()
            except BaseException:
                failed = True
                raise
            finally:
                sp.set(items=n_items, error=failed)
                if ctx.deadline_expired():
                    sp.set(deadline_expired=True)
                sp.__exit__(None, None, None)
                if stats is not None:
                    stats.in_flight -= 1
                    stats.errors += 1 if failed else 0
                    stats.processing_ms_total += (
                        time.monotonic() - t0
                    ) * 1e3

        return gen()


class Client:
    """Endpoint client: live instance list via hub prefix watch.

    Reference: component/client.rs (etcd prefix watcher -> watch channel of
    ``Vec<Instance>``).
    """

    def __init__(self, endpoint: Endpoint) -> None:
        self.endpoint = endpoint
        self.instances: List[Instance] = []
        self._by_key: Dict[str, Instance] = {}
        self._watch: Optional[WatchHandle] = None
        self._task: Optional[asyncio.Task] = None
        self._changed = asyncio.Event()

    async def start(self) -> None:
        self._watch = await self.endpoint.runtime.hub.watch_prefix(
            self.endpoint.instance_prefix
        )
        for key, value in self._watch.snapshot:
            self._by_key[key] = Instance.from_json(value)
        self._rebuild()
        self._task = asyncio.create_task(self._pump())

    def _rebuild(self) -> None:
        self.instances = sorted(
            self._by_key.values(), key=lambda i: i.instance_id
        )
        self._changed.set()

    async def _pump(self) -> None:
        assert self._watch is not None
        with contextlib.suppress(asyncio.CancelledError):
            while True:
                ev = await self._watch.events.get()
                if ev.type == "put":
                    self._by_key[ev.key] = Instance.from_json(ev.value)
                else:
                    self._by_key.pop(ev.key, None)
                self._rebuild()

    async def wait_for_instances(self, timeout: float = 10.0) -> List[Instance]:
        deadline = asyncio.get_running_loop().time() + timeout
        while not self.instances:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"no instances for {self.endpoint.path} after {timeout}s"
                )
            self._changed.clear()
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._changed.wait(), remaining)
        return self.instances

    def instance_ids(self) -> List[int]:
        return [i.instance_id for i in self.instances]

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
        if self._watch:
            await self._watch.close()


class InstanceNotFoundError(RuntimeError):
    """direct() addressed an instance no longer in the live set (stale
    selection -- the worker died between the choice and the dispatch)."""


class NoInstancesError(RuntimeError):
    """No (non-excluded) live instance to dispatch to."""


class RouterMode(str, Enum):
    ROUND_ROBIN = "round_robin"
    RANDOM = "random"
    DIRECT = "direct"


@dataclass
class FailoverPolicy:
    """Bounded request-level failover: a worker lost before it delivered
    any response item is retried on a *different* instance (the failed one
    is excluded from selection) after a full-jitter backoff.  A worker
    lost after output reached the caller is never retried -- redispatching
    could duplicate delivered tokens -- so mid-stream death degrades to an
    immediate error frame instead.

    Env defaults: ``DYN_FAILOVER_ATTEMPTS`` (redispatch budget),
    ``DYN_FAILOVER_BACKOFF_S`` (backoff base)."""

    max_redispatches: int = 2
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 1.0

    @classmethod
    def from_env(cls) -> "FailoverPolicy":
        return cls(
            max_redispatches=int(os.environ.get("DYN_FAILOVER_ATTEMPTS", "2")),
            backoff_base_s=float(
                os.environ.get("DYN_FAILOVER_BACKOFF_S", "0.05")
            ),
        )

    def backoff_s(self, redispatch_index: int) -> float:
        """Full jitter over an exponentially-growing window: concurrent
        failovers off one dead worker spread out instead of stampeding the
        survivors in lockstep."""
        window = min(
            self.backoff_cap_s, self.backoff_base_s * (2 ** redispatch_index)
        )
        return random.uniform(0.0, window)


# Transport-shaped dispatch failures: the request provably delivered
# nothing, so redispatch to another instance cannot duplicate output.
# (WorkerLostError covers conn loss + drained subjects; OSError covers
# refused/failed dials; InstanceNotFoundError covers stale selections.)
_RETRYABLE = (WorkerLostError, InstanceNotFoundError, OSError)


class PushRouter:
    """Instance selection + remote dispatch (reference push_router.rs:35-203).

    ``generate`` picks an instance (round-robin / random), ``direct`` targets
    a specific instance id (the KV router uses this after best-match).
    Yields :class:`Annotated` items.  With a :class:`FailoverPolicy`
    attached, ``generate`` additionally survives worker death before the
    first response item by redispatching to a surviving instance.
    """

    def __init__(
        self,
        client: Client,
        mode: RouterMode = RouterMode.ROUND_ROBIN,
        failover: Optional[FailoverPolicy] = None,
    ) -> None:
        self.client = client
        self.mode = mode
        self.failover = failover
        self._rr = 0

    def _pick(self, exclude: Optional[Set[int]] = None) -> Instance:
        instances = self.client.instances
        if exclude:
            instances = [
                i for i in instances if i.instance_id not in exclude
            ]
        if not instances:
            raise NoInstancesError(
                f"no instances available for {self.client.endpoint.path}"
            )
        if self.mode == RouterMode.RANDOM:
            return random.choice(instances)
        inst = instances[self._rr % len(instances)]
        self._rr += 1
        return inst

    async def generate(
        self, request: Context[Any]
    ) -> ResponseStream[Annotated]:
        if self.failover is not None:
            return ResponseStream(request.ctx, self._failover_gen(request))
        return await self._dispatch(self._pick(), request)

    @staticmethod
    def _count_redispatch(stage: str) -> None:
        from . import metrics as rtm

        rtm.default_registry().counter(
            "dynamo_router_redispatches",
            "Failover redispatches by stage "
            "(dispatch = connect/prologue failed, "
            "before_first_token = stream died with nothing delivered)",
            ["stage"],
        ).labels(stage).inc()

    @staticmethod
    def _flightrec_worker_lost(
        stage: str, instance_id: int, request_id: str
    ) -> None:
        """Worker-loss failover edge: snapshot the flight recorder so the
        postmortem has the tick ring + queue state from the moment of
        loss, not whatever the logs happened to keep."""
        from . import profiling

        profiling.flight_recorder.snapshot(
            "worker_lost",
            stage=stage,
            instance_id=f"{instance_id:x}",
            request_id=request_id,
        )

    async def _failover_gen(
        self, request: Context[Any]
    ) -> AsyncIterator[Annotated]:
        """The failover dispatch loop.  Worker loss *before* any response
        item: exclude the instance, back off with full jitter, redispatch.
        Worker loss *after* output was delivered: immediate error frame
        (never a hang, never a duplicate).  Budget exhausted: error frame
        naming the last failure."""
        policy = self.failover
        assert policy is not None
        excluded: Set[int] = set()
        last_exc: Optional[BaseException] = None
        attempts = policy.max_redispatches + 1
        for attempt in range(attempts):
            if attempt:
                await asyncio.sleep(policy.backoff_s(attempt - 1))
            if request.ctx.is_stopped():
                return
            try:
                inst = self._pick(exclude=excluded)
            except NoInstancesError as e:
                # everyone is dead or excluded; the backoff window also
                # gives the instance watch time to deliver replacements
                last_exc = e
                continue
            try:
                stream = await self._dispatch(inst, request)
            except DeadlineExceededError as e:
                yield Annotated.from_error(str(e) or DEADLINE_EXCEEDED_MSG)
                return
            except _RETRYABLE as e:
                excluded.add(inst.instance_id)
                last_exc = e
                self._count_redispatch("dispatch")
                self._flightrec_worker_lost(
                    "dispatch", inst.instance_id, request.id
                )
                logger.warning(
                    "dispatch to %x failed (%s); redispatching",
                    inst.instance_id, e,
                )
                continue
            delivered = False
            try:
                async for item in stream:
                    delivered = True
                    yield item
                return
            except DeadlineExceededError as e:
                yield Annotated.from_error(str(e) or DEADLINE_EXCEEDED_MSG)
                return
            except _RETRYABLE as e:
                if delivered:
                    # output already reached the caller: a redispatch could
                    # duplicate it -- fail fast with an error frame instead
                    self._flightrec_worker_lost(
                        "mid_stream", inst.instance_id, request.id
                    )
                    yield Annotated.from_error(
                        f"worker {inst.instance_id:x} lost mid-stream: {e}"
                    )
                    return
                excluded.add(inst.instance_id)
                last_exc = e
                self._count_redispatch("before_first_token")
                self._flightrec_worker_lost(
                    "before_first_token", inst.instance_id, request.id
                )
                logger.warning(
                    "worker %x lost before first token (%s); redispatching",
                    inst.instance_id, e,
                )
                continue
        yield Annotated.from_error(
            f"dispatch failed after {attempts} attempts: {last_exc}"
        )

    def _find_instance(self, instance_id: int) -> Instance:
        for inst in self.client.instances:
            if inst.instance_id == instance_id:
                return inst
        raise InstanceNotFoundError(f"instance {instance_id:x} not found")

    async def direct(
        self, request: Context[Any], instance_id: int
    ) -> ResponseStream[Annotated]:
        return await self._dispatch(self._find_instance(instance_id), request)

    async def direct_upload(
        self,
        instance_id: int,
        request_id: str,
        meta: Dict[str, Any],
        chunks: Any,
        ctx,
    ) -> AsyncIterator[bytes]:
        """Stream a bulk upload to a specific instance's raw endpoint and
        return its raw response iterator (the P2P KV delivery path)."""
        inst = self._find_instance(instance_id)
        rt = self.client.endpoint.runtime
        return await rt.data_client.request_upload(
            inst.host, inst.port, inst.subject, request_id, meta, chunks, ctx,
            trace=tracing.wire_context(request_id),
        )

    async def direct_raw(
        self,
        instance_id: int,
        request_id: str,
        meta: Dict[str, Any],
        payload: bytes,
        ctx,
    ) -> AsyncIterator[bytes]:
        """Plain request to a raw endpoint, yielding raw response payloads
        (no Annotated/JSON envelope) -- the bulk download path (cross-worker
        block export)."""
        inst = self._find_instance(instance_id)
        rt = self.client.endpoint.runtime
        return await rt.data_client.request(
            inst.host, inst.port, inst.subject, request_id, meta, payload, ctx,
            trace=tracing.wire_context(request_id),
        )

    async def random(self, request: Context[Any]) -> ResponseStream[Annotated]:
        self.mode = RouterMode.RANDOM
        return await self.generate(request)

    async def round_robin(self, request: Context[Any]) -> ResponseStream[Annotated]:
        self.mode = RouterMode.ROUND_ROBIN
        return await self.generate(request)

    async def _dispatch(
        self, inst: Instance, request: Context[Any]
    ) -> ResponseStream[Annotated]:
        rt = self.client.endpoint.runtime
        # Deadline check at the hop: an expired budget never dispatches --
        # the caller gets its fast 504 without spending a worker on it.
        dl = request.ctx.deadline_remaining()
        if dl is not None and dl <= 0:
            raise DeadlineExceededError()
        # In-process fast path: skip serialization when the instance lives in
        # this very process (static mode pipelines).  Items are wrapped into
        # the same Annotated envelope the remote path produces, so the stream
        # type does not depend on deployment mode.
        local = rt.local_engines.get(inst.subject)
        if local is not None:
            stream = ensure_response_stream(
                request.ctx, await local.generate(request)
            )

            async def local_gen() -> AsyncIterator[Annotated]:
                async for item in stream:
                    if not isinstance(item, Annotated):
                        item = Annotated.from_data(item)
                    yield item

            return ResponseStream(request.ctx, local_gen())

        payload = json.dumps(request.data).encode()
        # Egress span: covers send + prologue; its context rides the frame
        # header so the remote ingress span links under it.  Disabled
        # tracing: span.__enter__ is one attribute check, esp.context is
        # None, and the frame carries no trace field.
        with tracing.span(
            "egress",
            request.id,
            target=self.client.endpoint.path,
            instance=f"{inst.instance_id:x}",
        ) as esp:
            c = esp.context
            byte_stream = await rt.data_client.request(
                inst.host,
                inst.port,
                inst.subject,
                request.id,
                request.metadata,
                payload,
                request.ctx,
                trace=c.to_wire() if c is not None else None,
                # remaining budget rides the frame header next to the trace
                # context; the hop's transit time decrements it naturally
                deadline=dl,
            )

        async def gen() -> AsyncIterator[Annotated]:
            async for raw in byte_stream:
                yield Annotated.from_dict(json.loads(raw))

        return ResponseStream(request.ctx, gen())
