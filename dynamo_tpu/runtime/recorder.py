"""Recorder / replay: capture engine streams as JSONL fixtures.

Reference parity: lib/llm/src/recorder.rs:35 (stream recorder feeding
tests/data/replays) -- the cheapest route to engine-stream regression
tests: record a live engine once, replay the exact stream (optionally with
its original timing) without the engine.

Line format (one JSON object per line, append-only)::

    {"type": "request", "request_id": ..., "ts": ..., "data": ...}
    {"type": "item",    "request_id": ..., "dt": ...,  "data": <Annotated>}
    {"type": "end",     "request_id": ..., "dt": ...}
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import time
from typing import Any, AsyncIterator, Dict, List, Optional

from . import thread_sentry
from .engine import Annotated, AsyncEngine, Context, ResponseStream


class RecordingEngine:
    """AsyncEngine wrapper: pass items through, append them to a JSONL file.

    File I/O rides a dedicated single-writer thread (the same pattern the
    hub WAL uses): ``_write`` is called from inside an async generator on
    the event loop, so the actual ``write()+flush()`` must never run there
    (dynalint DT001).  One worker preserves line order; :meth:`close`
    drains queued lines, then closes the handle."""

    def __init__(self, inner: AsyncEngine, path: str) -> None:
        self.inner = inner
        self.path = path
        self._io = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="recorder-io"
        )
        # open on the writer thread too: every touch of the handle happens
        # on one thread, and the constructor stays loop-safe
        self._fh = None
        self._io.submit(self._open).result()

    def _open(self) -> None:
        """Writer thread only."""
        self._fh = open(self.path, "a", encoding="utf-8")

    def _append(self, line: str) -> None:
        """Writer thread only."""
        thread_sentry.assert_role(
            "recorder-io", what="RecordingEngine._append"
        )
        self._fh.write(line + "\n")
        self._fh.flush()

    def _write(self, entry: Dict[str, Any]) -> None:
        # serialize on the caller (cheap, keeps entry snapshots consistent);
        # hand the disk touch to the writer thread without waiting
        line = json.dumps(entry)
        try:
            self._io.submit(self._append, line)
        except RuntimeError:
            pass  # closed recorder (shutdown race): drop the line

    async def generate(self, request: Context[Any]) -> AsyncIterator[Annotated]:
        t0 = time.monotonic()
        self._write(
            {
                "type": "request",
                "request_id": request.id,
                # request id doubles as the trace id: a recorded request
                # is one hop from GET /trace/{request_id}
                "trace_id": request.id,
                "ts": round(time.time(), 6),
                "data": request.data,
            }
        )
        stream = await self.inner.generate(request)

        async def gen() -> AsyncIterator[Annotated]:
            try:
                async for item in stream:
                    if not isinstance(item, Annotated):
                        item = Annotated.from_data(item)
                    self._write(
                        {
                            "type": "item",
                            "request_id": request.id,
                            "dt": round(time.monotonic() - t0, 6),
                            "data": item.to_dict(),
                        }
                    )
                    yield item
            finally:
                self._write(
                    {
                        "type": "end",
                        "request_id": request.id,
                        "dt": round(time.monotonic() - t0, 6),
                    }
                )

        return ResponseStream(request.ctx, gen())

    def close(self) -> None:
        """Drain queued lines and close the file (blocking; call off-loop or
        via ``asyncio.to_thread`` from async code)."""
        try:
            self._io.submit(self._fh.close)
        except RuntimeError:
            return  # already closed
        self._io.shutdown(wait=True)


def load_recording(path: str) -> List[Dict[str, Any]]:
    """All entries, in file order."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


class ReplayEngine:
    """Replay a recording as an AsyncEngine: the i-th generate() call
    receives the i-th recorded stream (requests replay in recording order,
    matching the reference's replay fixtures).  ``timed=True`` reproduces
    the recorded inter-item gaps (scaled by ``speedup``)."""

    def __init__(
        self, path: str, timed: bool = False, speedup: float = 1.0
    ) -> None:
        self.timed = timed
        self.speedup = max(speedup, 1e-9)
        self._streams: List[List[Dict[str, Any]]] = []
        self._requests: List[Dict[str, Any]] = []
        by_id: Dict[str, List[Dict[str, Any]]] = {}
        for entry in load_recording(path):
            if entry["type"] == "request":
                by_id[entry["request_id"]] = []
                self._requests.append(entry)
                self._streams.append(by_id[entry["request_id"]])
            elif entry["type"] == "item":
                by_id[entry["request_id"]].append(entry)
        self._next = 0

    @property
    def num_recorded(self) -> int:
        return len(self._streams)

    def recorded_request(self, i: int) -> Any:
        return self._requests[i]["data"]

    async def generate(self, request: Context[Any]) -> AsyncIterator[Annotated]:
        if self._next >= len(self._streams):
            raise RuntimeError(
                f"replay exhausted after {len(self._streams)} recorded streams"
            )
        items = self._streams[self._next]
        self._next += 1

        async def gen() -> AsyncIterator[Annotated]:
            prev = 0.0
            for entry in items:
                if self.timed:
                    gap = max(0.0, entry["dt"] - prev) / self.speedup
                    prev = entry["dt"]
                    if gap:
                        await asyncio.sleep(gap)
                yield Annotated.from_dict(entry["data"])

        return ResponseStream(request.ctx, gen())
