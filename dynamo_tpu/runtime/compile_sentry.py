"""Runtime complement of dynalint's recompile rules (DT017-DT018).

The static pass (``analysis/compiles.py``) proves shape discipline only
relative to the blessed-bucketing manifest; this module makes the invariant
checkable where it actually bites -- the XLA compile cache.  Every backend
compilation is attributed to the engine entry point that triggered it and
counted against a per-entry ``COMPILE_BUDGET`` (declared next to the jits in
``engine/step.py``).  Armed with ``DYN_COMPILE_SENTRY=1`` (tier-1 arms it
like the thread sentry), an entry that compiles more distinct executables
than its budget raises ``CompileBudgetError`` at the moment of the overrun,
so an unbucketed shape fails a test instead of silently melting the cache.

Event source: ``jax.monitoring``'s
``/jax/core/compile/backend_compile_duration`` duration event, which fires
once per *new* executable (cache hits are free) synchronously on the thread
that called the jitted function.  The engine's dispatches run on the
"jax-engine" executor thread, so attribution uses a ``threading.local``
label set by the ``entry(...)`` context manager around each dispatch -- a
contextvar set in the tick coroutine would not be visible there.

This module itself never imports jax: ``install()`` does, lazily, so the
mocker (and any jax-free consumer) can feed synthetic events through
``note_compilation(entry=...)`` directly -- each distinct fused-K value the
mocker mints maps to a distinct ``lax.scan``-length executable in the real
engine, so the mocker is an honest device-free event source.

Overhead discipline (the thread-sentry pattern): disarmed, the budget check
is one module-global bool; counting + the ``dynamo_compile_events_total``
counter stay live either way so bench legs can price recompiles unarmed.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
from typing import Dict, Iterator, Mapping, Optional

logger = logging.getLogger("dynamo.compile_sentry")

ENV_VAR = "DYN_COMPILE_SENTRY"

_ARMED = os.environ.get(ENV_VAR, "").strip().lower() not in (
    "", "0", "false", "no", "off",
)

#: the jax.monitoring duration event that fires once per backend compile
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: label used when a compile fires outside any ``entry(...)`` scope
UNATTRIBUTED = "unattributed"


class CompileBudgetError(AssertionError):
    """An entry point compiled more executables than its declared budget."""


def armed() -> bool:
    return _ARMED


def arm(on: bool = True) -> bool:
    """Flip the sentry (tests).  Returns the previous state."""
    global _ARMED
    prev = _ARMED
    _ARMED = bool(on)
    return prev


# ---------------------------------------------------------------------------
# entry attribution + counts

_tls = threading.local()

_lock = threading.Lock()
_counts: Dict[str, int] = {}
_budgets: Dict[str, int] = {}

_installed = False

# lazy per-registry counter (the profiling pattern: rebuild when the
# default registry is swapped by a test or a fresh serving process)
_counter = None
_counter_reg = None


def _metric():
    global _counter, _counter_reg
    from . import metrics as rtm

    reg = rtm.default_registry()
    if _counter is None or _counter_reg is not reg:
        _counter = reg.counter(
            "dynamo_compile_events_total",
            "XLA backend compilations attributed to the engine entry point "
            "whose dispatch triggered them",
            ("entry",),
        )
        _counter_reg = reg
    return _counter


@contextlib.contextmanager
def entry(name: str) -> Iterator[None]:
    """Attribute compilations on THIS thread to ``name`` for the scope.

    Nestable; the innermost label wins (a packed dispatch that lazily
    builds a helper executable attributes the helper's compile to the
    packed entry, which is the budget that pays for it)."""
    prev = getattr(_tls, "entry", None)
    _tls.entry = name
    try:
        yield
    finally:
        _tls.entry = prev


def set_entry(name: Optional[str]) -> None:
    """Sticky thread-local label: dispatch-plane functions call this at
    entry and the label holds until the next set on the same thread.  The
    engine's device work is phase-structured (dispatch -> commit -> KV
    maintenance, each of which labels itself), so sticky semantics
    attribute every compile to the phase that is actually running; use
    the ``entry(...)`` context manager where scoped restore matters."""
    _tls.entry = name


def current_entry() -> Optional[str]:
    return getattr(_tls, "entry", None)


def register_budgets(budgets: Mapping[str, int]) -> None:
    """Merge per-entry compile budgets (``engine/step.py`` registers its
    ``COMPILE_BUDGET`` at import).  Budgets are ceilings on TOTAL compile
    events per entry within this process; only registered entries are
    enforced, so ad-hoc entries count and export but never raise."""
    with _lock:
        for name, limit in budgets.items():
            _budgets[name] = int(limit)


def budgets() -> Dict[str, int]:
    with _lock:
        return dict(_budgets)


def counts() -> Dict[str, int]:
    """Snapshot of per-entry compile-event counts (bench legs diff this)."""
    with _lock:
        return dict(_counts)


def total() -> int:
    with _lock:
        return sum(_counts.values())


def reset() -> None:
    """Zero the per-entry counts (tests; the prometheus counter, being
    monotonic by contract, is left alone)."""
    with _lock:
        _counts.clear()


def note_compilation(entry_name: Optional[str] = None) -> None:
    """Record one compile event.

    Called by the jax.monitoring listener (entry resolved from the
    thread-local label) and directly by synthetic sources (mocker).  When
    armed and the entry has a registered budget, an overrun raises
    immediately -- the thread-sentry contract: fail at the site, on the
    thread that did it."""
    name = entry_name or current_entry() or UNATTRIBUTED
    with _lock:
        _counts[name] = _counts.get(name, 0) + 1
        count = _counts[name]
        limit = _budgets.get(name)
    try:
        _metric().labels(name).inc()
    except Exception:  # metrics must never break the compile path
        logger.debug("compile-event metric emit failed", exc_info=True)
    try:
        from . import profiling

        profiling.profiler.note_compile_event(name)
    except Exception:
        logger.debug("compile-event profiler note failed", exc_info=True)
    if _ARMED and limit is not None and count > limit:
        raise CompileBudgetError(
            f"compile budget overrun: entry {name!r} compiled {count} "
            f"executables, budget {limit} (set {ENV_VAR}=0 to disarm; if "
            f"the shape set legitimately grew, raise COMPILE_BUDGET in "
            f"engine/step.py)"
        )


def _on_event(event: str, duration: float, **kwargs: object) -> None:
    if event == COMPILE_EVENT:
        note_compilation()


def install() -> bool:
    """Idempotently register the jax.monitoring compile listener.

    Returns True when the listener is (already) registered, False when jax
    or its monitoring API is unavailable (mocker-only processes)."""
    global _installed
    if _installed:
        return True
    try:
        from jax import monitoring  # deferred: module stays jax-free
    except Exception:
        logger.debug("jax.monitoring unavailable; sentry not installed",
                     exc_info=True)
        return False
    register = getattr(
        monitoring, "register_event_duration_secs_listener", None
    )
    if register is None:
        return False
    register(_on_event)
    _installed = True
    return True
