"""Worker-side fleet telemetry: compact periodic snapshots over the hub.

Every observability plane in the repo stops at the worker boundary -- the
tick profiler, SLO tracker, and flight recorder all describe ONE process.
This module is the outbound half of the fleet plane (ISSUE 18): each
worker periodically publishes a :class:`TelemetrySnapshot` -- worker id,
role, ``MetricsRegistry`` cumulative counters (the receiver computes
deltas), KV pressure, queue depth, SLO attainment, and recent KV-transfer
observations -- on the hub event subject ``{ns}.events.fleet_telemetry``.
The frontend/planner-side consumer is
:class:`dynamo_tpu.fleet.observatory.FleetObservatory`.

Design points:

* **Cumulative, not delta, counters on the wire.**  A lost snapshot then
  costs one sampling interval of resolution, never silent drift: the
  observatory diffs consecutive cumulative values and a gap simply
  stretches the interval.
* **The publisher never blocks the hot loop.**  It samples the registry on
  its own timer task (the ``KvEventPublisher`` queue+pump discipline);
  registry reads are lock-cheap gauge walks.
* **Transfer observations ride the snapshot.**  The disagg prefill worker
  notes each delivery into a :class:`TransferLog` (src/dst worker ids,
  bytes, seconds); the publisher drains the log into the next snapshot so
  the observatory's per-(src, dst) link model sees real samples without a
  second event stream.
* **Restart detection is first-class.**  ``started_ts`` stamps the
  publisher's birth; a changed value under the same worker id tells the
  observatory to reset that worker's rings and link-model edges instead
  of diffing counters across a process boundary.
"""

from __future__ import annotations

import asyncio
import collections
import contextlib
import json
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("dynamo.telemetry")

TELEMETRY_TOPIC = "fleet_telemetry"

# snapshot schema version: the observatory ignores majors it does not speak
SCHEMA = 1


class TransferLog:
    """Bounded ring of KV-transfer observations awaiting publication.

    ``note()`` is called from delivery paths (disagg upload completion,
    the mocker's synthetic link); ``drain()`` is called by the telemetry
    publisher.  Thread-safe: deliveries may complete on executor threads.
    """

    def __init__(self, capacity: int = 1024) -> None:
        self._ring: "collections.deque" = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def note(
        self, src: int, dst: int, nbytes: int, seconds: float
    ) -> None:
        if nbytes <= 0 or seconds < 0:
            return
        with self._lock:
            self._ring.append(
                {
                    "src": int(src),
                    "dst": int(dst),
                    "bytes": int(nbytes),
                    "seconds": round(float(seconds), 9),
                }
            )

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


# Process-wide log: production topology runs one worker per process, so
# delivery sites (disagg) note here without plumbing a handle.  In-process
# fleets (mocker tests) give each engine its own TransferLog instead.
transfers = TransferLog()


def note_transfer(src: int, dst: int, nbytes: int, seconds: float) -> None:
    transfers.note(src, dst, nbytes, seconds)


@dataclass
class TelemetrySnapshot:
    """One worker's periodic state report (wire form: compact JSON)."""

    worker_id: int
    role: str  # "prefill" | "decode" | "frontend" | ...
    seq: int
    ts: float
    started_ts: float
    # cumulative counters (receiver diffs consecutive snapshots)
    tokens_generated: float = 0.0
    step_count: float = 0.0
    step_seconds: float = 0.0
    prefix_hit_tokens: float = 0.0
    prefix_lookup_tokens: float = 0.0
    # instantaneous gauges
    kv_pages_used: int = 0
    kv_pages_total: int = 0
    kv_utilization: float = 0.0
    queue_depth: int = 0
    batch_occupancy: int = 0
    batch_slots: int = 0
    # SLO attainment by kind (absent kind = tracker disarmed / no samples)
    slo: Dict[str, float] = field(default_factory=dict)
    # cumulative SLO violation counts keyed "kind/cause" (the in-process
    # SloTracker.violation_count values) -- the observatory forwards the
    # TTFT queue/service pair into ForwardPassMetrics so an off-worker
    # planner can attribute misses exactly like a colocated one
    slo_violations: Dict[str, float] = field(default_factory=dict)
    # KV-transfer observations since the previous snapshot
    transfers: List[Dict[str, Any]] = field(default_factory=list)
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "v": SCHEMA,
            "worker_id": self.worker_id,
            "role": self.role,
            "seq": self.seq,
            "ts": round(self.ts, 6),
            "started_ts": round(self.started_ts, 6),
            "tokens_generated": self.tokens_generated,
            "step_count": self.step_count,
            "step_seconds": round(self.step_seconds, 9),
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_lookup_tokens": self.prefix_lookup_tokens,
            "kv_pages_used": self.kv_pages_used,
            "kv_pages_total": self.kv_pages_total,
            "kv_utilization": round(self.kv_utilization, 6),
            "queue_depth": self.queue_depth,
            "batch_occupancy": self.batch_occupancy,
            "batch_slots": self.batch_slots,
            "slo": {k: round(v, 6) for k, v in self.slo.items()},
            "slo_violations": dict(self.slo_violations),
            "transfers": list(self.transfers),
            "extra": dict(self.extra),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TelemetrySnapshot":
        known = {
            "worker_id": int(d["worker_id"]),
            "role": str(d.get("role", "")),
            "seq": int(d.get("seq", 0)),
            "ts": float(d.get("ts", 0.0)),
            "started_ts": float(d.get("started_ts", 0.0)),
            "tokens_generated": float(d.get("tokens_generated", 0.0)),
            "step_count": float(d.get("step_count", 0.0)),
            "step_seconds": float(d.get("step_seconds", 0.0)),
            "prefix_hit_tokens": float(d.get("prefix_hit_tokens", 0.0)),
            "prefix_lookup_tokens": float(
                d.get("prefix_lookup_tokens", 0.0)
            ),
            "kv_pages_used": int(d.get("kv_pages_used", 0)),
            "kv_pages_total": int(d.get("kv_pages_total", 0)),
            "kv_utilization": float(d.get("kv_utilization", 0.0)),
            "queue_depth": int(d.get("queue_depth", 0)),
            "batch_occupancy": int(d.get("batch_occupancy", 0)),
            "batch_slots": int(d.get("batch_slots", 0)),
            "slo": {
                str(k): float(v) for k, v in (d.get("slo") or {}).items()
            },
            "slo_violations": {
                str(k): float(v)
                for k, v in (d.get("slo_violations") or {}).items()
            },
            "transfers": list(d.get("transfers") or []),
            "extra": dict(d.get("extra") or {}),
        }
        return cls(**known)

    def encode(self) -> bytes:
        return json.dumps(self.to_dict(), separators=(",", ":")).encode()

    @classmethod
    def decode(cls, blob: bytes) -> "TelemetrySnapshot":
        return cls.from_dict(json.loads(blob))


def _hist_totals(registry, name: str) -> Tuple[float, float]:
    """(count, sum) across every label set of one histogram family."""
    count = total = 0.0
    for metric in registry.registry.collect():
        if metric.name != name:
            continue
        for s in metric.samples:
            if s.name == name + "_count":
                count += float(s.value)
            elif s.name == name + "_sum":
                total += float(s.value)
    return count, total


def snapshot_from_registry(
    registry=None,
    *,
    worker_id: int,
    role: str,
    seq: int = 0,
    started_ts: float = 0.0,
    transfer_log: Optional[TransferLog] = None,
    refresh_slo: bool = True,
) -> TelemetrySnapshot:
    """Build a snapshot from the exact series ``/metrics`` exports
    (``dynamo_engine_*`` + ``dynamo_slo_attainment``) -- the fleet plane
    and local dashboards can never disagree about what the load was."""
    from . import metrics as rtm
    from . import slo as _slo

    reg = registry or rtm.default_registry()

    def val(name: str) -> float:
        return reg.sample(name) or 0.0

    if refresh_slo:
        _slo.tracker.refresh_gauges()
    slo_att: Dict[str, float] = {}
    for kind in _slo.KINDS:
        got = reg.sample("dynamo_slo_attainment", {"kind": kind})
        if got is not None:
            slo_att[kind] = got
    slo_viol: Dict[str, float] = {}
    if _slo.tracker.enabled:
        for kind in _slo.KINDS:
            for cause in _slo.CAUSES:
                n = _slo.tracker.violation_count(kind, cause)
                if n:
                    slo_viol[f"{kind}/{cause}"] = float(n)

    step_count, step_seconds = _hist_totals(
        reg, "dynamo_engine_step_latency_seconds"
    )
    log = transfer_log if transfer_log is not None else transfers
    return TelemetrySnapshot(
        worker_id=worker_id,
        role=role,
        seq=seq,
        ts=time.time(),
        started_ts=started_ts,
        tokens_generated=val("dynamo_engine_tokens_generated"),
        step_count=step_count,
        step_seconds=step_seconds,
        prefix_hit_tokens=val("dynamo_engine_prefix_hit_tokens"),
        prefix_lookup_tokens=val("dynamo_engine_prefix_lookup_tokens"),
        kv_pages_used=int(val("dynamo_engine_kv_pages_used")),
        kv_pages_total=int(val("dynamo_engine_kv_pages_total")),
        kv_utilization=val("dynamo_engine_kv_utilization"),
        queue_depth=int(val("dynamo_engine_prefill_queue_depth")),
        batch_occupancy=int(val("dynamo_engine_batch_occupancy")),
        batch_slots=int(val("dynamo_engine_batch_slots")),
        slo=slo_att,
        slo_violations=slo_viol,
        transfers=log.drain(),
    )


class TelemetryPublisher:
    """Periodic snapshot publisher: samples the registry on its own timer
    and ships each snapshot to the hub topic and/or an in-process sink.

    ``namespace`` is a :class:`~dynamo_tpu.runtime.component.Namespace`
    (hub pub/sub); ``sink`` is a plain callable receiving the snapshot
    dict (colocated observatory, tests).  Either may be None; with both
    None :meth:`publish_once` still returns the snapshot, which is how
    pull-style integrations (bench probes) use it.
    """

    def __init__(
        self,
        namespace=None,
        *,
        worker_id: int,
        role: str,
        registry=None,
        interval_s: float = 1.0,
        transfer_log: Optional[TransferLog] = None,
        sink: Optional[Callable[[Dict[str, Any]], None]] = None,
    ) -> None:
        self.namespace = namespace
        self.worker_id = int(worker_id)
        self.role = role
        self.registry = registry
        self.interval_s = max(float(interval_s), 0.01)
        self.transfer_log = transfer_log
        self.sink = sink
        self.started_ts = time.time()
        self.seq = 0
        self._task: Optional[asyncio.Task] = None

    def collect(self) -> TelemetrySnapshot:
        self.seq += 1
        return snapshot_from_registry(
            self.registry,
            worker_id=self.worker_id,
            role=self.role,
            seq=self.seq,
            started_ts=self.started_ts,
            transfer_log=self.transfer_log,
        )

    async def publish_once(self) -> TelemetrySnapshot:
        snap = self.collect()
        payload = snap.to_dict()
        if self.sink is not None:
            try:
                self.sink(payload)
            except Exception:
                logger.exception("telemetry sink failed")
        if self.namespace is not None:
            await self.namespace.publish(TELEMETRY_TOPIC, payload)
        return snap

    async def _loop(self) -> None:
        while True:
            try:
                await self.publish_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # a hub hiccup must not kill the worker's telemetry forever
                logger.exception("telemetry publish failed")
            await asyncio.sleep(self.interval_s)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(
                self._loop(), name=f"telemetry-pub-{self.worker_id}"
            )

    async def stop(self, final: bool = True) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._task
            self._task = None
        if final:
            # parting snapshot: the observatory sees the final counters
            # (and drained transfer log) instead of a truncated series
            with contextlib.suppress(Exception):
                await self.publish_once()
