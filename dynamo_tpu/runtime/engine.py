"""Async engine core: the universal compute abstraction of the runtime.

Every unit of work in the framework -- an HTTP handler, a preprocessor, a
router, a remote worker, the JAX engine itself -- implements the same shape:

    engine.generate(Context[Req]) -> AsyncIterator[Resp]   (a ResponseStream)

with cooperative cancellation carried by the ``AsyncEngineContext`` attached to
the request's :class:`Context` wrapper.

Reference parity: mirrors the semantics of ``AsyncEngine`` /
``AsyncEngineContext`` / ``ResponseStream`` in the reference runtime
(lib/runtime/src/engine.rs:22-168) and ``Context<T>``
(lib/runtime/src/pipeline/context.rs), re-designed for Python asyncio: engines
are objects with an async ``generate`` method returning an async iterator, and
cancellation is an ``asyncio.Event`` pair (graceful stop vs. hard kill) instead
of tokio CancellationTokens.
"""

from __future__ import annotations

import asyncio
import contextlib
import time
import uuid
from dataclasses import dataclass, field
from typing import (
    Any,
    AsyncIterator,
    Awaitable,
    Callable,
    Dict,
    Generic,
    Optional,
    Protocol,
    TypeVar,
    runtime_checkable,
)

T = TypeVar("T")
U = TypeVar("U")


# The canonical deadline-expiry message: error frames carry it, and the
# HTTP frontend classifies error frames bearing it as 504.  One constant,
# shared by every producer and the classifier, so they cannot drift.
DEADLINE_EXCEEDED_MSG = "deadline exceeded"


class DeadlineExceededError(RuntimeError):
    """A request's deadline budget expired before it completed.  Maps to
    HTTP 504 at the frontend; transports answer it with a fast error frame
    instead of computing for a caller that stopped waiting."""

    def __init__(self, message: str = DEADLINE_EXCEEDED_MSG) -> None:
        super().__init__(message)


class AsyncEngineContext:
    """Per-request control surface: id, stop/kill signals, completion.

    ``stop_generating`` asks the producer to finish gracefully (emit what it
    has, then end the stream).  ``kill`` demands immediate termination (no
    further items).  Reference: engine.rs:47-85.

    An optional *deadline budget* (seconds remaining) rides along: it is
    re-anchored on the local monotonic clock at every hop (the wire carries
    relative seconds, ``codec.encode_deadline_context``), checked before
    work is admitted, and enforced mid-stream by transport watchdogs that
    ``kill`` the context at expiry.
    """

    __slots__ = (
        "_id", "_stopped", "_killed", "_complete", "_children", "_deadline",
    )

    def __init__(self, request_id: Optional[str] = None) -> None:
        self._id = request_id or uuid.uuid4().hex
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()
        self._complete = asyncio.Event()
        self._children: list["AsyncEngineContext"] = []
        self._deadline: Optional[float] = None  # absolute time.monotonic()

    @property
    def id(self) -> str:
        return self._id

    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    def is_killed(self) -> bool:
        return self._killed.is_set()

    def is_complete(self) -> bool:
        return self._complete.is_set()

    def stop_generating(self) -> None:
        self._stopped.set()
        for child in self._children:
            child.stop_generating()

    def kill(self) -> None:
        self._killed.set()
        self._stopped.set()
        for child in self._children:
            child.kill()

    def set_complete(self) -> None:
        self._complete.set()

    async def stopped(self) -> None:
        await self._stopped.wait()

    async def killed(self) -> None:
        await self._killed.wait()

    def link_child(self, child: "AsyncEngineContext") -> None:
        """Propagate stop/kill to a downstream context (cross-process hops
        re-create the context; linking keeps the cancellation chain intact)."""
        self._children.append(child)
        if self.is_killed():
            child.kill()
        elif self.is_stopped():
            child.stop_generating()

    # -- deadline budget ---------------------------------------------------

    def set_deadline(self, remaining_s: float) -> None:
        """Arm (or re-anchor, on a hop) the deadline budget: ``remaining_s``
        seconds from now on this host's monotonic clock."""
        self._deadline = time.monotonic() + remaining_s

    def deadline_remaining(self) -> Optional[float]:
        """Seconds left in the budget (may be negative), or None when no
        deadline is armed -- the value the next hop's header carries."""
        if self._deadline is None:
            return None
        return self._deadline - time.monotonic()

    def deadline_expired(self) -> bool:
        return self._deadline is not None and time.monotonic() >= self._deadline


@dataclass
class Context(Generic[T]):
    """Request envelope: payload + id + metadata + cancellation context.

    Reference: ``Context<T>`` (pipeline/context.rs) — the id travels across
    process boundaries inside the request-plane control header so that remote
    cancellation and tracing work end to end.
    """

    data: T
    ctx: AsyncEngineContext = field(default_factory=AsyncEngineContext)
    metadata: Dict[str, Any] = field(default_factory=dict)

    @property
    def id(self) -> str:
        return self.ctx.id

    def map(self, fn: Callable[[T], U]) -> "Context[U]":
        """Transform the payload while preserving id/context/metadata."""
        return Context(data=fn(self.data), ctx=self.ctx, metadata=self.metadata)

    def replace(self, data: U) -> "Context[U]":
        return Context(data=data, ctx=self.ctx, metadata=self.metadata)

    @classmethod
    def new(cls, data: T, request_id: Optional[str] = None) -> "Context[T]":
        return cls(data=data, ctx=AsyncEngineContext(request_id))


class ResponseStream(Generic[U]):
    """An async iterator of responses bound to an AsyncEngineContext.

    Wraps a raw async generator so consumers can reach the context (for
    cancellation) without plumbing it separately.  Iteration stops early when
    the context is killed.
    """

    def __init__(self, ctx: AsyncEngineContext, gen: AsyncIterator[U]) -> None:
        self._ctx = ctx
        self._gen = gen
        self._kill_waiter: Optional[asyncio.Task] = None

    @property
    def ctx(self) -> AsyncEngineContext:
        return self._ctx

    def __aiter__(self) -> "ResponseStream[U]":
        return self

    async def __anext__(self) -> U:
        ctx = self._ctx
        if ctx.is_killed():
            await self._shutdown_killed()
            raise StopAsyncIteration
        # Race the producer against kill: "immediate termination" must hold
        # even when the producer is blocked awaiting a stalled backend.
        if self._kill_waiter is None or self._kill_waiter.done():
            self._kill_waiter = asyncio.ensure_future(ctx.killed())
        nxt = asyncio.ensure_future(self._gen.__anext__())
        try:
            await asyncio.wait(
                {nxt, self._kill_waiter}, return_when=asyncio.FIRST_COMPLETED
            )
        except asyncio.CancelledError:
            nxt.cancel()
            raise
        if nxt.done():
            try:
                # dynalint: disable=DT001 -- guarded by nxt.done(): non-blocking
                return nxt.result()
            except StopAsyncIteration:
                ctx.set_complete()
                self._cleanup_waiter()
                raise
        # kill fired while the producer was still pending
        nxt.cancel()
        with contextlib.suppress(asyncio.CancelledError, Exception):
            await nxt
        await self._shutdown_killed()
        raise StopAsyncIteration

    def _cleanup_waiter(self) -> None:
        if self._kill_waiter is not None and not self._kill_waiter.done():
            self._kill_waiter.cancel()
        self._kill_waiter = None

    def __del__(self) -> None:
        # a consumer that breaks out of iteration without aclose() must not
        # leak the kill-race task ("Task was destroyed but it is pending")
        w = self._kill_waiter
        if w is not None and not w.done():
            w.cancel()

    async def _shutdown_killed(self) -> None:
        self._cleanup_waiter()
        await self._dispose()

    async def _dispose(self) -> None:
        aclose = getattr(self._gen, "aclose", None)
        if aclose is not None:
            with contextlib.suppress(Exception):
                await aclose()

    async def aclose(self) -> None:
        self._cleanup_waiter()
        await self._dispose()


@runtime_checkable
class AsyncEngine(Protocol[T, U]):
    """The universal compute interface (reference engine.rs:104-109).

    ``generate`` accepts a :class:`Context`-wrapped request and returns an
    async iterator of responses.  Implementations may return a plain async
    generator; pipeline glue wraps it into a :class:`ResponseStream`.
    """

    async def generate(self, request: Context[T]) -> AsyncIterator[U]:
        ...


class EngineFn(Generic[T, U]):
    """Adapt a plain ``async def fn(request) -> async iterator`` into an engine."""

    def __init__(
        self, fn: Callable[[Context[T]], Awaitable[AsyncIterator[U]]]
    ) -> None:
        self._fn = fn

    async def generate(self, request: Context[T]) -> AsyncIterator[U]:
        return await self._fn(request)


def ensure_response_stream(
    ctx: AsyncEngineContext, out: AsyncIterator[U]
) -> ResponseStream[U]:
    """Normalize an engine's output into a ResponseStream (idempotent)."""
    if isinstance(out, ResponseStream):
        return out
    return ResponseStream(ctx, out)


async def as_response_stream(
    engine: AsyncEngine[T, U], request: Context[T]
) -> ResponseStream[U]:
    """Invoke an engine and normalize its output into a ResponseStream."""
    return ensure_response_stream(request.ctx, await engine.generate(request))


@dataclass
class Annotated(Generic[U]):
    """SSE-style envelope: payload plus optional event/comment annotations.

    Reference: protocols/annotated.rs.  Used on every response hop so that
    out-of-band signals (errors, ``formatted_prompt`` / ``token_ids``
    annotations, completion sentinels) ride the same stream as data.
    """

    data: Optional[U] = None
    event: Optional[str] = None
    comment: Optional[list] = None
    id: Optional[str] = None

    @classmethod
    def from_data(cls, data: U) -> "Annotated[U]":
        return cls(data=data)

    @classmethod
    def from_error(cls, message: str) -> "Annotated[U]":
        return cls(event="error", comment=[message])

    @classmethod
    def from_annotation(cls, name: str, value: Any) -> "Annotated[Any]":
        import json

        return cls(event=name, comment=[json.dumps(value)])

    def is_error(self) -> bool:
        return self.event == "error"

    def error_message(self) -> Optional[str]:
        if self.is_error():
            return "; ".join(self.comment or ["unknown error"])
        return None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.data is not None:
            out["data"] = self.data
        if self.event is not None:
            out["event"] = self.event
        if self.comment is not None:
            out["comment"] = self.comment
        if self.id is not None:
            out["id"] = self.id
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Annotated[Any]":
        return cls(
            data=d.get("data"),
            event=d.get("event"),
            comment=d.get("comment"),
            id=d.get("id"),
        )
