"""Hub client: the async API every runtime component uses for discovery,
events, queues and small objects.

Two interchangeable implementations:

* :class:`HubClient` -- TCP connection to a :class:`~.hub.HubServer`
  (distributed mode).
* :class:`StaticHub` -- in-process :class:`~.hub.HubState` (static mode, no
  sockets; reference distributed.rs:85 "static mode, no etcd").

Both expose the same coroutine surface, so Namespace/Component/Endpoint and
everything above them is transport-agnostic.
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
import logging
import random
from dataclasses import dataclass
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

from .. import faults, tracing
from .codec import encode_trace_context, read_frame, write_frame
from .hub import HubState, WatchEvent

logger = logging.getLogger("dynamo.hub.client")

# poison pill pushed into every watch/subscription queue when the hub
# connection drops: consumers must fail loudly, never hang on a dead stream
_CONN_LOST = object()


@dataclass
class WatchHandle:
    """A live prefix watch: initial snapshot + a stream of deltas."""

    snapshot: List[Tuple[str, bytes]]
    events: "asyncio.Queue[WatchEvent]"
    watch_id: int
    _close: Any = None

    async def close(self) -> None:
        if self._close is not None:
            await self._close()

    async def __aiter__(self) -> AsyncIterator[WatchEvent]:
        while True:
            ev = await self.events.get()
            if ev is _CONN_LOST:
                # re-enqueue so every current and future consumer fails too
                self.events.put_nowait(_CONN_LOST)
                raise ConnectionError("hub connection lost (watch orphaned)")
            yield ev


@dataclass
class Subscription:
    queue: "asyncio.Queue[Tuple[str, bytes]]"
    sub_id: int
    _close: Any = None

    async def next(self) -> Tuple[str, bytes]:
        return await self.__anext__()

    async def close(self) -> None:
        if self._close is not None:
            await self._close()

    def __aiter__(self) -> "Subscription":
        return self

    async def __anext__(self) -> Tuple[str, bytes]:
        msg = await self.queue.get()
        if msg is _CONN_LOST:
            # re-enqueue so every current and future consumer fails too
            self.queue.put_nowait(_CONN_LOST)
            raise ConnectionError("hub connection lost (subscription orphaned)")
        return msg


class HubClient:
    """TCP client for HubServer with request/response correlation.

    A single connection carries all ops; server-initiated frames (watch
    events, subscription messages, blocking queue pops) are demuxed to their
    owning handle's queue by id.
    """

    def __init__(
        self, host: str, port: int, reconnect_window: float = 0.0
    ) -> None:
        self.host = host
        self.port = port
        # fires once when the connection drops un-asked (not on close());
        # components register shutdown here -- the reference gets the same
        # property from etcd lease loss + CriticalTaskExecutionHandle
        self.on_connection_lost: Optional[Any] = None
        # > 0: on connection loss, retry connecting for this many seconds
        # (backoff), then re-establish watches/subscriptions and resume
        # lease keepalives -- the durable-hub restart-survival path.  The
        # restored hub holds this client's lease-bound keys (HubJournal),
        # so reconnect + keepalive is a full recovery with no
        # re-registration.  0 keeps loss fatal (fail-fast mode).
        self.reconnect_window = reconnect_window
        self._closing = False
        self._conn_lost = False
        self._connected = asyncio.Event()
        self._seq = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watches: Dict[int, asyncio.Queue] = {}
        self._watch_prefixes: Dict[int, str] = {}
        self._subs: Dict[int, asyncio.Queue] = {}
        self._sub_patterns: Dict[int, str] = {}
        # Events for ids whose local queue isn't registered yet: the pump can
        # see a watch/sub frame before the registering coroutine resumes.
        self._early: Dict[Tuple[str, int], list] = {}
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pump: Optional[asyncio.Task] = None
        self._reconnect_task: Optional[asyncio.Task] = None
        self._reconnecting = False
        self._keepalives: Dict[int, asyncio.Task] = {}
        self._send_lock = asyncio.Lock()
        # strong refs for on_connection_lost callback coroutines (a bare
        # ensure_future can be GC'd mid-await, silently dropping the
        # notification -- dynalint DT008's hazard class)
        self._bg_tasks: set = set()

    def _spawn_bg(self, coro: Any) -> None:
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    async def connect(self) -> "HubClient":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )
        self._pump = asyncio.create_task(self._pump_loop())
        self._connected.set()
        return self

    async def close(self) -> None:
        self._closing = True
        # release callers parked on the reconnect gate: they re-check
        # _conn_lost and raise instead of riding out the window
        self._conn_lost = True
        self._connected.set()
        for task in self._keepalives.values():
            task.cancel()
        if self._reconnect_task:
            self._reconnect_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._reconnect_task
        if self._pump:
            self._pump.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._pump
        if self._writer:
            self._writer.close()
            with contextlib.suppress(Exception):
                await self._writer.wait_closed()

    async def _pump_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                frame = await read_frame(self._reader)
                if frame is None:
                    break
                hdr, payload = frame
                if faults.injector.enabled:
                    # chaos plane: drop or delay incoming hub frames (watch
                    # events, sub messages, RPC responses) to exercise the
                    # reconnect / stale-view recovery paths deterministically
                    if faults.injector.should_fire("hub.frame_drop"):
                        continue
                    await faults.injector.maybe_delay("hub.frame_delay")
                if "watch" in hdr:
                    ev = WatchEvent(hdr["type"], hdr["key"], payload)
                    q = self._watches.get(hdr["watch"])
                    if q is not None:
                        q.put_nowait(ev)
                    else:
                        self._early.setdefault(("w", hdr["watch"]), []).append(ev)
                elif "sub" in hdr:
                    msg = (hdr["subject"], payload)
                    q = self._subs.get(hdr["sub"])
                    if q is not None:
                        q.put_nowait(msg)
                    else:
                        self._early.setdefault(("s", hdr["sub"]), []).append(msg)
                elif "seq" in hdr:
                    fut = self._pending.pop(hdr["seq"], None)
                    if fut is not None and not fut.done():
                        fut.set_result((hdr, payload))
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001
            logger.warning("hub connection lost: %s", exc)
        finally:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("hub connection closed"))
            self._pending.clear()
            if not self._closing:
                self._connected.clear()
                if self.reconnect_window > 0:
                    if self._reconnecting:
                        # this pump belonged to a reconnect attempt that
                        # failed mid-reestablish; the active reconnect loop
                        # owns recovery -- a second loop would race it
                        return
                    # durable-hub mode: try to ride out a hub restart before
                    # declaring the cluster view dead
                    logger.warning(
                        "hub connection lost; reconnecting for up to %.0fs",
                        self.reconnect_window,
                    )
                    self._reconnect_task = asyncio.create_task(
                        self._reconnect_loop(), name="hub-reconnect"
                    )
                else:
                    self._fail_connection()

    def _fail_connection(self) -> None:
        """Unrecoverable loss: every watch, subscription and lease this
        client held is orphaned server-side.  Poison the local streams and
        notify, so the process fails loudly instead of serving from a
        silently frozen view of the cluster."""
        self._conn_lost = True
        # wake callers parked on the reconnect gate; they re-check
        # _conn_lost and raise immediately instead of riding out the window
        self._connected.set()
        for task in self._keepalives.values():
            task.cancel()
        for q in self._watches.values():
            q.put_nowait(_CONN_LOST)
        for q in self._subs.values():
            q.put_nowait(_CONN_LOST)
        logger.error(
            "hub connection lost: %d watches, %d subscriptions and "
            "%d leases orphaned",
            len(self._watches), len(self._subs), len(self._keepalives),
        )
        cb = self.on_connection_lost
        if cb is not None:
            with contextlib.suppress(Exception):
                res = cb()
                if asyncio.iscoroutine(res):
                    self._spawn_bg(res)

    async def _reconnect_loop(self) -> None:
        """Retry the connection with backoff; on success, re-establish
        server-side registrations (watches get their current prefix state
        replayed as synthetic puts -- level-triggered catch-up; deletes
        missed while down surface when the restored hub expires the dead
        owners' leases)."""
        self._reconnecting = True
        try:
            deadline = asyncio.get_running_loop().time() + self.reconnect_window
            delay = 0.2
            while not self._closing:
                try:
                    self._reader, self._writer = await asyncio.open_connection(
                        self.host, self.port
                    )
                except OSError:
                    if asyncio.get_running_loop().time() + delay > deadline:
                        self._fail_connection()
                        return
                    # full jitter (sleep U[0, delay]): a restarted hub sees
                    # its N clients' reconnects spread across the window
                    # instead of a thundering herd of synchronized dials
                    await asyncio.sleep(random.uniform(0.0, delay))
                    delay = min(delay * 2, 2.0)
                    continue
                self._pump = asyncio.create_task(self._pump_loop())
                try:
                    await self._reestablish()
                except Exception:
                    logger.exception("hub re-establish failed; retrying")
                    with contextlib.suppress(Exception):
                        self._writer.close()
                    if asyncio.get_running_loop().time() + delay > deadline:
                        self._fail_connection()
                        return
                    await asyncio.sleep(random.uniform(0.0, delay))
                    continue
                self._connected.set()
                logger.info(
                    "hub reconnected (%d watches, %d subscriptions resumed)",
                    len(self._watches), len(self._subs),
                )
                return
        finally:
            self._reconnecting = False

    async def _reestablish(self) -> None:
        """Re-register every watch and subscription on a fresh connection.

        Transactional against retries: the registration maps are swapped
        only after EVERY re-register RPC succeeded, so a connection that
        dies mid-reestablish leaves the old maps intact for the next
        attempt (nothing is popped-then-lost)."""
        new_watches: Dict[int, asyncio.Queue] = {}
        new_prefixes: Dict[int, str] = {}
        replays: list = []
        for old_wid, prefix in list(self._watch_prefixes.items()):
            q = self._watches[old_wid]
            hdr, blob = await self._call_raw({"op": "watch", "prefix": prefix})
            self._check(hdr)
            wid = int(hdr["watch_id"])
            new_watches[wid] = q
            new_prefixes[wid] = prefix
            replays.append((q, _split_entries(hdr["entries"], blob)))
        new_subs: Dict[int, asyncio.Queue] = {}
        new_patterns: Dict[int, str] = {}
        for old_sid, pattern in list(self._sub_patterns.items()):
            q = self._subs[old_sid]
            hdr, _ = await self._call_raw(
                {"op": "subscribe", "pattern": pattern}
            )
            self._check(hdr)
            sid = int(hdr["sub_id"])
            new_subs[sid] = q
            new_patterns[sid] = pattern
        # commit: swap maps, replay watch snapshots as puts, then drain any
        # events the pump parked in _early before the ids were mapped
        self._watches = new_watches
        self._watch_prefixes = new_prefixes
        self._subs = new_subs
        self._sub_patterns = new_patterns
        for q, entries in replays:
            for key, value in entries:
                q.put_nowait(WatchEvent("put", key, value))
        for wid, q in self._watches.items():
            for ev in self._early.pop(("w", wid), ()):
                q.put_nowait(ev)
        for sid, q in self._subs.items():
            for msg in self._early.pop(("s", sid), ()):
                q.put_nowait(msg)

    async def _call_raw(
        self, hdr: Dict[str, Any], payload: bytes = b""
    ) -> Tuple[Dict[str, Any], bytes]:
        assert self._writer is not None, "not connected"
        seq = next(self._seq)
        hdr["seq"] = seq
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[seq] = fut
        async with self._send_lock:
            write_frame(self._writer, hdr, payload)
            await self._writer.drain()
        return await fut

    async def _call(
        self, hdr: Dict[str, Any], payload: bytes = b""
    ) -> Tuple[Dict[str, Any], bytes]:
        # hub RPCs issued while a request span is open (disagg queue pushes,
        # discovery lookups on the request path) carry the trace context, so
        # control-plane time attributes to the right trace; disabled tracing
        # is one attribute check and leaves the frame untouched
        if tracing.collector.enabled:
            encode_trace_context(hdr, tracing.wire_context())
        if self._conn_lost:
            raise ConnectionError("hub connection lost")
        if not self._connected.is_set() and self.reconnect_window > 0:
            # a reconnect is in progress: park until it lands (or fails,
            # which sets _conn_lost and wakes us to raise)
            try:
                await asyncio.wait_for(
                    self._connected.wait(), self.reconnect_window + 5.0
                )
            except asyncio.TimeoutError:
                raise ConnectionError("hub reconnect timed out") from None
            if self._conn_lost:
                raise ConnectionError("hub connection lost")
        return await self._call_raw(hdr, payload)

    @staticmethod
    def _check(hdr: Dict[str, Any]) -> Dict[str, Any]:
        if not hdr.get("ok"):
            raise RuntimeError(hdr.get("err", "hub op failed"))
        return hdr

    # -- kv ---------------------------------------------------------------

    async def kv_put(self, key: str, value: bytes, lease: int = 0) -> None:
        hdr, _ = await self._call({"op": "kv_put", "key": key, "lease": lease}, value)
        self._check(hdr)

    async def kv_create(self, key: str, value: bytes, lease: int = 0) -> bool:
        hdr, _ = await self._call(
            {"op": "kv_create", "key": key, "lease": lease}, value
        )
        return bool(hdr.get("ok"))

    async def kv_get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        hdr, blob = await self._call({"op": "kv_get", "prefix": prefix})
        self._check(hdr)
        return _split_entries(hdr["entries"], blob)

    async def kv_delete(self, key: str) -> bool:
        hdr, _ = await self._call({"op": "kv_delete", "key": key})
        return bool(hdr.get("ok"))

    async def kv_delete_prefix(self, prefix: str) -> int:
        hdr, _ = await self._call({"op": "kv_delete_prefix", "prefix": prefix})
        self._check(hdr)
        return int(hdr.get("count", 0))

    # -- leases -----------------------------------------------------------

    async def lease_grant(self, ttl: float = 10.0, keepalive: bool = True) -> int:
        hdr, _ = await self._call({"op": "lease_grant", "ttl": ttl})
        self._check(hdr)
        lease = int(hdr["lease"])
        if keepalive:
            # a silently-dead keepalive means the hub evicts this client's
            # instances while the process believes it is healthy -- exactly
            # the failure CriticalTaskExecutionHandle exists for (reference
            # runtime/src/utils/task.rs:42): promote it to connection loss
            from ..utils import CriticalTaskExecutionHandle

            self._keepalives[lease] = CriticalTaskExecutionHandle(
                self._keepalive_loop(lease, ttl),
                on_failure=lambda e: self._signal_connection_lost(
                    f"lease {lease:#x} keepalive died: {e}"
                ),
                name=f"lease-keepalive-{lease:#x}",
            )
        return lease

    def _signal_connection_lost(self, reason: str) -> None:
        logger.error("%s", reason)
        cb = self.on_connection_lost
        if cb is not None:
            try:
                res = cb()
                if asyncio.iscoroutine(res):
                    self._spawn_bg(res)
            except Exception:
                logger.exception("on_connection_lost callback failed")

    async def _keepalive_loop(self, lease: int, ttl: float) -> None:
        interval = max(ttl / 3.0, 0.2)
        with contextlib.suppress(asyncio.CancelledError):
            while True:
                await asyncio.sleep(interval)
                try:
                    hdr, _ = await self._call(
                        {"op": "lease_keepalive", "lease": lease}
                    )
                except ConnectionError:
                    if self.reconnect_window > 0 and not self._conn_lost:
                        continue  # reconnect in progress; retry next beat
                    return
                if not hdr.get("ok"):
                    # the lease genuinely expired (e.g. an outage longer
                    # than TTL + reconnect): every key it held is gone --
                    # raising lets CriticalTaskExecutionHandle promote this
                    # to connection-lost so the process fails loudly
                    # instead of serving while invisible to discovery
                    raise RuntimeError(
                        f"lease {lease:#x} lost (keepalive rejected)"
                    )

    async def lease_revoke(self, lease: int) -> None:
        task = self._keepalives.pop(lease, None)
        if task:
            task.cancel()
        hdr, _ = await self._call({"op": "lease_revoke", "lease": lease})
        self._check(hdr)

    # -- watch ------------------------------------------------------------

    async def watch_prefix(self, prefix: str) -> WatchHandle:
        q: asyncio.Queue = asyncio.Queue()
        # Register the local queue under the id the server hands back; events
        # can only start flowing after the response, so there is no race.
        hdr, blob = await self._call({"op": "watch", "prefix": prefix})
        self._check(hdr)
        wid = int(hdr["watch_id"])
        self._watches[wid] = q
        self._watch_prefixes[wid] = prefix
        for ev in self._early.pop(("w", wid), ()):
            q.put_nowait(ev)
        snapshot = _split_entries(hdr["entries"], blob)

        async def close() -> None:
            # find the watch's CURRENT id: reconnects remap it
            cur = next(
                (w for w, qq in self._watches.items() if qq is q), None
            )
            if cur is not None:
                self._watches.pop(cur, None)
                self._watch_prefixes.pop(cur, None)
                with contextlib.suppress(Exception):
                    await self._call({"op": "unwatch", "watch_id": cur})

        return WatchHandle(snapshot=snapshot, events=q, watch_id=wid, _close=close)

    # -- pub/sub ----------------------------------------------------------

    async def publish(self, subject: str, payload: bytes) -> int:
        hdr, _ = await self._call({"op": "publish", "subject": subject}, payload)
        self._check(hdr)
        return int(hdr.get("receivers", 0))

    async def subscribe(self, pattern: str) -> Subscription:
        hdr, _ = await self._call({"op": "subscribe", "pattern": pattern})
        self._check(hdr)
        sid = int(hdr["sub_id"])
        q: asyncio.Queue = asyncio.Queue()
        self._subs[sid] = q
        self._sub_patterns[sid] = pattern
        for msg in self._early.pop(("s", sid), ()):
            q.put_nowait(msg)

        async def close() -> None:
            cur = next((s for s, qq in self._subs.items() if qq is q), None)
            if cur is not None:
                self._subs.pop(cur, None)
                self._sub_patterns.pop(cur, None)
                with contextlib.suppress(Exception):
                    await self._call({"op": "unsubscribe", "sub_id": cur})

        return Subscription(queue=q, sub_id=sid, _close=close)

    # -- queues -----------------------------------------------------------

    async def queue_push(self, queue: str, payload: bytes) -> None:
        hdr, _ = await self._call({"op": "queue_push", "queue": queue}, payload)
        self._check(hdr)

    async def queue_pop(
        self, queue: str, block: bool = True
    ) -> Optional[bytes]:
        hdr, payload = await self._call(
            {"op": "queue_pop", "queue": queue, "block": block}
        )
        self._check(hdr)
        return payload if hdr.get("found") else None

    async def queue_depth(self, queue: str) -> int:
        hdr, _ = await self._call({"op": "queue_depth", "queue": queue})
        self._check(hdr)
        return int(hdr["depth"])

    # -- objects ----------------------------------------------------------

    async def obj_put(self, name: str, blob: bytes) -> None:
        hdr, _ = await self._call({"op": "obj_put", "name": name}, blob)
        self._check(hdr)

    async def obj_get(self, name: str) -> Optional[bytes]:
        hdr, blob = await self._call({"op": "obj_get", "name": name})
        if not hdr.get("ok"):
            return None
        return blob

    async def obj_del(self, name: str) -> bool:
        hdr, _ = await self._call({"op": "obj_del", "name": name})
        return bool(hdr.get("found"))

    # -- KV blobs (the G4 remote tier's verbs) -----------------------------

    async def blob_put(self, name: str, blob: bytes) -> None:
        hdr, _ = await self._call({"op": "blob_put", "name": name}, blob)
        self._check(hdr)

    async def blob_get(self, name: str) -> Optional[bytes]:
        hdr, blob = await self._call({"op": "blob_get", "name": name})
        if not hdr.get("ok"):
            return None
        return blob

    async def blob_del(self, name: str) -> bool:
        hdr, _ = await self._call({"op": "blob_del", "name": name})
        return bool(hdr.get("found"))

    async def blob_stats(self) -> Dict[str, int]:
        hdr, _ = await self._call({"op": "blob_stats"})
        self._check(hdr)
        return {
            "blobs": int(hdr.get("blobs", 0)),
            "bytes": int(hdr.get("bytes", 0)),
        }


def _split_entries(
    metas: List[Dict[str, Any]], blob: bytes
) -> List[Tuple[str, bytes]]:
    out = []
    off = 0
    for m in metas:
        n = int(m["len"])
        out.append((m["key"], blob[off : off + n]))
        off += n
    return out


class StaticHub:
    """In-process hub: same surface as HubClient, zero sockets.

    Used for single-process serving ("static mode") and unit tests; also the
    lease semantics degenerate to no-ops (nothing can crash independently).
    """

    def __init__(self, state: Optional[HubState] = None) -> None:
        self.state = state or HubState()
        self._lease_seq = itertools.count(0x9000)

    async def connect(self) -> "StaticHub":
        return self

    async def close(self) -> None:
        pass

    async def kv_put(self, key: str, value: bytes, lease: int = 0) -> None:
        self.state.kv_put(key, value, 0)

    async def kv_create(self, key: str, value: bytes, lease: int = 0) -> bool:
        try:
            self.state.kv_create(key, value, 0)
            return True
        except FileExistsError:
            return False

    async def kv_get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        return [(e.key, e.value) for e in self.state.kv_get_prefix(prefix)]

    async def kv_delete(self, key: str) -> bool:
        return self.state.kv_delete(key)

    async def kv_delete_prefix(self, prefix: str) -> int:
        return self.state.kv_delete_prefix(prefix)

    async def lease_grant(self, ttl: float = 10.0, keepalive: bool = True) -> int:
        return next(self._lease_seq)

    async def lease_revoke(self, lease: int) -> None:
        pass

    async def watch_prefix(self, prefix: str) -> WatchHandle:
        q: asyncio.Queue = asyncio.Queue()
        wid = self.state.watch_add(prefix, q.put_nowait)
        snapshot = [(e.key, e.value) for e in self.state.kv_get_prefix(prefix)]

        async def close() -> None:
            self.state.watch_remove(wid)

        return WatchHandle(snapshot=snapshot, events=q, watch_id=wid, _close=close)

    async def publish(self, subject: str, payload: bytes) -> int:
        return self.state.publish(subject, payload)

    async def subscribe(self, pattern: str) -> Subscription:
        q: asyncio.Queue = asyncio.Queue()
        sid = self.state.subscribe(pattern, lambda s, p: q.put_nowait((s, p)))

        async def close() -> None:
            self.state.unsubscribe(sid)

        return Subscription(queue=q, sub_id=sid, _close=close)

    async def queue_push(self, queue: str, payload: bytes) -> None:
        self.state.queue_push(queue, payload)

    async def queue_pop(self, queue: str, block: bool = True) -> Optional[bytes]:
        item = self.state.queue_try_pop(queue)
        if item is not None or not block:
            return item
        fut = self.state.queue_wait(queue)
        return await fut

    async def queue_depth(self, queue: str) -> int:
        return self.state.queue_depth(queue)

    async def obj_put(self, name: str, blob: bytes) -> None:
        self.state.objects[name] = blob

    async def obj_get(self, name: str) -> Optional[bytes]:
        return self.state.objects.get(name)

    async def obj_del(self, name: str) -> bool:
        return self.state.objects.pop(name, None) is not None

    async def blob_put(self, name: str, blob: bytes) -> None:
        await self.state.blob_store.put(name, blob)

    async def blob_get(self, name: str) -> Optional[bytes]:
        return await self.state.blob_store.get(name)

    async def blob_del(self, name: str) -> bool:
        return await self.state.blob_store.delete(name)

    async def blob_stats(self) -> Dict[str, int]:
        return self.state.blob_store.stats()


class HubBlobClient:
    """Sync adapter from the offload plane's kv-remote thread onto an
    async hub client's blob verbs.

    The RemoteTier's store protocol is synchronous (it already owns a
    dedicated thread); a real deployment's store is the hub, whose
    client is loop-bound.  Each call here schedules the coroutine on the
    client's loop with ``run_coroutine_threadsafe`` and blocks the
    CALLING thread only -- the loop never waits.  Never call from the
    event loop itself (that would deadlock by definition); the thread
    sentry on the RemoteTier's entry points already enforces this."""

    def __init__(self, client: Any, loop: asyncio.AbstractEventLoop) -> None:
        self.client = client
        self.loop = loop

    def _run(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result()

    def put(self, name: str, data: bytes) -> None:
        self._run(self.client.blob_put(name, bytes(data)))

    def get(self, name: str) -> Optional[bytes]:
        return self._run(self.client.blob_get(name))

    def delete(self, name: str) -> bool:
        return self._run(self.client.blob_del(name))
