"""Wire framing for the control hub and the request/response plane.

Two-part frames: a small JSON header and an opaque binary payload, each
length-prefixed (u32 big-endian).  Reference parity: TwoPartCodec
(lib/runtime/src/pipeline/network/codec/two_part.rs) which frames
{RequestControlMessage, payload} the same way; re-used here for every plane
(hub RPC, request plane, response stream) instead of mixing NATS messages and
raw TCP.

Frame layout:  [u32 header_len][u32 payload_len][header JSON][payload bytes]
"""

from __future__ import annotations

import asyncio
import json
import struct
from typing import Any, Dict, Optional, Tuple

_LEN = struct.Struct(">II")

# 64 MiB hard cap per frame: a corrupt length prefix should fail fast, not OOM.
MAX_FRAME = 64 * 1024 * 1024


def encode_frame(header: Dict[str, Any], payload: bytes = b"") -> bytes:
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    if len(hdr) > MAX_FRAME or len(payload) > MAX_FRAME:
        raise ValueError("frame exceeds MAX_FRAME")
    return _LEN.pack(len(hdr), len(payload)) + hdr + payload


class TruncatedFrame(ConnectionError):
    """Connection died mid-frame: NOT a clean close."""


async def read_frame(
    reader: asyncio.StreamReader,
) -> Optional[Tuple[Dict[str, Any], bytes]]:
    """Read one frame.

    Returns None only on clean EOF at a frame boundary; a connection torn
    mid-frame raises :class:`TruncatedFrame` so callers can distinguish
    graceful shutdown from transport failure.
    """
    try:
        prefix = await reader.readexactly(_LEN.size)
    except ConnectionResetError:
        return None
    except asyncio.IncompleteReadError as exc:
        if exc.partial:
            raise TruncatedFrame("EOF inside frame length prefix") from exc
        return None
    hdr_len, payload_len = _LEN.unpack(prefix)
    if hdr_len > MAX_FRAME or payload_len > MAX_FRAME:
        raise ValueError(f"oversized frame: hdr={hdr_len} payload={payload_len}")
    try:
        hdr_bytes = await reader.readexactly(hdr_len)
        payload = await reader.readexactly(payload_len) if payload_len else b""
    except (asyncio.IncompleteReadError, ConnectionResetError) as exc:
        raise TruncatedFrame("EOF inside frame body") from exc
    return json.loads(hdr_bytes), payload


def write_frame(
    writer: asyncio.StreamWriter, header: Dict[str, Any], payload: bytes = b""
) -> None:
    """Write one frame.  ``payload`` may be any bytes-like (memoryview
    included): it is written as its own buffer, so multi-MB uploads aren't
    copied into a concatenated frame first."""
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    if len(hdr) > MAX_FRAME or len(payload) > MAX_FRAME:
        raise ValueError("frame exceeds MAX_FRAME")
    writer.write(_LEN.pack(len(hdr), len(payload)) + hdr)
    if len(payload):
        writer.write(payload)
