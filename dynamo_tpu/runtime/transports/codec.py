"""Wire framing for the control hub and the request/response plane.

Two-part frames: a small JSON header and an opaque binary payload, each
length-prefixed (u32 big-endian).  Reference parity: TwoPartCodec
(lib/runtime/src/pipeline/network/codec/two_part.rs) which frames
{RequestControlMessage, payload} the same way; re-used here for every plane
(hub RPC, request plane, response stream) instead of mixing NATS messages and
raw TCP.

Frame layout:  [u32 header_len][u32 payload_len][header JSON][payload bytes]
"""

from __future__ import annotations

import asyncio
import json
import struct
from typing import Any, Dict, List, Optional, Tuple

_LEN = struct.Struct(">II")

# Wire-format registry: every kind listed here must have BOTH an encoder
# (encode_*/write_* function) and a decoder (decode_*/read_* function) in
# this module -- dynalint DT006 enforces the pairing, so a new frame kind
# cannot ship half-implemented (an encoder the peer cannot parse, or a
# decoder nothing emits).  Add the kind here FIRST when growing the wire
# format; the lint failure then lists exactly what is missing.
FRAME_KINDS = ("frame", "chunk", "trace", "deadline")

# 64 MiB hard cap per frame: a corrupt length prefix should fail fast, not OOM.
MAX_FRAME = 64 * 1024 * 1024


def encode_frame(header: Dict[str, Any], payload: bytes = b"") -> bytes:
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    if len(hdr) > MAX_FRAME or len(payload) > MAX_FRAME:
        raise ValueError("frame exceeds MAX_FRAME")
    return _LEN.pack(len(hdr), len(payload)) + hdr + payload


class TruncatedFrame(ConnectionError):
    """Connection died mid-frame: NOT a clean close."""


async def read_frame(
    reader: asyncio.StreamReader,
) -> Optional[Tuple[Dict[str, Any], bytes]]:
    """Read one frame.

    Returns None only on clean EOF at a frame boundary; a connection torn
    mid-frame raises :class:`TruncatedFrame` so callers can distinguish
    graceful shutdown from transport failure.
    """
    try:
        prefix = await reader.readexactly(_LEN.size)
    except ConnectionResetError:
        return None
    except asyncio.IncompleteReadError as exc:
        if exc.partial:
            raise TruncatedFrame("EOF inside frame length prefix") from exc
        return None
    hdr_len, payload_len = _LEN.unpack(prefix)
    if hdr_len > MAX_FRAME or payload_len > MAX_FRAME:
        raise ValueError(f"oversized frame: hdr={hdr_len} payload={payload_len}")
    try:
        hdr_bytes = await reader.readexactly(hdr_len)
        payload = await reader.readexactly(payload_len) if payload_len else b""
    except (asyncio.IncompleteReadError, ConnectionResetError) as exc:
        raise TruncatedFrame("EOF inside frame body") from exc
    return json.loads(hdr_bytes), payload


# ---------------------------------------------------------------------------
# Trace-context header field (distributed tracing, runtime/tracing.py)
#
# The trace context -- which trace a request belongs to and which span is
# the parent of whatever the receiver opens -- rides every plane's JSON
# frame header under one reserved key.  It is optional: tracing disabled
# means the key is absent and frames are byte-identical to the untraced
# wire format (tests assert this).
# ---------------------------------------------------------------------------

TRACE_HDR_KEY = "trace"


def encode_trace_context(
    header: Dict[str, Any], wire_ctx: Optional[Dict[str, str]]
) -> Dict[str, Any]:
    """Stamp a trace context (``tracing.wire_context()`` output) into a
    frame header in place; a None context leaves the header untouched, so
    call sites need no tracing-enabled branch of their own."""
    if wire_ctx:
        header[TRACE_HDR_KEY] = wire_ctx
    return header


def decode_trace_context(header: Dict[str, Any]) -> Optional[Dict[str, str]]:
    """Inverse of :func:`encode_trace_context`: the raw wire dict
    (``{"tid": ..., "sid": ...}``) or None.  Validation/typing lives in
    ``tracing.TraceContext.from_wire`` -- the codec only carries bytes."""
    ctx = header.get(TRACE_HDR_KEY)
    return ctx if isinstance(ctx, dict) else None


# ---------------------------------------------------------------------------
# Deadline-budget header field (request recovery, runtime/engine.py)
#
# A request's remaining deadline budget rides every hop's JSON frame header
# next to the trace context, as *relative seconds remaining* -- wall clocks
# across hosts need not agree; each receiver re-anchors the budget on its
# own monotonic clock (``AsyncEngineContext.set_deadline``).  Time spent on
# the hop decrements the budget naturally.  Optional: requests without a
# deadline leave the header untouched (byte-identical wire format).
# ---------------------------------------------------------------------------

DEADLINE_HDR_KEY = "dl"


def encode_deadline_context(
    header: Dict[str, Any], remaining_s: Optional[float]
) -> Dict[str, Any]:
    """Stamp the remaining deadline budget (seconds) into a frame header in
    place; None leaves the header untouched, so call sites need no
    deadline-armed branch of their own."""
    if remaining_s is not None:
        header[DEADLINE_HDR_KEY] = round(float(remaining_s), 4)
    return header


def decode_deadline_context(header: Dict[str, Any]) -> Optional[float]:
    """Inverse of :func:`encode_deadline_context`: the remaining budget in
    seconds, or None.  Non-numeric junk decodes to None (a malformed
    header must not crash the read loop)."""
    v = header.get(DEADLINE_HDR_KEY)
    return float(v) if isinstance(v, (int, float)) else None


# ---------------------------------------------------------------------------
# Chunked binary messages (the disagg KV streaming wire format)
#
# A large binary payload split into N logical chunks rides the upload plane as
# a sequence of self-describing sub-frames, each tagged with its chunk index
# and absolute byte offset, so the receiver can (a) place bytes into a
# preallocated buffer as they land, (b) tolerate whole chunks arriving out of
# order (retried/parallel senders), and (c) reject truncated or overlapping
# streams instead of assembling garbage.  The chunk boundaries themselves are
# carried in the message header (layer spans for KV exports), not here.
# ---------------------------------------------------------------------------

CHUNK_MAGIC = 0x4B564331  # "KVC1"
_CHUNK_HDR = struct.Struct(">IIQ")  # magic, chunk index, absolute byte offset


def encode_chunk_frame(index: int, offset: int, payload) -> bytearray:
    """Frame one piece of chunk ``index`` starting at absolute ``offset``.
    ``payload`` is any bytes-like; the result is a single upload part.
    One payload copy total (pack_into + slice assign), not the two a
    bytes-concat would pay -- this sits on the bulk KV upload path."""
    out = bytearray(_CHUNK_HDR.size + len(payload))
    _CHUNK_HDR.pack_into(out, 0, CHUNK_MAGIC, index, offset)
    out[_CHUNK_HDR.size :] = payload
    return out


def iter_chunk_frames(index: int, base_offset: int, payload, chunk_bytes: int):
    """Split one chunk's payload into wire frames of at most
    ``chunk_bytes`` each, all tagged with the chunk's ``index`` and their
    absolute byte offset.  The single framing loop both KV emitters
    (disagg delivery, prefix-onboard export) share."""
    view = memoryview(payload)
    for off in range(0, len(view), chunk_bytes):
        yield encode_chunk_frame(
            index, base_offset + off, view[off : off + chunk_bytes]
        )


def decode_chunk_frame(frame) -> Tuple[int, int, memoryview]:
    """Inverse of :func:`encode_chunk_frame`; the payload view is zero-copy."""
    view = memoryview(frame)
    if len(view) < _CHUNK_HDR.size:
        raise ValueError("chunk frame shorter than its header")
    magic, index, offset = _CHUNK_HDR.unpack_from(view)
    if magic != CHUNK_MAGIC:
        raise ValueError(f"bad chunk magic {magic:#x}")
    return index, offset, view[_CHUNK_HDR.size :]


class ChunkAssembler:
    """Assemble chunk frames into a caller-provided buffer.

    ``bounds`` gives each chunk's [start, end) byte range in the full
    message; frames may arrive in any chunk order and a chunk may span
    several frames, but every frame must land entirely inside its chunk's
    range and never overlap previously received bytes.  ``add`` returns the
    indices of chunks the frame completed, so the consumer can act on each
    chunk (e.g. scatter a layer group) without waiting for the whole
    message; ``complete`` is the end-of-stream truncation check.
    """

    def __init__(self, buffer: memoryview, bounds: List[Tuple[int, int]]) -> None:
        total = len(buffer)
        if bounds and bounds[-1][1] != total:
            raise ValueError(
                f"chunk bounds end at {bounds[-1][1]}, buffer holds {total}"
            )
        self.buffer = buffer
        self.bounds = [(int(s), int(e)) for s, e in bounds]
        # per-chunk merged received intervals (few per chunk: senders emit
        # sequential sub-frames; out-of-order support is per whole chunk)
        self._got: List[List[Tuple[int, int]]] = [[] for _ in bounds]
        self.received_bytes = 0

    def _merge(self, idx: int, start: int, end: int) -> None:
        ivs = self._got[idx]
        for s, e in ivs:
            if start < e and s < end:
                raise ValueError(
                    f"chunk {idx}: bytes [{start},{end}) overlap [{s},{e})"
                )
        ivs.append((start, end))
        ivs.sort()
        merged = [ivs[0]]
        for s, e in ivs[1:]:
            ls, le = merged[-1]
            if s == le:
                merged[-1] = (ls, e)
            else:
                merged.append((s, e))
        self._got[idx] = merged

    def chunk_complete(self, idx: int) -> bool:
        start, end = self.bounds[idx]
        return start == end or self._got[idx] == [(start, end)]

    @property
    def complete(self) -> bool:
        return all(self.chunk_complete(i) for i in range(len(self.bounds)))

    def add(self, frame) -> List[int]:
        """Place one frame; returns chunk indices this frame completed."""
        idx, off, payload = decode_chunk_frame(frame)
        if not 0 <= idx < len(self.bounds):
            raise ValueError(f"chunk index {idx} out of range")
        start, end = self.bounds[idx]
        if off < start or off + len(payload) > end:
            raise ValueError(
                f"chunk {idx}: frame [{off},{off + len(payload)}) outside "
                f"its bounds [{start},{end})"
            )
        was_done = self.chunk_complete(idx)
        self._merge(idx, off, off + len(payload))
        self.buffer[off : off + len(payload)] = payload
        self.received_bytes += len(payload)
        if not was_done and self.chunk_complete(idx):
            return [idx]
        return []


def write_frame(
    writer: asyncio.StreamWriter, header: Dict[str, Any], payload: bytes = b""
) -> None:
    """Write one frame.  ``payload`` may be any bytes-like (memoryview
    included): it is written as its own buffer, so multi-MB uploads aren't
    copied into a concatenated frame first."""
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    if len(hdr) > MAX_FRAME or len(payload) > MAX_FRAME:
        raise ValueError("frame exceeds MAX_FRAME")
    writer.write(_LEN.pack(len(hdr), len(payload)) + hdr)
    if len(payload):
        writer.write(payload)
