"""Peer-to-peer streaming RPC: the request/response data plane.

The reference splits one logical RPC across two transports: the request rides
a NATS message to the worker's subject (addressed_router.rs:152) and the
response stream comes back over a *reverse* raw-TCP connection that the worker
dials into the caller (push_handler.rs:65, network/tcp/*).  That split exists
because NATS cannot stream.  With a first-party transport we use a single
duplex TCP connection per peer pair and multiplex many concurrent request
streams over it -- which preserves every property the split design bought
(streaming, per-request cancellation, backpressure, prologue errors) with one
fewer connection handshake on the hot path.

Frames (two-part codec, see codec.py):
  client -> server:  {t:"req",  sid, subject, id, meta}  + request payload
                     {t:"part", sid}  + chunk   -- upload continuation (up:true)
                     {t:"upend", sid}           -- upload complete
                     {t:"cancel", sid, kill}
  server -> client:  {t:"ack",  sid}            -- prologue: handler accepted
                     {t:"err",  sid, msg}       -- prologue or mid-stream error
                     {t:"data", sid}            + response item payload
                     {t:"end",  sid}            -- stream complete

``sid`` is a client-chosen stream id unique per connection.

Bulk uploads (the disagg KV delivery path): a ``req`` frame carrying
``up: true`` opens a client->server chunk stream for the request payload --
the frame's own payload is the first chunk, ``part`` frames append, ``upend``
closes.  The receiving handler must be registered raw (``register_raw``) and
consumes chunks as they arrive, so a multi-hundred-MB KV blockset never
materializes as one frame (frames cap at codec.MAX_FRAME) and the receive
side can overlap assembly with the sender's socket writes.  This replaces
the reference's NIXL one-sided RDMA leg (block_manager/storage/nixl.rs:173):
same role -- bulk KV moves peer-to-peer, off the control plane.
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
import logging
import os
from typing import Any, AsyncIterator, Awaitable, Callable, Dict, Optional, Tuple

from .. import faults
from ..engine import (
    DEADLINE_EXCEEDED_MSG,
    AsyncEngineContext,
    DeadlineExceededError,
    ensure_response_stream,
)
from .codec import (
    decode_deadline_context,
    encode_deadline_context,
    encode_trace_context,
    read_frame,
    write_frame,
)

logger = logging.getLogger("dynamo.dataplane")

# How long a stalled consumer may block its (bounded) stream queue before the
# stream is considered abandoned and dropped (env: DYN_ABANDONED_STREAM_S).
ABANDONED_STREAM_TIMEOUT = float(os.environ.get("DYN_ABANDONED_STREAM_S", "60"))

_DEADLINE_MSG = DEADLINE_EXCEEDED_MSG


def _count_abandoned(side: str) -> None:
    """abandoned_streams counter (lazy: transports must import without
    dragging prometheus in)."""
    from .. import metrics as rtm

    rtm.default_registry().counter(
        "dynamo_abandoned_streams",
        "Streams dropped by the request plane after a consumer stalled "
        "past the abandoned-stream timeout",
        ["side"],  # response (client pump) | upload (server chunk queue)
    ).labels(side).inc()

# A raw byte-level handler: receives (header, payload, ctx) and returns an
# async iterator of payload byte strings.  Serde lives one layer up (ingress).
ByteHandler = Callable[
    [Dict[str, Any], bytes, AsyncEngineContext], Awaitable[AsyncIterator[bytes]]
]

# A raw streaming handler: receives the request payload as an async iterator
# of chunks (one for plain requests, many for up:true uploads).
RawHandler = Callable[
    [Dict[str, Any], AsyncIterator[bytes], AsyncEngineContext],
    Awaitable[AsyncIterator[bytes]],
]

# Bound on buffered upload chunks per stream: past this the connection read
# loop stalls and TCP flow control pushes back on the sender.
UPLOAD_QUEUE_DEPTH = 8

_UPLOAD_END = None  # sentinel closing an upload queue


class StreamEnd(Exception):
    pass


class RemoteError(Exception):
    """Error raised by the remote handler, propagated through the stream."""


class WorkerLostError(RemoteError):
    """The stream died for transport-shaped reasons -- connection lost, or
    the worker no longer serves the subject (drain/restart).  Distinct from
    a handler error so failover can tell "the worker vanished" (retryable
    on another instance when nothing was delivered yet) from "the request
    itself failed" (never retryable)."""


class DataPlaneServer:
    """Worker-side listener: dispatches request frames to subject handlers.

    One server per process; endpoints register their subject here and their
    address in the hub's ``instances/`` keyspace (component/endpoint.py).
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 0) -> None:
        self.host = host
        self.port = port
        self.advertise_host: Optional[str] = None
        self._handlers: Dict[str, ByteHandler] = {}
        self._raw_handlers: Dict[str, RawHandler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_writers: set = set()

    def register(self, subject: str, handler: ByteHandler) -> None:
        self._handlers[subject] = handler

    def register_raw(self, subject: str, handler: RawHandler) -> None:
        """Register a streaming byte handler (upload-capable subjects)."""
        self._raw_handlers[subject] = handler

    def unregister(self, subject: str) -> None:
        self._handlers.pop(subject, None)
        self._raw_handlers.pop(subject, None)

    @property
    def address(self) -> Tuple[str, int]:
        host = self.advertise_host or (
            "127.0.0.1" if self.host in ("0.0.0.0", "::") else self.host
        )
        return host, self.port

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.address

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            # 3.12+ wait_closed() blocks until handlers return; unblock them.
            for w in list(self._conn_writers):
                with contextlib.suppress(Exception):
                    w.close()
            await self._server.wait_closed()

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._conn_writers.add(writer)
        send_lock = asyncio.Lock()
        live: Dict[int, AsyncEngineContext] = {}
        uploads: Dict[int, asyncio.Queue] = {}
        tasks: set = set()  # strong refs: loop holds only weak task refs

        async def send(hdr: Dict[str, Any], payload: bytes = b"") -> None:
            async with send_lock:
                try:
                    write_frame(writer, hdr, payload)
                    await writer.drain()
                except (ConnectionError, RuntimeError):
                    pass

        async def run_stream(
            sid: int,
            hdr: Dict[str, Any],
            payload: bytes,
            ctx: AsyncEngineContext,
            uq: Optional[asyncio.Queue],
        ) -> None:
            subject = hdr.get("subject", "")
            raw = self._raw_handlers.get(subject)
            handler = self._handlers.get(subject) if raw is None else None
            if raw is None and handler is None:
                live.pop(sid, None)
                uploads.pop(sid, None)
                # "retry" marks a transport-shaped failure: the worker is
                # not serving this subject (drained / restarting), so the
                # caller's failover may safely try another instance
                await send(
                    {"t": "err", "sid": sid, "retry": True,
                     "msg": f"no handler for subject {subject!r}"}
                )
                return
            if ctx.deadline_expired():
                # fast 504: the budget died in flight or on the queue --
                # answer immediately, never touch the engine
                live.pop(sid, None)
                uploads.pop(sid, None)
                await send(
                    {"t": "err", "sid": sid, "deadline": True,
                     "msg": _DEADLINE_MSG}
                )
                return
            try:
                if raw is not None:
                    # uq is captured at req time by the read loop: the upend
                    # frame may be processed (and the uploads entry popped)
                    # before this task first runs
                    async def chunk_iter() -> AsyncIterator[bytes]:
                        if uq is None:
                            yield payload
                            return
                        while True:
                            chunk = await uq.get()
                            if chunk is _UPLOAD_END:
                                return
                            yield chunk

                    stream = await raw(hdr, chunk_iter(), ctx)
                elif hdr.get("up"):
                    raise RuntimeError(
                        f"subject {subject!r} does not accept uploads"
                    )
                else:
                    stream = await handler(hdr, payload, ctx)
            except Exception as exc:  # noqa: BLE001 - prologue error to caller
                logger.exception("handler prologue failed for %s", subject)
                await send({"t": "err", "sid": sid, "msg": str(exc)})
                live.pop(sid, None)
                uploads.pop(sid, None)
                return
            await send({"t": "ack", "sid": sid})
            if faults.injector.enabled and faults.injector.should_fire(
                "engine.crash_before_first_token", subject
            ):
                # simulated worker death at the transport level, after the
                # engine accepted but before any item: the connection drops
                # with nothing delivered -- the failover-retryable window.
                # Kill the context so the engine side cleans up (pages
                # freed), as a real process death's connection loss would.
                ctx.kill()
                writer.close()
                return
            # Deadline watchdog: expiry kills the context, which wins the
            # ResponseStream race below even when the engine is blocked
            # mid-item; the stream then closes with a deadline error frame
            # (fast 504 at the frontend) and the kill propagates into the
            # engine's cancellation path, freeing the request's KV pages.
            wd = None
            rem = ctx.deadline_remaining()
            if rem is not None:
                wd = asyncio.get_running_loop().call_later(
                    max(rem, 0.0), ctx.kill
                )
            _F = faults.injector
            n_sent = 0
            try:
                # ResponseStream races the handler against kill, so a killed
                # request terminates even when the engine is blocked mid-item.
                async for item in ensure_response_stream(ctx, stream):
                    if ctx.is_killed():
                        break
                    if _F.enabled and _F.should_fire(
                        "req.stream_abort", subject
                    ):
                        await send(
                            {"t": "err", "sid": sid,
                             "msg": "injected stream abort"}
                        )
                        return
                    await send({"t": "data", "sid": sid}, item)
                    n_sent += 1
                    if n_sent == 1 and _F.enabled and _F.should_fire(
                        "engine.crash_after_first_token", subject
                    ):
                        ctx.kill()
                        writer.close()  # simulated worker death mid-stream
                        return
                if ctx.is_killed() and ctx.deadline_expired():
                    await send(
                        {"t": "err", "sid": sid, "deadline": True,
                         "msg": _DEADLINE_MSG}
                    )
                else:
                    await send({"t": "end", "sid": sid})
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - mid-stream error
                logger.exception("handler stream failed for %s", hdr.get("subject"))
                await send({"t": "err", "sid": sid, "msg": str(exc)})
            finally:
                if wd is not None:
                    wd.cancel()
                ctx.set_complete()
                live.pop(sid, None)
                uq_dead = uploads.pop(sid, None)
                if uq_dead is not None:
                    # the read loop may be parked in put() on this queue; a
                    # dead consumer must not head-of-line-block every other
                    # stream for ABANDONED_STREAM_TIMEOUT -- drain so the
                    # parked put completes immediately (later parts find the
                    # sid deregistered and are dropped)
                    while True:
                        try:
                            uq_dead.get_nowait()
                        except asyncio.QueueEmpty:
                            break

        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                hdr, payload = frame
                t = hdr.get("t")
                if t == "req":
                    sid = int(hdr["sid"])
                    # Register the context *before* yielding to the loop so a
                    # cancel frame already sitting in the TCP buffer can't
                    # race past the stream it targets.
                    ctx = AsyncEngineContext(hdr.get("id"))
                    rem = decode_deadline_context(hdr)
                    if rem is not None:
                        # re-anchor the caller's remaining budget on this
                        # host's monotonic clock (the hop's transit time has
                        # already decremented it)
                        ctx.set_deadline(rem)
                    live[sid] = ctx
                    uq = None
                    if hdr.get("up"):
                        uq = asyncio.Queue(maxsize=UPLOAD_QUEUE_DEPTH)
                        uploads[sid] = uq
                        if payload:
                            uq.put_nowait(payload)  # fresh queue: has room
                    task = asyncio.create_task(
                        run_stream(sid, hdr, payload, ctx, uq)
                    )
                    tasks.add(task)
                    task.add_done_callback(tasks.discard)
                elif t in ("part", "upend"):
                    usid0 = int(hdr["sid"])
                    uq = (
                        uploads.get(usid0) if t == "part"
                        else uploads.pop(usid0, None)
                    )
                    if uq is not None:
                        item = payload if t == "part" else _UPLOAD_END
                        # Bounded queue: a slow consumer stalls this read
                        # loop and TCP flow control reaches the uploader
                        # (accepted HOL cost, as on the response path).  A
                        # consumer stalled past the deadline is abandoned.
                        try:
                            await asyncio.wait_for(
                                uq.put(item), ABANDONED_STREAM_TIMEOUT
                            )
                        except asyncio.TimeoutError:
                            usid = int(hdr["sid"])
                            logger.warning(
                                "upload %s abandoned (consumer stalled "
                                "%.0fs); dropping", usid,
                                ABANDONED_STREAM_TIMEOUT,
                            )
                            _count_abandoned("upload")
                            uploads.pop(usid, None)
                            uctx = live.get(usid)
                            if uctx is not None:
                                uctx.kill()
                elif t == "cancel":
                    sid = int(hdr["sid"])
                    ctx = live.get(sid)
                    if ctx is not None:
                        if hdr.get("kill"):
                            ctx.kill()
                        else:
                            ctx.stop_generating()
                    # unblock a handler draining this stream's upload; make
                    # room first -- the sentinel must land even on a full
                    # queue or the handler blocks on get() forever
                    uq = uploads.pop(sid, None)
                    if uq is not None:
                        if uq.full():
                            with contextlib.suppress(asyncio.QueueEmpty):
                                uq.get_nowait()
                        with contextlib.suppress(asyncio.QueueFull):
                            uq.put_nowait(_UPLOAD_END)
        except ConnectionError as exc:
            logger.warning("data-plane connection failed mid-frame: %s", exc)
        finally:
            # Peer went away: kill all of its in-flight streams and unblock
            # handlers mid-upload (their chunk iterator must terminate).
            for ctx in list(live.values()):
                ctx.kill()
            for uq in list(uploads.values()):
                if uq.full():
                    with contextlib.suppress(asyncio.QueueEmpty):
                        uq.get_nowait()
                with contextlib.suppress(asyncio.QueueFull):
                    uq.put_nowait(_UPLOAD_END)
            uploads.clear()
            self._conn_writers.discard(writer)
            with contextlib.suppress(Exception):
                writer.close()


def _remote_error(hdr: Dict[str, Any]) -> Exception:
    """Typed exception for an err frame: deadline expiries and transport-
    shaped losses (conn drop, drained subject) get their own classes so the
    frontend can map them to 504 / failover without string matching."""
    msg = hdr.get("msg", "remote error")
    if hdr.get("deadline"):
        return DeadlineExceededError(msg)
    if hdr.get("lost") or hdr.get("retry"):
        return WorkerLostError(msg)
    return RemoteError(msg)


class _Connection:
    """One multiplexed client connection to a worker's data-plane server."""

    def __init__(self, host: str, port: int) -> None:
        self.host, self.port = host, port
        self._sid = itertools.count(1)
        self._streams: Dict[int, asyncio.Queue] = {}
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pump: Optional[asyncio.Task] = None
        self._send_lock = asyncio.Lock()
        self.closed = False

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )
        self._pump = asyncio.create_task(self._pump_loop())

    async def _pump_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                frame = await read_frame(self._reader)
                if frame is None:
                    break
                hdr, payload = frame
                sid = hdr.get("sid")
                q = self._streams.get(sid)
                if q is not None:
                    # Bounded queue: a stalled consumer stops the pump, TCP
                    # flow control kicks in, and backpressure reaches the
                    # producer (head-of-line blocking across the multiplexed
                    # connection is the accepted cost, as in HTTP/2 w/o
                    # per-stream flow control).  A consumer that stays stalled
                    # past the deadline is treated as abandoned: its stream is
                    # dropped and the server told to kill the request, so one
                    # dead consumer can't wedge the shared connection forever.
                    try:
                        await asyncio.wait_for(
                            q.put((hdr, payload)), ABANDONED_STREAM_TIMEOUT
                        )
                    except asyncio.TimeoutError:
                        logger.warning(
                            "stream %s abandoned (queue full %.0fs); dropping",
                            sid, ABANDONED_STREAM_TIMEOUT,
                        )
                        _count_abandoned("response")
                        self._streams.pop(sid, None)
                        with contextlib.suppress(ConnectionError):
                            await self.send(
                                {"t": "cancel", "sid": sid, "kill": True}
                            )
        except Exception as exc:  # noqa: BLE001
            logger.warning("data-plane connection %s:%d lost: %s",
                           self.host, self.port, exc)
        finally:
            self.closed = True
            for q in self._streams.values():
                # Make room if the bounded queue is full: the error must land.
                if q.full():
                    with contextlib.suppress(asyncio.QueueEmpty):
                        q.get_nowait()
                with contextlib.suppress(asyncio.QueueFull):
                    q.put_nowait(
                        ({"t": "err", "lost": True, "msg": "connection lost"},
                         b"")
                    )

    async def send(self, hdr: Dict[str, Any], payload: bytes = b"") -> None:
        assert self._writer is not None
        async with self._send_lock:
            write_frame(self._writer, hdr, payload)
            await self._writer.drain()

    async def close(self) -> None:
        self.closed = True
        if self._pump:
            self._pump.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._pump
        if self._writer:
            self._writer.close()
            with contextlib.suppress(Exception):
                await self._writer.wait_closed()

    async def request(
        self,
        subject: str,
        request_id: str,
        meta: Dict[str, Any],
        payload: bytes,
        ctx: AsyncEngineContext,
        trace: Optional[Dict[str, str]] = None,
        deadline: Optional[float] = None,
    ) -> AsyncIterator[bytes]:
        """Issue a request; await the prologue; yield response payloads.
        ``trace`` is an optional trace-context wire dict carried in the req
        frame header (absent = untraced, byte-identical wire format);
        ``deadline`` is the remaining budget in seconds, stamped next to
        it."""
        sid = next(self._sid)
        q: asyncio.Queue = asyncio.Queue(maxsize=512)
        self._streams[sid] = q
        await self.send(
            encode_deadline_context(
                encode_trace_context(
                    {"t": "req", "sid": sid, "subject": subject,
                     "id": request_id, "meta": meta},
                    trace,
                ),
                deadline,
            ),
            payload,
        )

        # Prologue: ack or err (reference: TCP prologue, network.rs:64-73).
        hdr, _ = await q.get()
        if hdr.get("t") == "err":
            self._streams.pop(sid, None)
            raise _remote_error(hdr)
        assert hdr.get("t") == "ack", f"bad prologue {hdr}"

        async def gen() -> AsyncIterator[bytes]:
            cancel_sent = [False]
            watcher = asyncio.create_task(
                self._cancel_watch(sid, ctx, cancel_sent)
            )
            ended = False
            try:
                while True:
                    hdr, payload = await q.get()
                    t = hdr.get("t")
                    if t == "data":
                        yield payload
                    elif t == "end":
                        ended = True
                        return
                    elif t == "err":
                        ended = True
                        raise _remote_error(hdr)
            finally:
                watcher.cancel()
                # The consumer may stop iterating (kill / early aclose) before
                # the watcher got scheduled: make sure the worker hears about
                # it, or it would keep generating into the void.
                if not ended and ctx.is_stopped() and not cancel_sent[0]:
                    cancel_sent[0] = True
                    with contextlib.suppress(ConnectionError, RuntimeError):
                        await self.send(
                            {"t": "cancel", "sid": sid, "kill": ctx.is_killed()}
                        )
                self._streams.pop(sid, None)

        return gen()

    async def _cancel_watch(
        self, sid: int, ctx: AsyncEngineContext, cancel_sent: list
    ) -> None:
        """Forward local stop/kill onto the wire as cancel frames."""
        with contextlib.suppress(asyncio.CancelledError, ConnectionError):
            await ctx.stopped()
            cancel_sent[0] = True
            await self.send(
                {"t": "cancel", "sid": sid, "kill": ctx.is_killed()}
            )

    async def request_upload(
        self,
        subject: str,
        request_id: str,
        meta: Dict[str, Any],
        chunks: Any,
        ctx: AsyncEngineContext,
        trace: Optional[Dict[str, str]] = None,
    ) -> AsyncIterator[bytes]:
        """Issue an upload-stream request: send every chunk, then read the
        response stream.  ``chunks`` is an iterable or async iterable of
        bytes-like objects, each < codec.MAX_FRAME.

        Chunks are sent eagerly (TCP flow control is the backpressure); the
        prologue is read only after ``upend``, so a handler that assembles
        the full payload before opening its response stream cannot deadlock
        against a client waiting for the ack.
        """
        sid = next(self._sid)
        q: asyncio.Queue = asyncio.Queue(maxsize=512)
        self._streams[sid] = q
        req_sent = False
        try:
            await self.send(
                encode_trace_context(
                    {"t": "req", "sid": sid, "subject": subject,
                     "id": request_id, "meta": meta, "up": True},
                    trace,
                )
            )
            req_sent = True
            if hasattr(chunks, "__aiter__"):
                async for chunk in chunks:
                    await self.send({"t": "part", "sid": sid}, chunk)
            else:
                for chunk in chunks:
                    await self.send({"t": "part", "sid": sid}, chunk)
            await self.send({"t": "upend", "sid": sid})
        except Exception:
            self._streams.pop(sid, None)
            if req_sent:
                # a chunk-source failure with a healthy connection (e.g. the
                # blob iterator raised) must not leave the server's raw
                # handler blocked on its chunk queue forever: kill the
                # half-sent stream so its byte-count check fails fast
                with contextlib.suppress(Exception):
                    await self.send({"t": "cancel", "sid": sid, "kill": True})
                    await self.send({"t": "upend", "sid": sid})
            raise

        # Prologue: ack or err (may arrive mid-upload; the queue holds it).
        hdr, _ = await q.get()
        if hdr.get("t") == "err":
            self._streams.pop(sid, None)
            raise _remote_error(hdr)
        assert hdr.get("t") == "ack", f"bad prologue {hdr}"

        async def gen() -> AsyncIterator[bytes]:
            cancel_sent = [False]
            watcher = asyncio.create_task(
                self._cancel_watch(sid, ctx, cancel_sent)
            )
            ended = False
            try:
                while True:
                    hdr, payload = await q.get()
                    t = hdr.get("t")
                    if t == "data":
                        yield payload
                    elif t == "end":
                        ended = True
                        return
                    elif t == "err":
                        ended = True
                        raise _remote_error(hdr)
            finally:
                watcher.cancel()
                if not ended and ctx.is_stopped() and not cancel_sent[0]:
                    cancel_sent[0] = True
                    with contextlib.suppress(ConnectionError, RuntimeError):
                        await self.send(
                            {"t": "cancel", "sid": sid, "kill": ctx.is_killed()}
                        )
                self._streams.pop(sid, None)
        return gen()


class DataPlaneClient:
    """Connection pool: one multiplexed connection per (host, port)."""

    def __init__(self) -> None:
        self._conns: Dict[Tuple[str, int], _Connection] = {}
        self._locks: Dict[Tuple[str, int], asyncio.Lock] = {}

    async def _get(self, host: str, port: int) -> _Connection:
        key = (host, port)
        lock = self._locks.setdefault(key, asyncio.Lock())
        async with lock:
            conn = self._conns.get(key)
            if conn is None or conn.closed:
                conn = _Connection(host, port)
                await conn.connect()
                self._conns[key] = conn
            return conn

    async def request(
        self,
        host: str,
        port: int,
        subject: str,
        request_id: str,
        meta: Dict[str, Any],
        payload: bytes,
        ctx: AsyncEngineContext,
        trace: Optional[Dict[str, str]] = None,
        deadline: Optional[float] = None,
    ) -> AsyncIterator[bytes]:
        conn = await self._get(host, port)
        return await conn.request(
            subject, request_id, meta, payload, ctx, trace=trace,
            deadline=deadline,
        )

    async def request_upload(
        self,
        host: str,
        port: int,
        subject: str,
        request_id: str,
        meta: Dict[str, Any],
        chunks: Any,
        ctx: AsyncEngineContext,
        trace: Optional[Dict[str, str]] = None,
    ) -> AsyncIterator[bytes]:
        conn = await self._get(host, port)
        return await conn.request_upload(
            subject, request_id, meta, chunks, ctx, trace=trace
        )

    async def close(self) -> None:
        for conn in self._conns.values():
            await conn.close()
        self._conns.clear()
