"""Transports: control hub (KV/leases/watch/pubsub/queues) + TCP data plane."""

from .client import HubClient, StaticHub, Subscription, WatchHandle
from .hub import HubServer, HubState, WatchEvent
from .request_plane import DataPlaneClient, DataPlaneServer, RemoteError

__all__ = [
    "DataPlaneClient",
    "DataPlaneServer",
    "HubClient",
    "HubServer",
    "HubState",
    "RemoteError",
    "StaticHub",
    "Subscription",
    "WatchEvent",
    "WatchHandle",
]
