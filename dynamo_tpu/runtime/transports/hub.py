"""The control hub: discovery KV + leases + prefix watches + pub/sub + queues
+ object store, as one embeddable asyncio service.

The reference splits its L1 infra across *external* services: etcd (leases,
prefix watches; lib/runtime/src/transports/etcd.rs), NATS core (request
subjects, events), NATS JetStream (prefill queue), and the NATS object store
(model cards) (lib/runtime/src/transports/nats.rs).  The TPU build ships its
control plane first-party instead: a single hub process (or in-process task)
speaking the two-part frame codec, providing the same primitives:

  * ``kv_*``        -- key-value with atomic create, prefix get/delete
  * ``lease_*``     -- TTL leases with keepalive; lease loss deletes its keys
                       (liveness = leases, exactly as in the reference)
  * ``watch``       -- prefix watch: initial dump + put/delete deltas
  * ``publish/subscribe`` -- subject-based events ("ns.events.kv_events", ...)
  * ``queue_*``     -- FIFO work queues with blocking pop (prefill queue)
  * ``obj_put/obj_get``   -- small-object store (model cards, tokenizer blobs)

Bulk data (response streams, KV pages) never transits the hub -- it flows
peer-to-peer over the TCP data plane (``request_plane.py``) or over ICI/DCN
(block manager transfer engine).

``StaticHub`` implements the same client interface fully in-process for
single-node / test use (reference "static mode": distributed.rs:85).
"""

from __future__ import annotations

import asyncio
import collections
import contextlib
import fnmatch
import hashlib
import itertools
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Tuple

from ..utils import log_throttled
from .codec import read_frame, write_frame

logger = logging.getLogger("dynamo.hub")

# ---------------------------------------------------------------------------
# Shared data model
# ---------------------------------------------------------------------------


@dataclass
class KvEntry:
    key: str
    value: bytes
    lease_id: int = 0
    revision: int = 0


@dataclass
class WatchEvent:
    """One delta on a watched prefix. type: 'put' | 'delete'."""

    type: str
    key: str
    value: bytes = b""


def _subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style matching: '.' separated tokens, '*' one token, '>' tail."""
    if pattern == subject:
        return True
    p_toks = pattern.split(".")
    s_toks = subject.split(".")
    for i, pt in enumerate(p_toks):
        if pt == ">":
            # NATS semantics: '>' matches one or more remaining tokens.
            return i < len(s_toks)
        if i >= len(s_toks):
            return False
        if pt != "*" and pt != s_toks[i]:
            return False
    return len(p_toks) == len(s_toks)


# ---------------------------------------------------------------------------
# Core state machine (shared by the TCP server and StaticHub)
# ---------------------------------------------------------------------------


class HubState:
    """The hub's data: pure in-memory state + waiter bookkeeping.

    All mutation happens on one event loop, so no locks are needed
    (the same single-writer discipline the reference applies to its radix
    tree and etcd caches).
    """

    def __init__(self) -> None:
        self.kv: Dict[str, KvEntry] = {}
        self.revision = 0
        self.leases: Dict[int, float] = {}  # lease_id -> expiry monotonic time
        self.lease_ttl: Dict[int, float] = {}
        self.lease_keys: Dict[int, set] = collections.defaultdict(set)
        self._lease_seq = itertools.count(0x1000)
        # durability hook: called with (record_dict, payload_bytes) after
        # every state mutation; None = in-memory only (StaticHub, tests).
        # The journal (HubJournal) makes a hub restart recoverable -- the
        # reference gets this property from etcd raft + NATS JetStream
        # persistence (transports/etcd.rs:41-58, nats.rs:50-123).
        self.journal: Optional[Callable[[Dict[str, Any], bytes], None]] = None
        # prefix -> list of callbacks(WatchEvent)
        self.watchers: Dict[int, Tuple[str, Callable[[WatchEvent], None]]] = {}
        self._watch_seq = itertools.count(1)
        # sub_id -> (pattern, callback(subject, payload))
        self.subs: Dict[int, Tuple[str, Callable[[str, bytes], None]]] = {}
        self._sub_seq = itertools.count(1)
        self.queues: Dict[str, collections.deque] = collections.defaultdict(
            collections.deque
        )
        self.queue_waiters: Dict[str, collections.deque] = collections.defaultdict(
            collections.deque
        )
        self.objects: Dict[str, bytes] = {}
        # the G4 KV-blob cache (blob_* verbs) -- unjournaled by design,
        # disk-backed when the owning server has a data_dir
        self.blob_store = HubBlobStore()
        # expiry-loop wakeup: called whenever a new lease deadline appears
        # (grant), so the owner's wait can re-aim at the earliest expiry
        # instead of polling on a fixed interval
        self.lease_wake: Optional[Callable[[], None]] = None

    # -- kv ---------------------------------------------------------------

    def _notify(self, ev: WatchEvent) -> None:
        for prefix, cb in list(self.watchers.values()):
            if ev.key.startswith(prefix):
                cb(ev)

    def kv_put(self, key: str, value: bytes, lease_id: int = 0) -> int:
        if lease_id and lease_id not in self.leases:
            raise KeyError(f"unknown lease {lease_id:#x}")
        self.revision += 1
        self.kv[key] = KvEntry(key, value, lease_id, self.revision)
        if lease_id:
            self.lease_keys[lease_id].add(key)
        if self.journal is not None:
            self.journal({"op": "kv_put", "key": key, "lease": lease_id}, value)
        self._notify(WatchEvent("put", key, value))
        return self.revision

    def kv_create(self, key: str, value: bytes, lease_id: int = 0) -> int:
        """Atomic create: fails if the key exists (etcd txn version==0)."""
        if key in self.kv:
            raise FileExistsError(key)
        return self.kv_put(key, value, lease_id)

    def kv_get_prefix(self, prefix: str) -> List[KvEntry]:
        return [e for k, e in sorted(self.kv.items()) if k.startswith(prefix)]

    def kv_delete(self, key: str) -> bool:
        entry = self.kv.pop(key, None)
        if entry is None:
            return False
        if entry.lease_id:
            self.lease_keys[entry.lease_id].discard(key)
        self.revision += 1
        if self.journal is not None:
            self.journal({"op": "kv_delete", "key": key}, b"")
        self._notify(WatchEvent("delete", key))
        return True

    def kv_delete_prefix(self, prefix: str) -> int:
        keys = [k for k in self.kv if k.startswith(prefix)]
        for k in keys:
            self.kv_delete(k)
        return len(keys)

    # -- leases -----------------------------------------------------------

    def lease_grant(self, ttl: float) -> int:
        lease_id = next(self._lease_seq)
        self.leases[lease_id] = time.monotonic() + ttl
        self.lease_ttl[lease_id] = ttl
        if self.journal is not None:
            self.journal({"op": "lease", "id": lease_id, "ttl": ttl}, b"")
        if self.lease_wake is not None:
            # a fresh grant can move the earliest deadline EARLIER; the
            # expiry loop re-aims.  Keepalives only push deadlines later,
            # so they never need a wake (the loop wakes at the stale
            # deadline, finds nothing expired, recomputes)
            self.lease_wake()
        return lease_id

    def next_lease_expiry(self) -> Optional[float]:
        """Earliest lease deadline (monotonic), None when no leases."""
        return min(self.leases.values()) if self.leases else None

    def lease_keepalive(self, lease_id: int) -> bool:
        # deliberately NOT journaled (high frequency): a restore re-arms
        # every lease with one fresh TTL of grace instead
        if lease_id not in self.leases:
            return False
        self.leases[lease_id] = time.monotonic() + self.lease_ttl[lease_id]
        return True

    def lease_revoke(self, lease_id: int) -> None:
        had = self.leases.pop(lease_id, None) is not None
        self.lease_ttl.pop(lease_id, None)
        if had and self.journal is not None:
            self.journal({"op": "lease_revoke", "id": lease_id}, b"")
        for key in list(self.lease_keys.pop(lease_id, ())):
            self.kv_delete(key)

    def expire_leases(self) -> None:
        now = time.monotonic()
        for lease_id, expiry in list(self.leases.items()):
            if expiry < now:
                logger.warning("lease %#x expired; dropping its keys", lease_id)
                self.lease_revoke(lease_id)

    # -- watch ------------------------------------------------------------

    def watch_add(self, prefix: str, cb: Callable[[WatchEvent], None]) -> int:
        wid = next(self._watch_seq)
        self.watchers[wid] = (prefix, cb)
        return wid

    def watch_remove(self, wid: int) -> None:
        self.watchers.pop(wid, None)

    # -- pub/sub ----------------------------------------------------------

    def subscribe(self, pattern: str, cb: Callable[[str, bytes], None]) -> int:
        sid = next(self._sub_seq)
        self.subs[sid] = (pattern, cb)
        return sid

    def unsubscribe(self, sid: int) -> None:
        self.subs.pop(sid, None)

    def publish(self, subject: str, payload: bytes) -> int:
        n = 0
        for pattern, cb in list(self.subs.values()):
            if _subject_matches(pattern, subject):
                cb(subject, payload)
                n += 1
        return n

    # -- queues -----------------------------------------------------------

    def queue_push(self, queue: str, payload: bytes) -> None:
        waiters = self.queue_waiters.get(queue)
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                # direct handoff to a blocked popper: the item never enters
                # stored state, so nothing is journaled -- an in-flight
                # delivery lost to a crash is the same at-most-once window
                # core NATS has (JetStream-grade redelivery is out of scope)
                fut.set_result(payload)
                return
        self.queues[queue].append(payload)
        if self.journal is not None:
            self.journal({"op": "qpush", "queue": queue}, payload)

    def queue_try_pop(self, queue: str) -> Optional[bytes]:
        q = self.queues.get(queue)
        if q:
            item = q.popleft()
            if self.journal is not None:
                self.journal({"op": "qpop", "queue": queue}, b"")
            return item
        return None

    def queue_depth(self, queue: str) -> int:
        return len(self.queues.get(queue, ()))

    def queue_wait(self, queue: str) -> asyncio.Future:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self.queue_waiters[queue].append(fut)
        return fut

    # -- objects ----------------------------------------------------------

    def obj_put(self, name: str, blob: bytes) -> None:
        self.objects[name] = blob
        if self.journal is not None:
            self.journal({"op": "obj_put", "name": name}, blob)

    def obj_del(self, name: str) -> bool:
        existed = self.objects.pop(name, None) is not None
        if existed and self.journal is not None:
            self.journal({"op": "obj_del", "name": name}, b"")
        return existed


class HubBlobStore:
    """The hub's G4 KV-blob store (the ``blob_put``/``blob_get``/
    ``blob_del``/``blob_stats`` verbs).

    Deliberately NOT journaled, unlike ``objects``: blobs are a fleet
    *cache* -- losing one costs a worker a recompute, never correctness
    -- so multi-MB KV frames stay out of the WAL and snapshots.  With a
    ``data_dir`` (a durable HubServer) each blob is one file under
    ``<data_dir>/blobs/`` behind an in-RAM name->size index, and every
    file op runs on the journal's single I/O worker (role ``hub-io``) --
    a slow disk stalls blob traffic, never the hub's event loop.
    Without one (StaticHub, tests) the same byte-capacity LRU runs over
    an in-RAM dict.  Capacity: ``DYN_HUB_BLOB_CAP`` bytes (default 1
    GiB)."""

    def __init__(self, cap_bytes: Optional[int] = None) -> None:
        if cap_bytes is None:
            cap_bytes = int(os.environ.get("DYN_HUB_BLOB_CAP", str(1 << 30)))
        self.cap_bytes = int(cap_bytes)
        # LRU order over resident blob names; value = blob nbytes
        self._index: "collections.OrderedDict[str, int]" = (
            collections.OrderedDict()
        )
        self._mem: Dict[str, bytes] = {}
        self._total = 0
        self._dir: Optional[str] = None
        self._io: Optional[Any] = None
        # the index is touched from the loop (StaticHub direct calls)
        # AND the hub-io worker (disk-backed ops): lock it
        self._lock = threading.Lock()

    def attach_disk(self, root: str, io: Any) -> None:
        """Back blobs with files under ``root``; ``io`` is the journal's
        single-thread executor (every file op rides it)."""
        os.makedirs(root, exist_ok=True)
        self._dir = root
        self._io = io

    def _path(self, name: str) -> str:
        # hashed filename: blob names carry '/' namespacing and arbitrary
        # worker-supplied bytes -- never let them pick filesystem paths
        digest = hashlib.sha256(name.encode("utf-8")).hexdigest()
        return os.path.join(self._dir, digest + ".blob")

    # -- RAM core (loop-safe: index + in-memory bytes, no file I/O) --------

    def _index_put(self, name: str, nbytes: int, data: Optional[bytes]) -> List[str]:
        """LRU-insert; returns evicted names (disk callers unlink them)."""
        evicted: List[str] = []
        with self._lock:
            old = self._index.pop(name, None)
            if old is not None:
                self._total -= old
            self._index[name] = nbytes
            self._total += nbytes
            if data is not None:
                self._mem[name] = data
            while self._total > self.cap_bytes and len(self._index) > 1:
                victim, vb = self._index.popitem(last=False)
                self._total -= vb
                self._mem.pop(victim, None)
                evicted.append(victim)
        return evicted

    def _mem_get(self, name: str) -> Optional[bytes]:
        with self._lock:
            if name not in self._index:
                return None
            self._index.move_to_end(name)
            return self._mem.get(name)

    def _index_del(self, name: str) -> bool:
        with self._lock:
            nbytes = self._index.pop(name, None)
            if nbytes is not None:
                self._total -= nbytes
            self._mem.pop(name, None)
        return nbytes is not None

    # -- disk core (hub-io worker only: every file op lives here) ----------

    def put_sync(self, name: str, data: bytes) -> None:
        from .. import thread_sentry

        thread_sentry.assert_role("hub-io", what="HubBlobStore.put")
        path = self._path(name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        for victim in self._index_put(name, len(data), None):
            with contextlib.suppress(OSError):
                os.remove(self._path(victim))

    def get_sync(self, name: str) -> Optional[bytes]:
        with self._lock:
            if name not in self._index:
                return None
            self._index.move_to_end(name)
        from .. import thread_sentry

        thread_sentry.assert_role("hub-io", what="HubBlobStore.get")
        try:
            with open(self._path(name), "rb") as f:
                return f.read()
        except OSError:
            self._index_del(name)
            return None

    def del_sync(self, name: str) -> bool:
        existed = self._index_del(name)
        if existed:
            with contextlib.suppress(OSError):
                os.remove(self._path(name))
        return existed

    # -- async surface (hub dispatch + StaticHub) --------------------------

    async def put(self, name: str, data: bytes) -> None:
        if self._io is not None:
            await asyncio.get_running_loop().run_in_executor(
                self._io, self.put_sync, name, data
            )
        else:
            self._index_put(name, len(data), bytes(data))

    async def get(self, name: str) -> Optional[bytes]:
        if self._io is not None:
            return await asyncio.get_running_loop().run_in_executor(
                self._io, self.get_sync, name
            )
        return self._mem_get(name)

    async def delete(self, name: str) -> bool:
        if self._io is not None:
            return await asyncio.get_running_loop().run_in_executor(
                self._io, self.del_sync, name
            )
        return self._index_del(name)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"blobs": len(self._index), "bytes": self._total}


# ---------------------------------------------------------------------------
# Durability: write-ahead journal + snapshot
# ---------------------------------------------------------------------------


class HubJournal:
    """Append-only journal + snapshot making a hub restart recoverable.

    The reference's control plane survives restarts because etcd is raft-
    replicated and the prefill queue / object store ride NATS JetStream
    (transports/etcd.rs:41-58, nats.rs:50-123).  The first-party hub gets
    the single-node equivalent: every mutation appends one framed record
    (json header + payload) to ``wal.bin``; past ``compact_every`` records
    the full state is rewritten as ``snapshot.bin`` (atomic rename) and the
    WAL truncates.  On start, snapshot then WAL replay rebuild the state.

    Leases are restored with ONE fresh TTL of grace: a surviving owner
    reconnects and keepalives within it (its keys never vanished); a dead
    owner's lease expires and drops its keys exactly as a live hub would
    have.  Keepalives themselves are not journaled (high frequency).

    Writes flush on every record; fsync only with ``DYN_HUB_FSYNC=1``
    (power-loss durability costs ~ms per mutation, process-crash
    durability is free).

    Every byte that touches disk -- WAL open, appends, rotation, snapshot
    write -- runs on ONE dedicated I/O worker thread (``_io``), never on
    the hub's event loop: a slow disk must stall the journal, not every
    connected worker's RPCs.  Submission order from the loop IS write
    order (single worker, FIFO queue), so the snapshot/rotation
    chronology the restore path depends on is preserved without locks.
    In the default (no-fsync) mode the durability point moves from "when
    the mutation returns" to "when the queued write lands" -- a few-ms
    ack-before-flush window; power-loss durability was never promised
    without fsync.  Under ``DYN_HUB_FSYNC=1`` the old contract stands:
    ``append`` BLOCKS until the record is fsynced, so a mutation is never
    acked before it is durable (that is the mode's entire point, and its
    documented ~ms/mutation price)."""

    REC_HDR = 8  # two u32 LE: header length, payload length

    def __init__(self, data_dir: str, compact_every: int = 8192) -> None:
        import concurrent.futures
        import os
        import struct

        self._struct = struct
        self.dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.snap_path = os.path.join(data_dir, "snapshot.bin")
        self.wal_path = os.path.join(data_dir, "wal.bin")
        # mid-compaction segment: records between the state capture and the
        # snapshot landing (restore replays snapshot -> wal.old -> wal)
        self.wal_old_path = os.path.join(data_dir, "wal.old.bin")
        self.compact_every = compact_every
        self.fsync = os.environ.get("DYN_HUB_FSYNC") == "1"
        self._wal = None  # owned by the _io worker after open
        self._pending = 0
        self._compacting = False
        self._io = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="hub-journal"
        )
        self._io_failed = False

    # -- record framing ----------------------------------------------------

    def _write_record(self, f, rec: Dict[str, Any], payload: bytes) -> None:
        import json

        hdr = json.dumps(rec, separators=(",", ":")).encode()
        f.write(self._struct.pack("<II", len(hdr), len(payload)))
        f.write(hdr)
        f.write(payload)

    def _read_records(self, path: str):
        import json
        import os

        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            while True:
                head = f.read(self.REC_HDR)
                if len(head) < self.REC_HDR:
                    break  # clean end or torn tail record: stop replay here
                hlen, plen = self._struct.unpack("<II", head)
                hdr = f.read(hlen)
                payload = f.read(plen)
                if len(hdr) < hlen or len(payload) < plen:
                    logger.warning("hub journal: torn record in %s", path)
                    break
                try:
                    yield json.loads(hdr), payload
                except ValueError:
                    logger.warning("hub journal: corrupt record in %s", path)
                    break

    # -- restore -----------------------------------------------------------

    def _old_segments(self) -> List[str]:
        """Rotated-out WAL segments awaiting a snapshot, in chronological
        (replay) order: ``wal.old.bin`` first, then numbered overflow
        segments from compactions that failed before their snapshot landed
        (each number was created while every lower one already existed)."""
        import os
        import re

        out: List[str] = []
        if os.path.exists(self.wal_old_path):
            out.append(self.wal_old_path)
        pat = re.compile(
            re.escape(os.path.basename(self.wal_old_path)) + r"\.(\d+)$"
        )
        try:
            names = os.listdir(self.dir)
        except OSError:
            names = []
        extras = []
        for name in names:
            m = pat.match(name)
            if m:
                extras.append((int(m.group(1)), os.path.join(self.dir, name)))
        out.extend(p for _, p in sorted(extras))
        return out

    def load_into(self, state: HubState) -> None:
        """Snapshot + WAL replay (journaling disabled while replaying)."""
        assert state.journal is None
        max_lease = 0
        for src in (self.snap_path, *self._old_segments(), self.wal_path):
            for rec, payload in self._read_records(src):
                op = rec.get("op")
                if op == "lease":
                    lid = int(rec["id"])
                    ttl = float(rec["ttl"])
                    state.leases[lid] = time.monotonic() + ttl  # grace
                    state.lease_ttl[lid] = ttl
                    max_lease = max(max_lease, lid)
                elif op == "lease_revoke":
                    state.lease_revoke(int(rec["id"]))
                elif op == "kv_put":
                    lid = int(rec.get("lease", 0))
                    if lid and lid not in state.leases:
                        continue  # lease already gone; key would be too
                    state.kv_put(rec["key"], payload, lid)
                elif op == "kv_delete":
                    state.kv_delete(rec["key"])
                elif op == "qpush":
                    state.queues[rec["queue"]].append(payload)
                elif op == "qpop":
                    q = state.queues.get(rec["queue"])
                    if q:
                        q.popleft()
                elif op == "obj_put":
                    state.objects[rec["name"]] = payload
                elif op == "obj_del":
                    state.objects.pop(rec["name"], None)
        # fresh lease ids must not collide with restored ones
        state._lease_seq = itertools.count(max(0x1000, max_lease + 1))

    # -- append + compaction -------------------------------------------------
    #
    # The caller-facing methods below (append, compact, close) are loop-safe:
    # they only capture state and enqueue work; the file ops they imply all
    # execute on the single _io worker in submission order.

    def open(self) -> None:
        """Open the WAL for append.  Runs on the _io worker in production
        (first queued append); callable directly when no appends are in
        flight (tests driving the journal synchronously)."""
        self._wal = open(self.wal_path, "ab")

    def append(self, state: HubState, rec: Dict[str, Any], payload: bytes) -> None:
        """Queue one record for the I/O worker; never touches disk itself.

        Called from the hub's mutation path (event loop).  ``rec`` is
        framed on the worker, so callers must hand over ownership (the hub
        builds a fresh dict per mutation); ``payload`` is immutable bytes.
        """
        try:
            fut = self._io.submit(self._do_append, rec, payload)
        except RuntimeError:  # closed journal (shutdown race): drop loudly
            log_throttled(
                logger, "hub-journal-closed",
                "hub journal closed; dropping a %s record", rec.get("op"),
            )
            return
        if self.fsync:
            # DYN_HUB_FSYNC promises acked == durable: wait for the fsync
            # (the mode's documented ~ms/mutation cost) instead of letting
            # the RPC reply race the disk
            fut.result()
        self._pending += 1
        if self._pending >= self.compact_every and not self._compacting:
            # capture on the caller (the loop): the dict copies of
            # immutable values are cheap and MUST see the state exactly as
            # of the last queued append.  Rotation + snapshot write queue
            # behind the already-submitted appends, so the rotated-out
            # segment holds precisely the records the capture covers.
            self._compacting = True
            self._pending = 0
            capture = self._capture(state)
            self._io.submit(self._do_compact, capture)

    def _do_append(self, rec: Dict[str, Any], payload: bytes) -> None:
        """Worker thread: frame, write, flush (fsync if configured)."""
        import os

        from .. import thread_sentry

        thread_sentry.assert_role("hub-io", what="HubJournal._do_append")
        try:
            if self._wal is None:
                self.open()
            self._write_record(self._wal, rec, payload)
            self._wal.flush()
            if self.fsync:
                os.fsync(self._wal.fileno())
        except Exception:
            # the hub keeps serving from memory; restart-durability of the
            # records since the last good write is lost and must be loud
            log_throttled(
                logger, "hub-journal-write",
                "hub journal write failed; recent mutations will not "
                "survive a restart", level=logging.ERROR, exc_info=True,
            )
            # re-raise into the future: in fsync mode append() awaits it,
            # so a failed write fails the mutation's RPC instead of acking
            # a record that never reached disk (acked == durable)
            raise

    def _do_compact(self, capture: Dict[str, Any]) -> None:
        """Worker thread: rotate then snapshot, error-isolated."""
        try:
            self._rotate_and_snapshot(capture)
        except Exception:
            logger.exception("hub snapshot compaction failed")
        finally:
            self._compacting = False

    def _rotate_and_snapshot(self, capture: Dict[str, Any]) -> None:
        segments = self._rotate_wal()
        self._write_snapshot(capture, segments)

    def _capture(self, state: HubState) -> Dict[str, Any]:
        """Shallow-copy the state for a consistent snapshot (values are
        immutable bytes; runs on the loop, O(entries) pointer copies)."""
        now = time.monotonic()
        return {
            "leases": [
                (lid, state.lease_ttl.get(lid, max(exp - now, 1.0)))
                for lid, exp in state.leases.items()
            ],
            "kv": [
                (key, e.lease_id, e.value)
                for key, e in sorted(state.kv.items())
            ],
            "queues": {q: list(items) for q, items in state.queues.items()},
            "objects": dict(state.objects),
        }

    def _rotate_wal(self) -> List[str]:
        """Swap in a fresh WAL; returns the rotated-out segments the
        pending snapshot covers.  Always a rename, never a byte copy: when
        a previous compaction failed before its snapshot landed (wal.old
        still holds the only copy of that segment), the current WAL rotates
        into the next NUMBERED segment instead of being merge-copied onto
        wal.old on the event loop -- restore replays snapshot -> old
        segments in order -> wal, so chronology is preserved for free."""
        import os

        if self._wal is not None:
            self._wal.close()
        dst = self.wal_old_path
        if os.path.exists(dst):
            n = 1
            while os.path.exists(f"{self.wal_old_path}.{n}"):
                n += 1
            dst = f"{self.wal_old_path}.{n}"
        with contextlib.suppress(FileNotFoundError):
            os.replace(self.wal_path, dst)
        self._wal = open(self.wal_path, "wb")
        return self._old_segments()

    def _write_snapshot(
        self, capture: Dict[str, Any], segments: List[str]
    ) -> None:
        """``segments`` MUST be the old-segment list captured at rotation
        time: re-listing at deletion time (this runs in a worker thread)
        could delete a segment a racing rotation created AFTER this
        snapshot's capture -- records the snapshot does not cover."""
        import os

        tmp = self.snap_path + ".tmp"
        with open(tmp, "wb") as f:
            for lid, ttl in capture["leases"]:
                self._write_record(f, {"op": "lease", "id": lid, "ttl": ttl}, b"")
            for key, lease_id, value in capture["kv"]:
                self._write_record(
                    f, {"op": "kv_put", "key": key, "lease": lease_id}, value
                )
            for queue, items in capture["queues"].items():
                for item in items:
                    self._write_record(f, {"op": "qpush", "queue": queue}, item)
            for name, blob in capture["objects"].items():
                self._write_record(f, {"op": "obj_put", "name": name}, blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snap_path)
        # the snapshot covers everything through the rotation point: the
        # rotated-out segments it was captured against are now redundant.
        # Delete NEWEST-first: wal.old anchors the numbered chain, so a
        # crash mid-cleanup must never leave a stale numbered segment
        # behind an already-removed wal.old (a later rotation would reuse
        # wal.old for newer records and restore would replay them BEFORE
        # the stale segment, inverting chronology)
        for path in reversed(segments):
            with contextlib.suppress(FileNotFoundError):
                os.remove(path)

    def compact(self, state: HubState) -> None:
        """Blocking compaction (tests / shutdown): capture now, then wait
        for the worker to rotate + write behind any queued appends.
        Exceptions propagate to the caller, unlike the background path."""
        capture = self._capture(state)
        self._pending = 0
        self._io.submit(self._rotate_and_snapshot, capture).result()

    def _close_wal(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None

    def close(self) -> None:
        """Drain every queued write, close the WAL, stop the worker."""
        with contextlib.suppress(RuntimeError):  # already closed
            self._io.submit(self._close_wal)
        self._io.shutdown(wait=True)


# ---------------------------------------------------------------------------
# TCP hub server
# ---------------------------------------------------------------------------


class HubServer:
    """Serves HubState over TCP with the two-part frame codec.

    Ops are request/response correlated by ``seq``; watches, subscriptions and
    blocking queue pops push server-initiated frames tagged with their id.
    Connection drop removes that connection's watches/subs and revokes leases
    it created (so a crashed worker disappears exactly like an expired etcd
    lease in the reference).
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        data_dir: Optional[str] = None,
    ) -> None:
        self.host = host
        self.port = port
        self.state = HubState()
        self.journal: Optional[HubJournal] = None
        if data_dir:
            self.journal = HubJournal(data_dir)
            self.journal.load_into(self.state)
            self.state.journal = lambda rec, payload: self.journal.append(
                self.state, rec, payload
            )
            # KV blobs persist as files (not WAL records), served off the
            # journal's single I/O worker
            self.state.blob_store.attach_disk(
                os.path.join(data_dir, "blobs"), self.journal._io
            )
        self._server: Optional[asyncio.AbstractServer] = None
        self._expiry_task: Optional[asyncio.Task] = None
        self._conn_writers: set = set()

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self._expiry_task = asyncio.create_task(self._expiry_loop())
        logger.info(
            "hub listening on %s:%d%s", self.host, self.port,
            f" (journal {self.journal.dir})" if self.journal else "",
        )
        return self.host, self.port

    async def serve_forever(self) -> None:
        """Start (if needed) and run until cancelled -- the standalone-hub
        entrypoint (``dynamo-tpu hub``, k8s hub Deployment)."""
        if self._server is None:
            await self.start()
        try:
            await asyncio.Event().wait()
        finally:
            await self.stop()

    async def stop(self) -> None:
        if self._expiry_task:
            self._expiry_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._expiry_task
        if self._server:
            self._server.close()
            # Force-close live connections: wait_closed() (3.12+) blocks until
            # every connection handler returns, and handlers read until EOF.
            for w in list(self._conn_writers):
                with contextlib.suppress(Exception):
                    w.close()
            await self._server.wait_closed()
        if self.journal is not None:
            # close() drains every queued write (and any in-flight
            # snapshot): that wait belongs on a thread, not on the loop a
            # colocated engine/HTTP frontend may still be serving from
            await asyncio.to_thread(self.journal.close)

    async def _expiry_loop(self) -> None:
        """Event-driven lease expiry: sleep until the EARLIEST lease
        deadline (not a fixed 2 Hz poll -- an idle hub makes zero wakeups),
        re-aimed whenever a grant introduces an earlier one.  Keepalives
        only extend deadlines, so waking at a stale deadline just finds
        nothing expired and recomputes."""
        wake = asyncio.Event()
        self.state.lease_wake = wake.set
        while True:
            self.state.expire_leases()
            # clear BEFORE reading the deadline: a grant landing between
            # the read and the wait sets the event and wakes us right back
            wake.clear()
            nxt = self.state.next_lease_expiry()
            timeout = (
                None if nxt is None else max(nxt - time.monotonic(), 0.0)
            )
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(wake.wait(), timeout)

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        st = self.state
        self._conn_writers.add(writer)
        conn_watches: list = []
        conn_subs: list = []
        conn_qwaiters: set = set()
        send_tasks: set = set()  # strong refs: loop holds only weak task refs
        send_lock = asyncio.Lock()

        async def send(hdr: Dict[str, Any], payload: bytes = b"") -> bool:
            async with send_lock:
                try:
                    write_frame(writer, hdr, payload)
                    await writer.drain()
                    return True
                except (ConnectionError, RuntimeError):
                    return False

        def send_soon(hdr: Dict[str, Any], payload: bytes = b"") -> None:
            task = asyncio.ensure_future(send(hdr, payload))
            send_tasks.add(task)
            task.add_done_callback(send_tasks.discard)

        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                hdr, payload = frame
                op = hdr.get("op")
                seq = hdr.get("seq")
                try:
                    if op == "kv_put":
                        rev = st.kv_put(hdr["key"], payload, hdr.get("lease", 0))
                        await send({"seq": seq, "ok": True, "rev": rev})
                    elif op == "kv_create":
                        try:
                            rev = st.kv_create(hdr["key"], payload, hdr.get("lease", 0))
                            await send({"seq": seq, "ok": True, "rev": rev})
                        except FileExistsError:
                            await send({"seq": seq, "ok": False, "err": "exists"})
                    elif op == "kv_get":
                        entries = st.kv_get_prefix(hdr["prefix"])
                        # values are base64-free: ship as concatenated frames
                        metas = [
                            {"key": e.key, "lease": e.lease_id, "rev": e.revision,
                             "len": len(e.value)}
                            for e in entries
                        ]
                        blob = b"".join(e.value for e in entries)
                        await send({"seq": seq, "ok": True, "entries": metas}, blob)
                    elif op == "kv_delete":
                        ok = st.kv_delete(hdr["key"])
                        await send({"seq": seq, "ok": ok})
                    elif op == "kv_delete_prefix":
                        n = st.kv_delete_prefix(hdr["prefix"])
                        await send({"seq": seq, "ok": True, "count": n})
                    elif op == "lease_grant":
                        lease = st.lease_grant(float(hdr["ttl"]))
                        await send({"seq": seq, "ok": True, "lease": lease})
                    elif op == "lease_keepalive":
                        ok = st.lease_keepalive(hdr["lease"])
                        await send({"seq": seq, "ok": ok})
                    elif op == "lease_revoke":
                        st.lease_revoke(hdr["lease"])
                        await send({"seq": seq, "ok": True})
                    elif op == "watch":
                        prefix = hdr["prefix"]

                        def on_event(ev: WatchEvent, _wid_holder=[None]) -> None:
                            send_soon(
                                {"watch": _wid_holder[0], "type": ev.type,
                                 "key": ev.key},
                                ev.value,
                            )

                        holder = on_event.__defaults__[0]
                        wid = st.watch_add(prefix, on_event)
                        holder[0] = wid
                        conn_watches.append(wid)
                        entries = st.kv_get_prefix(prefix)
                        metas = [
                            {"key": e.key, "len": len(e.value)} for e in entries
                        ]
                        blob = b"".join(e.value for e in entries)
                        await send(
                            {"seq": seq, "ok": True, "watch_id": wid,
                             "entries": metas},
                            blob,
                        )
                    elif op == "unwatch":
                        st.watch_remove(hdr["watch_id"])
                        await send({"seq": seq, "ok": True})
                    elif op == "subscribe":
                        pattern = hdr["pattern"]

                        def on_msg(subject: str, data: bytes, _sid_holder=[None]):
                            send_soon(
                                {"sub": _sid_holder[0], "subject": subject}, data
                            )

                        sholder = on_msg.__defaults__[0]
                        sid = st.subscribe(pattern, on_msg)
                        sholder[0] = sid
                        conn_subs.append(sid)
                        await send({"seq": seq, "ok": True, "sub_id": sid})
                    elif op == "unsubscribe":
                        st.unsubscribe(hdr["sub_id"])
                        await send({"seq": seq, "ok": True})
                    elif op == "publish":
                        n = st.publish(hdr["subject"], payload)
                        await send({"seq": seq, "ok": True, "receivers": n})
                    elif op == "queue_push":
                        st.queue_push(hdr["queue"], payload)
                        await send({"seq": seq, "ok": True})
                    elif op == "queue_pop":
                        item = st.queue_try_pop(hdr["queue"])
                        if item is not None:
                            await send({"seq": seq, "ok": True, "found": True}, item)
                        elif not hdr.get("block"):
                            await send({"seq": seq, "ok": True, "found": False})
                        else:
                            fut = st.queue_wait(hdr["queue"])
                            conn_qwaiters.add(fut)
                            qname = hdr["queue"]

                            async def deliver_job(
                                payload: bytes, _seq=seq, _q=qname
                            ) -> None:
                                ok = await send(
                                    {"seq": _seq, "ok": True, "found": True},
                                    payload,
                                )
                                if not ok:
                                    # Consumer died mid-delivery: requeue so
                                    # the job is not lost (at-least-once).
                                    st.queue_push(_q, payload)

                            def deliver(f: asyncio.Future) -> None:
                                conn_qwaiters.discard(f)
                                if not f.cancelled():
                                    task = asyncio.ensure_future(
                                        deliver_job(f.result())
                                    )
                                    send_tasks.add(task)
                                    task.add_done_callback(send_tasks.discard)

                            fut.add_done_callback(deliver)
                    elif op == "queue_depth":
                        await send(
                            {"seq": seq, "ok": True,
                             "depth": st.queue_depth(hdr["queue"])}
                        )
                    elif op == "obj_put":
                        st.obj_put(hdr["name"], payload)
                        await send({"seq": seq, "ok": True})
                    elif op == "obj_get":
                        blob = st.objects.get(hdr["name"])
                        if blob is None:
                            await send({"seq": seq, "ok": False, "err": "not found"})
                        else:
                            await send({"seq": seq, "ok": True}, blob)
                    elif op == "obj_del":
                        existed = st.obj_del(hdr["name"])
                        await send({"seq": seq, "ok": True, "found": existed})
                    elif op == "blob_put":
                        await st.blob_store.put(hdr["name"], payload)
                        await send({"seq": seq, "ok": True})
                    elif op == "blob_get":
                        blob = await st.blob_store.get(hdr["name"])
                        if blob is None:
                            await send(
                                {"seq": seq, "ok": False, "err": "not found"}
                            )
                        else:
                            await send({"seq": seq, "ok": True}, blob)
                    elif op == "blob_del":
                        existed = await st.blob_store.delete(hdr["name"])
                        await send({"seq": seq, "ok": True, "found": existed})
                    elif op == "blob_stats":
                        await send(
                            {"seq": seq, "ok": True, **st.blob_store.stats()}
                        )
                    elif op == "ping":
                        await send({"seq": seq, "ok": True})
                    else:
                        await send({"seq": seq, "ok": False, "err": f"bad op {op}"})
                except Exception as exc:  # noqa: BLE001 - report, keep serving
                    logger.exception("hub op %s failed", op)
                    await send({"seq": seq, "ok": False, "err": str(exc)})
        except ConnectionError as exc:
            logger.warning("hub connection failed mid-frame: %s", exc)
        finally:
            for wid in conn_watches:
                st.watch_remove(wid)
            for sid in conn_subs:
                st.unsubscribe(sid)
            # etcd semantics for conn loss: the lease is NOT revoked on a
            # dropped connection -- its keepalives simply stop, and it
            # expires after its TTL unless the owner reconnects (client
            # reconnect_window) and resumes them.  Instant revocation here
            # would make any transient disconnect erase a live worker's
            # registration behind its back (and, with a journal, persist
            # the erasure).  Crash detection latency is therefore <= TTL,
            # exactly as with reference etcd leases (transports/etcd.rs).
            # Graceful shutdown still revokes explicitly (lease_revoke op).
            # Cancel parked blocking pops so a future queue_push doesn't hand
            # a job to this dead connection (queue_push skips done futures).
            for fut in list(conn_qwaiters):
                if not fut.done():
                    fut.cancel()
            self._conn_writers.discard(writer)
            with contextlib.suppress(Exception):
                writer.close()
