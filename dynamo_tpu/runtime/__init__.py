"""Distributed runtime core (L2): engines, pipelines, components, transports."""

from .engine import (
    Annotated,
    AsyncEngine,
    AsyncEngineContext,
    Context,
    ResponseStream,
    as_response_stream,
)
from .pipeline import MapOperator, Operator, link
from .component import (
    Client,
    Component,
    DistributedRuntime,
    Endpoint,
    Instance,
    Namespace,
    PushRouter,
    RouterMode,
)

__all__ = [
    "Annotated",
    "AsyncEngine",
    "AsyncEngineContext",
    "Client",
    "Component",
    "Context",
    "DistributedRuntime",
    "Endpoint",
    "Instance",
    "MapOperator",
    "Namespace",
    "Operator",
    "PushRouter",
    "ResponseStream",
    "RouterMode",
    "as_response_stream",
    "link",
]
