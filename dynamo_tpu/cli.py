"""dynamo-tpu run: the single launch entrypoint.

Reference parity: launch/dynamo-run (opt.rs:23,83 ``in=http|text|dyn://…``
x ``out=echo|mocker|vllm|dyn``; flags.rs:26-137).  Usage::

    python -m dynamo_tpu run in=http out=jax --model-path /m/tinyllama
    python -m dynamo_tpu run in=http out=mocker --model-path /m/tok-only
    python -m dynamo_tpu run in=dyn  out=jax --model-path … --hub H:P
    python -m dynamo_tpu run in=http out=dyn --hub H:P          # frontend
    python -m dynamo_tpu run in=text out=jax --model-path …     # local REPL

``in=http out=<engine>`` is single-process aggregated serving (static mode,
no hub).  ``in=dyn`` serves the engine as a worker on the hub (registering
the model + KV/metrics publishers); ``in=http out=dyn`` runs the
discovery-driven frontend.  ``--hub auto`` spawns an in-process HubServer
(dev convenience).
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import logging
import os
import signal
import sys
from typing import Optional, Tuple

logger = logging.getLogger("dynamo.run")

from .protocols.endpoint import parse_endpoint_id  # noqa: E402 (re-export)


def _add_engine_flags(p) -> None:
    """Engine-construction flags consumed by ``_make_engine`` -- defined
    once, shared by every subcommand that builds a local engine (`run`,
    `profile-sla`), so the flag set and _make_engine's input contract
    cannot drift apart."""
    p.add_argument("--echo-delay-ms", type=float, default=0.0,
                   help="out=echo: per-token delay")
    p.add_argument("--model-path", help="HF model dir (weights + tokenizer)")
    p.add_argument("--model-name", help="served model name (default: dir name)")
    p.add_argument("--max-batch-size", type=int, default=8)
    p.add_argument("--max-seq-len", type=int, default=2048)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--num-pages", type=int, default=512)
    p.add_argument("--block-size", type=int, default=None,
                   help="router-visible KV block size (default: page size)")
    p.add_argument("--decode-block-size", type=int, default=16)
    p.add_argument("--quantize", choices=["int8"], default=None,
                   help="weight-only quantization (int8 + per-channel "
                        "scales; ~half the HBM stream per decode step)")
    p.add_argument("--kv-dtype", default=None, metavar="DTYPE",
                   help="paged KV pool dtype: 'int8' = quantized per-row "
                        "layout (~half the pool's HBM, dequant fused into "
                        "the ragged kernels), default = model dtype (env "
                        "DYN_KV_DTYPE overrides)")
    p.add_argument("--no-async-dispatch", dest="async_dispatch",
                   action="store_false", default=True,
                   help="disable the double-buffered host tick pipeline "
                        "(async commit + off-tick stream fanout); the "
                        "tick loop reverts to the exact serial "
                        "dispatch-then-commit order (env "
                        "DYN_ASYNC_DISPATCH overrides)")
    p.add_argument("--prefill-chunk-tokens", type=int, default=None,
                   help="chunked prefill: split long prompts into chunks "
                        "of this many tokens, interleaved with decode")
    p.add_argument("--no-mixed-batching", dest="mixed_batching",
                   action="store_false", default=True,
                   help="disable unified mixed prefill+decode dispatches "
                        "(ragged paged attention); prefill and decode "
                        "revert to separate launches per tick")
    p.add_argument("--mixed-token-budget", type=int, default=None,
                   help="fresh tokens per unified mixed-batch dispatch "
                        "(decode lanes cost one each, the rest packs "
                        "prefill chunks; env DYN_MIXED_TOKEN_BUDGET "
                        "overrides)")
    p.add_argument("--no-packed-ragged", dest="packed_ragged",
                   action="store_false", default=True,
                   help="disable the fully-packed ragged layout for "
                        "unified dispatches (revert to the lane rectangle "
                        "padded to the max chunk; env DYN_PACKED_RAGGED "
                        "overrides)")
    p.add_argument("--no-multistep-decode", dest="multistep_decode",
                   action="store_false", default=True,
                   help="disable multi-step device-resident decode (K "
                        "iterations fused into one packed dispatch on "
                        "pure-decode ticks, adaptive K); pure-decode "
                        "ticks revert to the classic fixed-width decode "
                        "block (env DYN_MULTISTEP overrides: 0=off, "
                        "adaptive, or a fixed integer K)")
    p.add_argument("--multistep-max-k", type=int, default=8,
                   metavar="K",
                   help="ceiling for the adaptive multi-step decode "
                        "controller (default 8)")
    p.add_argument("--no-fold-spec-verify", dest="fold_spec_verify",
                   action="store_false", default=True,
                   help="disable folded speculative verify (spec columns "
                        "riding the packed unified dispatch); verify "
                        "reverts to the standalone post-commit dispatch "
                        "(env DYN_SPEC_FOLD overrides)")
    p.add_argument("--no-spec-auto-disable", dest="spec_auto_disable",
                   action="store_false", default=True,
                   help="keep low-acceptance lanes drafting instead of "
                        "reverting them to plain decode (env "
                        "DYN_SPEC_AUTO_DISABLE overrides)")
    p.add_argument("--draft-model", default=None, metavar="PATH",
                   help="model-based drafter: checkpoint dir (or "
                        "'random[:seed]' test preset) loaded as a second "
                        "weight set, registered under drafter kind "
                        "'model' (env DYN_DRAFT_MODEL overrides)")
    p.add_argument("--kv-admit-budget", default=None, metavar="SPEC",
                   help="KV-budget admission: 'on' or "
                        "'util=0.9,headroom=256,reserve=16,floor_s=2,"
                        "skips=4' -- admit against predicted KV pages "
                        "with a skip-ahead fairness floor instead of "
                        "slot count (env DYN_KV_ADMIT_BUDGET overrides)")
    p.add_argument("--kv-prefetch-window", type=int, default=None,
                   help="queue-side prefetch window: offloaded prefix "
                        "chains of the first N queued requests stage "
                        "toward host RAM while they wait; 0 disables "
                        "(env DYN_KV_PREFETCH overrides)")
    p.add_argument("--host-offload-blocks", type=int, default=0,
                   help="G2 host-RAM KV offload capacity (blocks); 0 = off "
                        "(env DYN_KV_OFFLOAD arms/overrides the whole plane)")
    p.add_argument("--disk-offload-blocks", type=int, default=0,
                   help="G3 disk KV offload capacity (blocks); 0 = off")
    p.add_argument("--disk-offload-dir",
                   help="directory for G3 disk offload files")
    p.add_argument("--kv-remote", default=None, metavar="SPEC",
                   help="G4 fleet KV store tier: 'on', or "
                        "'mirror=1,fetch=1,prefill_tok_s=4000,gbps=1.0,"
                        "namespace=dynamo' (offload.parse_kv_remote_spec); "
                        "requires the offload plane armed and a hub; env "
                        "DYN_KV_REMOTE wins")
    p.add_argument("--no-swap-preemption", dest="swap_preemption",
                   action="store_false", default=True,
                   help="disable swap-based preemption (offload the "
                        "victim's KV and restore it on resume); preempted "
                        "sequences always recompute instead")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel degree (shards over local devices)")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel degree (decode batch sharded over dp)")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel degree (ring-attention prefill)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel degree (microbatched prefill)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel degree (MoE experts sharded)")
    # multi-host engine bootstrap (jax.distributed; env DYN_NUM_NODES /
    # DYN_NODE_RANK / DYN_LEADER_ADDR also work)
    p.add_argument("--num-nodes", type=int, default=None,
                   help="hosts in the engine's multi-host world")
    p.add_argument("--node-rank", type=int, default=None,
                   help="this host's rank (0 = leader)")
    p.add_argument("--leader-addr", default=None,
                   help="leader host:port for the jax.distributed "
                        "coordinator")


def _positive_int(v: str) -> int:
    n = int(v)
    if n < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {n}")
    return n


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dynamo-tpu",
        description="TPU-native distributed LLM serving (dynamo rebuild)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    run = sub.add_parser("run", help="launch an engine/frontend/worker")
    run.add_argument("io", nargs=2, metavar=("in=...", "out=..."),
                     help="in=http|text|dyn out=jax|mocker|echo|dyn")
    run.add_argument("--hub", help="hub address host:port, or 'auto'")
    run.add_argument("--endpoint", default="dyn://dynamo.backend.generate",
                     help="worker endpoint id (dyn://ns.comp.ep)")
    run.add_argument("--host", default="127.0.0.1")
    run.add_argument("--port", type=int, default=8080)
    run.add_argument("--router-mode", default="round_robin",
                     choices=["round_robin", "random", "kv"])
    run.add_argument("--router-index-shards", type=_positive_int, default=1,
                     help="KV router index shards (>1 = worker-sharded "
                          "index for large fleets)")
    _add_engine_flags(run)
    run.add_argument("--request-template",
                     help="JSON file with request defaults "
                          "{model, temperature, max_completion_tokens} "
                          "applied when the client omits them")
    run.add_argument("--prompt", help="in=text: run one prompt and exit")
    run.add_argument("--input-file", help="in=batch: JSONL prompts file")
    run.add_argument("--output-file", help="in=batch: JSONL results path "
                                           "(default stdout)")
    run.add_argument("--max-tokens", type=int, default=128)
    # disaggregated prefill/decode (in=dyn workers only)
    run.add_argument("--disagg", choices=["decode", "prefill"],
                     help="serve as a disaggregated decode or prefill worker")
    run.add_argument("--max-local-prefill-length", type=int, default=512)
    run.add_argument("--max-prefill-queue-depth", type=int, default=16)
    run.add_argument(
        "--kv-chunk-layers", type=int, default=None,
        help="layers per chunk for the streamed KV export (prefill "
             "workers; default splits the stack into ~8 groups)",
    )
    run.add_argument(
        "--no-chunked-kv", action="store_true",
        help="legacy monolithic KV export/upload (disables the pipelined "
             "chunked transfer path)",
    )

    # standalone hub (the control plane process; k8s hub Deployment)
    hub = sub.add_parser("hub", help="run a standalone hub server")
    hub.add_argument("--host", default="0.0.0.0")
    hub.add_argument("--port", type=int, default=6650)
    hub.add_argument("--data-dir", default=None,
                     help="persist state (WAL + snapshot) here; a restart "
                          "restores KV/leases/queues/objects")

    # standalone cluster metrics component (reference components/metrics)
    mt = sub.add_parser("metrics",
                        help="cluster Prometheus metrics on :9091")
    mt.add_argument("--hub", required=True, help="hub address host:port")
    mt.add_argument("--namespace", default="dynamo")
    mt.add_argument("--component", default="backend",
                    help="worker component to scrape")
    mt.add_argument("--host", default="0.0.0.0")
    mt.add_argument("--port", type=int, default=9091)

    # fleet: the observatory's read side over the hub -- subscribe to the
    # workers' telemetry topic, render a live cluster table
    fl = sub.add_parser("fleet",
                        help="live fleet table from worker telemetry")
    fl.add_argument("--hub", required=True, help="hub address host:port")
    fl.add_argument("--namespace", default="dynamo")
    fl.add_argument("--interval", type=float, default=2.0,
                    help="seconds between table refreshes")
    fl.add_argument("--once", action="store_true",
                    help="print one table after --interval and exit")
    fl.add_argument("--json", dest="json_out", action="store_true",
                    help="print the raw /fleet summary JSON instead")
    fl.add_argument("--plan", action="store_true",
                    help="show the planner's last adjustment + reason per "
                         "pool (note_adjustment / snapshot plan merge)")

    # trace: assemble one request's cross-component span timeline from the
    # hub (every served component auto-exposes a _trace scrape endpoint)
    tr = sub.add_parser("trace",
                        help="assemble a request's cross-component trace")
    tr.add_argument("--hub", required=True, help="hub address host:port")
    tr.add_argument("--namespace", default="dynamo")
    tr.add_argument("request_id", help="the request id (X-Request-Id header)")
    tr.add_argument("--json", dest="json_out",
                    help="write Chrome-trace JSON here (chrome://tracing / "
                         "ui.perfetto.dev)")
    tr.add_argument("--timeout", type=float, default=2.0,
                    help="per-component scrape timeout seconds")

    # llmctl: cluster model administration (reference llmctl/src/main.rs)
    ctl = sub.add_parser("llmctl", help="list/remove models on a hub")
    ctl.add_argument("--hub", required=True, help="hub address host:port")
    ctlsub = ctl.add_subparsers(dest="llmcmd", required=True)
    ctlsub.add_parser("list", help="list registered models + instances")
    rm = ctlsub.add_parser("remove", help="deregister a model by name")
    rm.add_argument("name")

    # api-store: deployment-artifact registry (reference deploy/cloud/
    # api-store -- FastAPI+Postgres+S3 there, the hub here)
    ap = sub.add_parser("api-store",
                        help="run the deployment-artifact registry")
    ap.add_argument("--hub", required=True, help="hub address host:port")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8282)

    # eval: perplexity of a checkpoint on real text (first-party accuracy
    # flow; the reference reaches this through its engines' lm-eval docs)
    ev = sub.add_parser("eval",
                        help="score a checkpoint's perplexity on a text")
    ev.add_argument("--model-path", required=True)
    ev.add_argument("--text-file", help="UTF-8 text to score")
    ev.add_argument("--text", help="inline text to score")
    ev.add_argument("--window", type=int, default=512,
                    help="independent scoring window (tokens)")
    ev.add_argument("--quantize", choices=["int8"], default=None)

    # operator: the reconcile controller over api-store deployment records
    # (reference deploy/cloud/operator controller loop)
    op = sub.add_parser("operator",
                        help="reconcile deployment records against the "
                             "cluster (controller loop)")
    op.add_argument("--hub", required=True, help="hub address host:port")
    op.add_argument("--kubectl", default="kubectl")
    op.add_argument("--namespace", default="default")
    op.add_argument("--interval", type=float, default=10.0)
    op.add_argument("--image", default="dynamo-tpu:latest")
    op.add_argument("--once", action="store_true",
                    help="run one reconcile round and exit")

    # build/deploy: graph packaging against the api-store (reference
    # `dynamo build` -> api-store upload, `dynamo deploy` -> manifests)
    bd = sub.add_parser("build",
                        help="package a graph dir and push it to api-store")
    bd.add_argument("--store", required=True,
                    help="api-store base url, e.g. http://H:8282")
    bd.add_argument("--name", required=True)
    bd.add_argument("--version", required=True)
    bd.add_argument("--path", required=True, help="graph directory to package")
    dp = sub.add_parser("deploy",
                        help="fetch a built graph and render its k8s manifests")
    dp.add_argument("--store", required=True)
    dp.add_argument("--name", required=True)
    dp.add_argument("--version", required=True)
    dp.add_argument("--out-dir", required=True,
                    help="where manifests + the unpacked artifact land")
    dp.add_argument("--model-path", default="/models/model",
                    help="model path the rendered workers mount")
    dp.add_argument("--image", default="dynamo-tpu:latest")

    # disagg-conf: live-reload the disagg routing policy (reference
    # disagg_router.rs:38-90 etcd watch); decode workers pick it up without
    # restarts
    dc = sub.add_parser("disagg-conf",
                        help="update the live disagg routing policy")
    dc.add_argument("--hub", required=True, help="hub address host:port")
    dc.add_argument("--namespace", default="dynamo")
    dc.add_argument("--max-local-prefill-length", type=int, default=None)
    dc.add_argument("--max-prefill-queue-depth", type=int, default=None)

    # datagen: workload analysis + synthesis (reference benchmarks/
    # data_generator `datagen analyze|synthesize`)
    dg = sub.add_parser("datagen", help="analyze/synthesize prefix workloads")
    dgsub = dg.add_subparsers(dest="dgcmd", required=True)
    an = dgsub.add_parser("analyze", help="prefix-sharing stats for a trace")
    an.add_argument("--input-file", required=True, help="JSONL trace")
    an.add_argument("--block-size", type=int, default=512)
    sy = dgsub.add_parser("synthesize", help="generate a synthetic trace")
    sy.add_argument("--input-file", required=True, help="JSONL seed trace")
    sy.add_argument("--output-file", required=True)
    sy.add_argument("--num-requests", type=int, default=1000)
    sy.add_argument("--block-size", type=int, default=512)
    sy.add_argument("--num-copies", type=int, default=1)
    sy.add_argument("--speedup-ratio", type=float, default=1.0)
    sy.add_argument("--prefix-len-multiplier", type=float, default=1.0,
                    help="scale shared-prefix lengths (any positive float; "
                         "<1 shrinks, like the reference synthesizer)")
    sy.add_argument("--prompt-len-multiplier", type=float, default=1.0)
    sy.add_argument("--seed", type=int, default=0)

    # profile-sla: pre-deployment TTFT/ITL profiling (reference
    # docs/architecture/planner.md profile_sla workflow)
    # profile: the tick-phase profiler's read side against a live frontend
    # (GET /profile/ticks; runtime/profiling.py) -- where does a serving
    # tick's wall time go, and how big is the dispatch gap?
    pf = sub.add_parser("profile",
                        help="tick-phase profile of a live serving frontend")
    pf.add_argument("url", help="frontend base url, e.g. "
                                "http://127.0.0.1:8080")
    pf.add_argument("--enable", action="store_true",
                    help="arm tick profiling on the server first "
                         "(POST /profile/ticks)")
    pf.add_argument("--disable", action="store_true",
                    help="disarm tick profiling on the server and exit")
    pf.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                    help="arm profiling, wait this long under live "
                         "traffic, then report (implies --enable)")
    pf.add_argument("--json", dest="json_out",
                    help="write the merged Chrome-trace JSON (tick phases "
                         "+ request spans) here")
    pf.add_argument("--device", type=float, default=None, metavar="SECONDS",
                    help="also capture a bounded jax.profiler device "
                         "trace (POST /profile/device)")

    ps = sub.add_parser("profile-sla",
                        help="measure TTFT/ITL per config, recommend SLO point")
    ps.add_argument("--out", default="jax", choices=["jax", "mocker", "echo"],
                    help="engine to profile")
    ps.add_argument("--isl", default="128,512",
                    help="comma-separated prefill lengths to probe")
    ps.add_argument("--batch", default="1,4,8",
                    help="comma-separated decode batch sizes to probe")
    ps.add_argument("--osl", type=int, default=64,
                    help="decode tokens per probe stream (span several "
                         "decode blocks or ITL reads near zero)")
    ps.add_argument("--ttft-slo-ms", type=float, default=None)
    ps.add_argument("--itl-slo-ms", type=float, default=None)
    _add_engine_flags(ps)

    # bench: serving benchmark against a running OpenAI frontend (the
    # north-star measurement: output tok/s + TTFT percentiles on a
    # ShareGPT-like workload -- BASELINE.md)
    bn = sub.add_parser("bench",
                        help="drive a frontend with a workload; report "
                             "tok/s + TTFT percentiles")
    bn.add_argument("--host", default="127.0.0.1")
    bn.add_argument("--port", type=int, required=True)
    bn.add_argument("--model", required=True)
    bn.add_argument("--num-requests", type=int, default=None,
                    help="synthetic: workload size (default 64); trace: "
                         "cap on records replayed (default: whole trace)")
    bn.add_argument("--isl", type=int, default=128)
    bn.add_argument("--osl", type=int, default=64)
    bn.add_argument("--request-rate", type=float, default=0.0,
                    help="Poisson arrival rate (req/s); 0 = all at once")
    bn.add_argument("--concurrency", type=int, default=64)
    bn.add_argument("--vocab-size", type=int, default=29000)
    bn.add_argument("--trace", help="datagen JSONL trace to replay instead "
                                    "of the synthetic workload")
    bn.add_argument("--trace-block-size", type=int, default=16,
                    help="tokens per trace hash id (fallback only: the "
                         "trace's input_length fields take precedence)")
    bn.add_argument("--speedup-ratio", type=float, default=1.0,
                    help="trace replay time compression")
    bn.add_argument("--seed", type=int, default=0)
    bn.add_argument("--fleet", action="store_true",
                    help="also fetch GET /fleet from the frontend and "
                         "attach the cluster summary to the report")
    return p


def _load_template(args):
    """--request-template JSON -> RequestTemplate (reference
    request_template.rs:18), or None."""
    if not getattr(args, "request_template", None):
        return None
    from .protocols.openai import RequestTemplate

    return RequestTemplate.load(args.request_template)


def _parse_io(io) -> Tuple[str, str]:
    try:
        kv = dict(part.split("=", 1) for part in io)
    except ValueError:
        kv = {}
    if "in" not in kv or "out" not in kv:
        raise SystemExit("usage: run in=<http|text|dyn> out=<jax|mocker|dyn>")
    return kv["in"], kv["out"]


async def _make_engine(args):
    """Build the local engine for out=jax|mocker|echo."""
    if args.out == "echo":
        from .llm.echo import EchoEngineCore

        return EchoEngineCore(delay_ms=args.echo_delay_ms)
    if args.out == "mocker":
        from .mocker import MockerConfig, MockerEngine

        block = args.block_size or args.page_size
        vocab = 32000
        if args.model_path:
            # emit ids the model's tokenizer can actually detokenize
            vocab = _tokenizer_for(args).vocab_size
        return MockerEngine(MockerConfig(block_size=block, vocab_size=vocab))
    from .engine import EngineConfig, JaxEngine

    if not args.model_path:
        raise SystemExit("out=jax requires --model-path")
    from .llm.local_model import resolve_model_path

    # local dir used as-is; an org/repo id resolves through the HF hub
    # (reference local_model.rs:27 + hub.rs)
    args.model_path = resolve_model_path(args.model_path)
    cfg = EngineConfig(
        max_batch_size=args.max_batch_size,
        max_seq_len=args.max_seq_len,
        page_size=args.page_size,
        num_pages=args.num_pages,
        block_size=args.block_size,
        decode_block_size=args.decode_block_size,
        prefill_chunk_tokens=args.prefill_chunk_tokens,
        mixed_batching=args.mixed_batching,
        host_offload_blocks=args.host_offload_blocks,
        disk_offload_blocks=args.disk_offload_blocks,
        disk_offload_dir=args.disk_offload_dir,
        swap_preemption=args.swap_preemption,
        kv_remote=args.kv_remote,
        packed_ragged=args.packed_ragged,
        kv_admit_budget=args.kv_admit_budget,
        quantize=args.quantize,
        kv_dtype=args.kv_dtype,
        async_dispatch=args.async_dispatch,
        fold_spec_verify=args.fold_spec_verify,
        spec_auto_disable=args.spec_auto_disable,
        draft_model=args.draft_model,
        multistep_decode=args.multistep_decode,
        multistep_max_k=args.multistep_max_k,
    )
    if args.mixed_token_budget is not None:
        cfg.mixed_token_budget = args.mixed_token_budget
    if args.kv_prefetch_window is not None:
        cfg.kv_prefetch_window = args.kv_prefetch_window
    logger.info("loading %s ...", args.model_path)
    from .parallel.multihost import MultiNodeConfig, initialize_multihost

    mn = MultiNodeConfig.from_env()
    if args.num_nodes is not None:
        mn.num_nodes = args.num_nodes
    if args.node_rank is not None:
        mn.node_rank = args.node_rank
    if args.leader_addr is not None:
        mn.leader_addr = args.leader_addr
    initialize_multihost(mn)  # must precede the first jax backend touch
    # DYN_TP / DYN_DP env overrides (the engine-startup knob, mirrors
    # DYN_KV_OFFLOAD): a set variable wins over the flag, so a deployment
    # can re-degree a worker without editing its launch line.  sp/pp/ep
    # stay flag-only -- they select step routes, not just shardings.
    from .parallel.mesh import env_parallel_spec

    env = env_parallel_spec()
    if env["tp"] is not None:
        args.tp = env["tp"]
    if env["dp"] is not None:
        args.dp = env["dp"]
    mesh_cfg = None
    if max(args.tp, args.dp, args.sp, args.pp, args.ep) > 1:
        from .parallel.mesh import MeshConfig

        mesh_cfg = MeshConfig(
            dp=args.dp, tp=args.tp, pp=args.pp, sp=args.sp, ep=args.ep
        )
    if mesh_cfg is not None:
        import jax

        from .engine.config import ModelConfig
        from .parallel.mesh import build_mesh

        devices = jax.devices()
        if len(devices) < mesh_cfg.num_devices:
            raise SystemExit(
                f"mesh dp={args.dp} tp={args.tp} pp={args.pp} sp={args.sp} "
                f"ep={args.ep} needs {mesh_cfg.num_devices} devices, have "
                f"{len(devices)}"
            )
        if args.dp > 1 and args.max_batch_size % args.dp:
            raise SystemExit(
                f"--max-batch-size {args.max_batch_size} must be divisible "
                f"by --dp {args.dp} (batch lanes shard over dp)"
            )
        model_cfg = None
        if args.tp > 1:
            # fail before any weight loads: a tp that cannot shard the kv
            # heads would silently replicate the KV pool and pay a
            # cross-chip gather per decode step
            model_cfg = ModelConfig.from_pretrained(args.model_path)
            try:
                model_cfg.validate_tp(args.tp)
            except ValueError as e:
                raise SystemExit(str(e))
        mesh = build_mesh(mesh_cfg, devices[: mesh_cfg.num_devices])
        return JaxEngine.from_pretrained(
            args.model_path, cfg, mesh=mesh, model_cfg=model_cfg
        )
    return JaxEngine.from_pretrained(args.model_path, cfg)


def _tokenizer_for(args):
    from .llm.tokenizer import Tokenizer

    if not args.model_path:
        raise SystemExit("this mode needs --model-path for the tokenizer")
    from .llm.local_model import resolve_model_path

    args.model_path = resolve_model_path(args.model_path)
    return Tokenizer.from_model_dir(args.model_path)


def _model_name(args) -> str:
    import os

    if args.model_name:
        return args.model_name
    if args.model_path:
        return os.path.basename(os.path.normpath(args.model_path))
    return "mocker"


async def _resolve_hub(args):
    """Returns (hub_address, owned_hub_server|None); spawns one for 'auto'."""
    if args.hub == "auto":
        from .runtime.transports.hub import HubServer

        server = HubServer()
        host, port = await server.start()
        logger.info("spawned in-process hub at %s:%d", host, port)
        return f"{host}:{port}", server
    return args.hub, None


async def run_http_local(args) -> None:
    """in=http out=jax|mocker: single-process aggregated serving."""
    from .http.service import HttpService, ModelManager
    from .llm.backend import Backend
    from .llm.preprocessor import OpenAIPreprocessor
    from .runtime.pipeline import link

    engine = await _make_engine(args)
    tokenizer = _tokenizer_for(args)
    name = _model_name(args)
    pipeline = link(OpenAIPreprocessor(name, tokenizer), Backend(tokenizer), engine)
    manager = ModelManager()
    manager.add_chat_model(name, pipeline)
    manager.add_completion_model(name, pipeline)
    from .llm.embedding import EmbeddingEngine, fake_embedder

    # /v1/embeddings: the JAX trunk embeds for real; echo/mocker get the
    # deterministic fake so the route works in every out= mode
    embed_fn = engine.embed if hasattr(engine, "embed") else fake_embedder()
    max_in = getattr(getattr(engine, "cfg", None), "max_seq_len", None)
    manager.add_embedding_model(
        name,
        EmbeddingEngine(embed_fn, tokenizer=tokenizer, max_input_tokens=max_in),
    )
    service = HttpService(
        manager, host=args.host, port=args.port,
        template=_load_template(args),
    )
    await service.start()
    print(f"serving {name} at {service.url}  (POST /v1/chat/completions)")
    try:
        await _wait_forever()
    finally:
        await service.stop()
        await engine.stop()


async def run_http_frontend(args) -> None:
    """in=http out=dyn: discovery-driven frontend over the hub."""
    if not args.hub:
        raise SystemExit("in=http out=dyn requires --hub")
    from .http.service import HttpService, ModelManager
    from .llm.discovery import ModelWatcher
    from .runtime.component import DistributedRuntime, RouterMode

    addr, owned_hub = await _resolve_hub(args)
    runtime = await DistributedRuntime.detached(addr)
    manager = ModelManager()
    # fleet observatory: ingest every worker's telemetry snapshots off the
    # hub and surface them at GET /fleet (+ the dynamo_fleet_* families).
    # Built before the router factory: the KV router's quarantine filter
    # and fetch-vs-recompute gate read its live link/straggler state.
    from .fleet import FleetObservatory

    observatory = FleetObservatory()
    if args.router_mode == "kv":
        from .llm.backend import Backend
        from .llm.kv_router.router import KvPushRouter, KvRouter
        from .llm.preprocessor import OpenAIPreprocessor
        from .offload import env_remote_spec
        from .runtime.pipeline import link

        try:
            remote_spec = env_remote_spec()
        except ValueError:
            logger.warning("ignoring malformed DYN_KV_REMOTE")
            remote_spec = None

        async def kv_factory(entry, card, client, router):
            ns = runtime.namespace(entry.namespace)
            comp = ns.component(entry.component)
            chooser = KvRouter(
                ns, comp, block_size=card.kv_block_size,
                index_shards=args.router_index_shards,
                quarantine=observatory.quarantine_source(),
            )
            await chooser.start()
            tokenizer = card.tokenizer()
            engine = link(
                OpenAIPreprocessor(entry.name, tokenizer),
                Backend(tokenizer),
                KvPushRouter(
                    router, chooser,
                    transfer_ms=observatory.predict_transfer_ms,
                    remote_spec=remote_spec,
                ),
            )
            return engine, chooser.stop  # watcher stops the chooser w/ model

        watcher = ModelWatcher(runtime, manager, engine_factory=kv_factory)
    else:
        watcher = ModelWatcher(
            runtime, manager, router_mode=RouterMode(args.router_mode)
        )
    await watcher.start()
    await observatory.start(runtime.namespace("dynamo"))
    service = HttpService(
        manager, host=args.host, port=args.port,
        template=_load_template(args),
        observatory=observatory,
    )
    await service.start()
    print(f"frontend at {service.url} (hub {addr}); models appear on discovery")
    stop = asyncio.Event()
    # hub loss must terminate the frontend (fail loud), not freeze its view
    if hasattr(runtime.hub, "on_connection_lost"):
        runtime.hub.on_connection_lost = stop.set
    try:
        await _wait_forever(stop)
    finally:
        await service.stop()
        await observatory.stop()
        await watcher.stop()
        await runtime.shutdown()
        if owned_hub:
            await owned_hub.stop()


async def run_worker(args) -> None:
    """in=dyn out=jax|mocker: engine worker on the hub."""
    if not args.hub:
        raise SystemExit("in=dyn requires --hub")
    from .llm.kv_router.publisher import KvEventPublisher, WorkerMetricsPublisher
    from .llm.model_card import register_llm
    from .runtime.component import DistributedRuntime

    ns_name, comp_name, ep_name = parse_endpoint_id(args.endpoint)
    # build the engine BEFORE connecting: weight loading blocks the event
    # loop long enough to starve lease keepalives and get this worker evicted
    engine = await _make_engine(args)
    addr, owned_hub = await _resolve_hub(args)
    runtime = await DistributedRuntime.detached(addr)
    ns = runtime.namespace(ns_name)
    comp = ns.component(comp_name)
    ep = comp.endpoint(ep_name)
    prefill_worker = None
    if args.disagg == "prefill":
        # queue consumer only: no generate endpoint, no model registration
        from .llm.disagg import PrefillWorker

        prefill_worker = PrefillWorker(
            engine, ns,
            chunked=not args.no_chunked_kv,
            layers_per_chunk=args.kv_chunk_layers,
        )
        await prefill_worker.start()
        print(f"prefill worker consuming {ns_name}_prefill_queue (hub {addr})")
    elif args.disagg == "decode":
        from .llm.disagg import (
            KV_DELIVER_ENDPOINT,
            DisaggConfig,
            DisaggDecodeEngine,
        )

        disagg = DisaggDecodeEngine(
            engine,
            ns,
            comp_name,
            # serve() registers under the primary lease; fixing the id now
            # avoids a window where a shipped job carries a placeholder
            instance_id=runtime.primary_lease,
            cfg=DisaggConfig(
                max_local_prefill_length=args.max_local_prefill_length,
                max_prefill_queue_depth=args.max_prefill_queue_depth,
            ),
            block_size=args.block_size or args.page_size,
        )
        # kv_deliver must exist before any request can be shipped remote, or
        # the prefill worker's write-back races a missing endpoint
        await comp.endpoint(KV_DELIVER_ENDPOINT).serve_raw(
            disagg.kv_deliver_handler()
        )
        await disagg.start_config_watch()  # live policy reload from the hub
        served = await _wire_prefix_onboard(disagg, engine, ns, comp, comp_name)
        await ep.serve(served)
    else:
        served = await _wire_prefix_onboard(engine, engine, ns, comp, comp_name)
        await ep.serve(served)
    embed_ep_name = ""
    if hasattr(engine, "embed") and args.disagg != "prefill":
        # pooled-embedding leg: a sibling endpoint the frontend watcher
        # discovers through the model entry's embed_endpoint field
        from .llm.embedding import EmbeddingEngine

        embed_ep_name = f"{ep_name}_embed"
        await comp.endpoint(embed_ep_name).serve(
            EmbeddingEngine(
                engine.embed,
                max_input_tokens=getattr(
                    getattr(engine, "cfg", None), "max_seq_len", None
                ),
            )
        )
    pub = KvEventPublisher(ns, worker_id=runtime.primary_lease)
    pub.hook(engine)
    # fleet KV economy: arm the G4 tier over the hub blob verbs when the
    # engine parsed a kv_remote spec, and publish tier-residency deltas
    # whenever the offload plane exists at all (peer host/disk holdings
    # feed the cluster-global prefix index even without G4)
    holdings_pub = None
    if getattr(engine, "offload_engine", None) is not None:
        from .llm.kv_router.publisher import KvHoldingsPublisher

        if getattr(engine, "kv_remote_spec", None) is not None:
            from .runtime.transports.client import HubBlobClient

            engine.attach_remote_kv(
                HubBlobClient(runtime.hub, asyncio.get_running_loop()),
                worker_id=runtime.primary_lease,
            )
        holdings_pub = KvHoldingsPublisher(ns, worker_id=runtime.primary_lease)
        holdings_pub.hook(engine)
    metrics_pub = WorkerMetricsPublisher(engine.metrics)
    await metrics_pub.attach(comp)
    # fleet plane: identity-label this worker's exposition and publish
    # periodic telemetry snapshots to the hub for the observatory
    from .runtime import metrics as rtm
    from .runtime.telemetry import TelemetryPublisher

    role = args.disagg or "worker"
    rtm.set_worker_identity(worker_id=runtime.primary_lease, role=role)
    telemetry_pub = TelemetryPublisher(
        ns,
        worker_id=runtime.primary_lease,
        role=role,
        # mocker engines route their synthetic link observations through a
        # per-engine log; everything else uses the process-wide one the
        # disagg delivery path feeds
        transfer_log=getattr(engine, "transfer_log", None),
    )
    telemetry_pub.start()
    stop = asyncio.Event()
    # hub loss orphans this worker's registrations: exit so a supervisor
    # restarts it into a live cluster (fail loud)
    if hasattr(runtime.hub, "on_connection_lost"):
        runtime.hub.on_connection_lost = stop.set
    if args.model_path and args.disagg != "prefill":
        card = await register_llm(
            runtime, ep, args.model_path,
            model_name=args.model_name,
            kv_block_size=args.block_size or args.page_size,
            embed_endpoint=embed_ep_name,
        )
        print(f"worker serving model {card.name} on {args.endpoint} (hub {addr})")
    elif args.disagg != "prefill":
        print(f"worker serving on {args.endpoint} (hub {addr}; no model card)")
    try:
        await _wait_forever(stop, drain_runtime=runtime)
    finally:
        if prefill_worker is not None:
            await prefill_worker.stop()
        await telemetry_pub.stop(final=False)
        if holdings_pub is not None:
            await holdings_pub.close()
        await pub.close()
        await engine.stop()
        await runtime.shutdown()
        if owned_hub:
            await owned_hub.stop()


async def run_text(args) -> None:
    """in=text out=jax|mocker: REPL / one-shot prompt through the full
    preprocessor->engine->detokenizer pipeline."""
    from .llm.backend import Backend
    from .llm.preprocessor import OpenAIPreprocessor
    from .protocols.openai import ChatCompletionRequest
    from .runtime.engine import Annotated, Context, as_response_stream
    from .runtime.pipeline import link

    engine = await _make_engine(args)
    tokenizer = _tokenizer_for(args)
    name = _model_name(args)
    pipeline = link(OpenAIPreprocessor(name, tokenizer), Backend(tokenizer), engine)

    async def ask(text: str) -> None:
        req = ChatCompletionRequest.from_dict(
            {
                "model": name,
                "messages": [{"role": "user", "content": text}],
                "stream": True,
                "max_tokens": args.max_tokens,
            }
        )
        stream = await as_response_stream(pipeline, Context.new(req))
        async for item in stream:
            if not isinstance(item, Annotated):
                item = Annotated.from_data(item)
            if item.is_error():
                print(f"\n[error] {item.error_message()}", flush=True)
                return
            data = item.data or {}
            for choice in data.get("choices", []):
                delta = (choice.get("delta") or {}).get("content")
                if delta:
                    print(delta, end="", flush=True)
        print()

    try:
        if args.prompt is not None:
            await ask(args.prompt)
            return
        loop = asyncio.get_running_loop()
        while True:
            line = await loop.run_in_executor(None, sys.stdin.readline)
            if not line:
                break
            line = line.strip()
            if line in ("exit", "quit", ""):
                if line:
                    break
                continue
            await ask(line)
    finally:
        await engine.stop()


async def run_batch(args) -> None:
    """in=batch out=jax|mocker|echo: run a JSONL file of prompts through the
    full pipeline concurrently; one JSON result line per prompt, in input
    order (reference dynamo-run ``in=batch:file``).

    Input lines: ``{"text": "..."}`` (or ``{"prompt": ...}``), optional
    ``max_tokens``.  Output lines: ``{"index", "text", "response"}``.
    """
    import json as _json

    from .llm.backend import Backend
    from .llm.preprocessor import OpenAIPreprocessor
    from .protocols.openai import ChatCompletionRequest
    from .runtime.engine import Annotated, Context, as_response_stream
    from .runtime.pipeline import link

    if not args.input_file:
        raise SystemExit("in=batch requires --input-file prompts.jsonl")
    engine = await _make_engine(args)
    tokenizer = _tokenizer_for(args)
    name = _model_name(args)
    pipeline = link(OpenAIPreprocessor(name, tokenizer), Backend(tokenizer), engine)

    def _read_prompts() -> list:
        out = []
        with open(args.input_file, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if line:
                    out.append(_json.loads(line))
        return out

    # file I/O off the loop: the engine may already be serving its tick
    # loop on this thread
    prompts = await asyncio.to_thread(_read_prompts)

    async def one(i, entry):
        text = entry.get("text") or entry.get("prompt") or ""
        req = ChatCompletionRequest.from_dict(
            {
                "model": name,
                "messages": [{"role": "user", "content": text}],
                "stream": True,
                "max_tokens": int(entry.get("max_tokens", args.max_tokens)),
            }
        )
        parts: list = []
        error = None
        stream = await as_response_stream(pipeline, Context.new(req))
        async for item in stream:
            if not isinstance(item, Annotated):
                item = Annotated.from_data(item)
            if item.is_error():
                error = item.error_message()
                break
            for choice in (item.data or {}).get("choices", []):
                delta = (choice.get("delta") or {}).get("content")
                if delta:
                    parts.append(delta)
        out = {"index": i, "text": text, "response": "".join(parts)}
        if error:
            out["error"] = error
        return out

    def _write_results(results: list) -> None:
        sink = (
            open(args.output_file, "w", encoding="utf-8")
            if args.output_file else sys.stdout
        )
        try:
            for r in results:
                sink.write(_json.dumps(r) + "\n")
        finally:
            if args.output_file:
                sink.close()

    try:
        results = await asyncio.gather(
            *(one(i, e) for i, e in enumerate(prompts))
        )
        await asyncio.to_thread(_write_results, results)
    finally:
        await engine.stop()


async def _wait_forever(
    stop: Optional[asyncio.Event] = None, drain_runtime=None
) -> None:
    """Park until a signal (or ``stop``).  With ``drain_runtime`` set,
    SIGTERM triggers a graceful drain first -- deregister from discovery,
    finish in-flight requests (``DYN_DRAIN_TIMEOUT_S``, default 30) --
    before stopping, so supervisor scale-down / k8s rollout never drops
    requests a drain could have finished.  SIGINT stays immediate."""
    stop = stop or asyncio.Event()
    loop = asyncio.get_running_loop()
    drain_tasks: set = set()

    async def _drain_then_stop() -> None:
        try:
            await drain_runtime.drain(
                float(os.environ.get("DYN_DRAIN_TIMEOUT_S", "30"))
            )
        finally:
            stop.set()

    def _on_term() -> None:
        if drain_runtime is None or drain_runtime.draining:
            stop.set()
            return
        task = asyncio.ensure_future(_drain_then_stop())
        drain_tasks.add(task)
        task.add_done_callback(drain_tasks.discard)

    with contextlib.suppress(NotImplementedError):
        loop.add_signal_handler(signal.SIGINT, stop.set)
    with contextlib.suppress(NotImplementedError):
        loop.add_signal_handler(signal.SIGTERM, _on_term)
    await stop.wait()


async def run_llmctl(args) -> int:
    """Model administration against a live hub (reference llmctl: list /
    remove chat-models)."""
    from .llm.model_card import MDC_OBJ_PREFIX, MODEL_ROOT, ModelEntry, slugify
    from .runtime.transports.client import HubClient

    host, _, port = args.hub.rpartition(":")
    try:
        hub = await HubClient(host or "127.0.0.1", int(port)).connect()
    except OSError as e:
        raise SystemExit(f"cannot reach hub at {args.hub}: {e}")
    try:
        entries = await hub.kv_get_prefix(f"{MODEL_ROOT}/")
        if args.llmcmd == "list":
            by_slug = {}
            for key, blob in entries:
                slug = key.split("/")[1]
                by_slug.setdefault(slug, []).append(ModelEntry.from_json(blob))
            if not by_slug:
                print("no models registered")
                return 0
            for slug, insts in sorted(by_slug.items()):
                e = insts[0]
                print(
                    f"{e.name}  instances={len(insts)}  "
                    f"endpoint=dyn://{e.namespace}.{e.component}.{e.endpoint}  "
                    f"type={e.model_type}"
                )
            return 0
        # remove
        slug = slugify(args.name)
        n = await hub.kv_delete_prefix(f"{MODEL_ROOT}/{slug}/")
        # the MDC object is keyed by slug as well; best-effort cleanup
        with contextlib.suppress(Exception):
            await hub.obj_del(f"{MDC_OBJ_PREFIX}/{slug}")
        print(f"removed {n} instance entr{'y' if n == 1 else 'ies'} for "
              f"{args.name!r}")
        return 0 if n else 1
    finally:
        await hub.close()


async def run_profile(args) -> int:
    """profile: read a live frontend's tick-phase profile
    (``GET /profile/ticks``) and print where tick wall time goes -- the
    host phases, occupancy, and the dispatch gap ROADMAP item 2 attacks.
    ``--watch S`` arms profiling, samples S seconds of live traffic, then
    reports; ``--device S`` additionally triggers a bounded
    ``jax.profiler`` capture on the server."""
    import json as _json
    import urllib.request

    base = args.url.rstrip("/")
    if "://" not in base:
        base = "http://" + base

    def _call(path: str, payload=None):
        import urllib.error

        data = None
        if payload is not None:
            data = _json.dumps(payload).encode()
        req = urllib.request.Request(
            base + path,
            data=data,
            headers={"Content-Type": "application/json"} if data else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=35.0) as resp:
                return _json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            # structured non-2xx bodies (e.g. /profile/device's graceful
            # 503 {ok:false,error}) are answers, not connectivity failures
            body = e.read().decode(errors="replace")
            try:
                return _json.loads(body)
            except ValueError:
                raise OSError(f"HTTP {e.code}: {body[:200]}") from e

    async def call(path: str, payload=None):
        return await asyncio.to_thread(_call, path, payload)

    try:
        if args.disable:
            out = await call("/profile/ticks", {"enabled": False})
            print(f"tick profiling disabled (server enabled={out['enabled']})")
            return 0
        if args.enable or args.watch is not None:
            await call("/profile/ticks", {"enabled": True, "clear": True})
        if args.watch is not None:
            print(f"profiling armed; sampling {args.watch:g}s of traffic...")
            await asyncio.sleep(max(args.watch, 0.0))
        if args.device is not None:
            dev = await call(
                "/profile/device", {"duration_s": args.device}
            )
            if dev.get("ok"):
                print(f"device trace captured to {dev['log_dir']}")
            else:
                print(f"device trace unavailable: {dev.get('error')}")
        data = await call("/profile/ticks")
    except OSError as e:
        print(f"cannot reach {base}: {e}")
        return 1
    summ = data.get("summary") or {}
    if not summ.get("ticks"):
        print(
            "no tick records yet (is the profiler enabled -- "
            "DYN_TICK_PROFILE=1, --enable, or --watch -- and is the "
            "engine serving traffic?)"
        )
        return 1
    wall = summ.get("wall_s") or 0.0
    print(
        f"{summ['ticks']} ticks, {summ['dispatches']} dispatches, "
        f"wall {wall:.3f}s, host occupancy "
        f"{summ.get('host_occupancy')}"
    )
    print(f"{'phase':<12} {'total_s':>10} {'% wall':>8}")
    totals = summ.get("phase_totals_s") or {}
    for name, tot in sorted(totals.items(), key=lambda kv: -kv[1]):
        frac = 100.0 * tot / wall if wall else 0.0
        print(f"{name:<12} {tot:>10.4f} {frac:>7.1f}%")
    print(
        f"dispatch gap p50={summ.get('gap_p50_ms')}ms "
        f"p95={summ.get('gap_p95_ms')}ms"
    )
    if args.json_out:
        payload = _json.dumps(data.get("chrome_trace") or {}, indent=2)
        await asyncio.to_thread(_write_text, args.json_out, payload)
        print(f"chrome trace written to {args.json_out}")
    return 0


async def run_profile_sla(args) -> int:
    """profile-sla: drive the engine, print the TTFT/ITL table + the SLO
    recommendation as one JSON object."""
    import json

    from .planner.profile_sla import SlaProfiler

    isls = [int(x) for x in args.isl.split(",") if x]
    batches = [int(x) for x in args.batch.split(",") if x]
    engine = await _make_engine(args)  # same builder as `run` (shared flags)
    vocab = _tokenizer_for(args).vocab_size if args.model_path else 30000
    try:
        prof = await SlaProfiler(engine, vocab_size=vocab).profile(
            isls=isls, batches=batches, osl=args.osl
        )
        print(
            json.dumps(
                {
                    "profile": prof.to_dict(),
                    "recommendation": prof.recommend(
                        args.ttft_slo_ms, args.itl_slo_ms
                    ),
                },
                indent=2,
            )
        )
    finally:
        await engine.stop()
    return 0


async def run_bench(args) -> int:
    """bench: fire the workload at a running frontend, print one JSON
    summary (output tok/s, TTFT percentiles, error counts)."""
    import json

    from .bench_serving import run_bench as drive, synth_workload, trace_workload

    if args.trace:
        workload = trace_workload(
            args.trace,
            block_size=args.trace_block_size,
            vocab=args.vocab_size,
            speedup=args.speedup_ratio,
            limit=args.num_requests,  # None = replay the whole trace
        )
    else:
        workload = synth_workload(
            args.num_requests if args.num_requests is not None else 64,
            args.isl, args.osl, args.request_rate,
            vocab=args.vocab_size, seed=args.seed,
        )
    report = await drive(
        args.host, args.port, args.model, workload,
        concurrency=args.concurrency,
    )
    summary = report.summary()
    if args.fleet:
        from .bench_serving import fetch_fleet

        try:
            summary["fleet"] = await fetch_fleet(args.host, args.port)
        except Exception as e:
            summary["fleet"] = {"error": repr(e)}
    print(json.dumps(summary, indent=2))
    return 0 if summary["num_errors"] == 0 else 1


async def run_metrics(args) -> int:
    """metrics: the standalone cluster Prometheus component (reference
    components/metrics :9091) -- scrapes worker load_metrics through the
    hub, subscribes to kv-hit-rate events, serves GET /metrics."""
    from .llm.components import MetricsService
    from .runtime.component import DistributedRuntime

    runtime = await DistributedRuntime.detached(args.hub)
    svc = MetricsService(runtime, args.namespace, args.component)
    await svc.start()
    host, port = await svc.serve_http(args.host, args.port)
    print(f"cluster metrics at http://{host}:{port}/metrics (hub {args.hub})")
    stop = asyncio.Event()
    if hasattr(runtime.hub, "on_connection_lost"):
        runtime.hub.on_connection_lost = stop.set
    try:
        await _wait_forever(stop)
    finally:
        await svc.stop()
        await runtime.shutdown()
    return 0


def format_fleet_table(summary, show_plan: bool = False) -> str:
    """Render one /fleet summary as the `dynamo-tpu fleet` table."""
    lines = []
    totals = summary.get("totals", {})
    roles = totals.get("workers_by_role", {})
    head = ", ".join(
        f"{n} {role}" for role, n in sorted(roles.items())
    ) or "no workers"
    lines.append(
        f"fleet: {head} | kv pressure "
        f"{totals.get('kv_pressure', 0.0):.2f} | queue "
        f"{totals.get('queue_depth', 0)}"
    )
    slo = totals.get("slo_attainment") or {}
    if slo:
        lines.append(
            "slo:   "
            + "  ".join(f"{k}={v:.3f}" for k, v in sorted(slo.items()))
        )
    cols = ("id", "role", "tok/s", "step ms", "kv", "queue", "slots", "flag")
    rows = []
    for w in summary.get("workers", []):
        step = w.get("step_ms")
        rows.append(
            (
                str(w["worker_id"]),
                w.get("role", "?"),
                f"{w.get('tokens_per_s', 0.0):.1f}",
                "-" if step is None else f"{step:.2f}",
                f"{w.get('kv_pages_used', 0)}/{w.get('kv_pages_total', 0)}",
                str(w.get("queue_depth", 0)),
                f"{w.get('batch_occupancy', 0)}/{w.get('batch_slots', 0)}",
                "QUARANTINED" if w.get("quarantined")
                else ("STRAGGLER" if w.get("straggler") else ""),
            )
        )
    if rows:
        widths = [
            max(len(cols[i]), max(len(r[i]) for r in rows))
            for i in range(len(cols))
        ]
        fmt = "  ".join(f"{{:<{w}}}" for w in widths)
        lines.append(fmt.format(*cols))
        for r in rows:
            lines.append(fmt.format(*r))
    if show_plan:
        plan = summary.get("plan") or {}
        if not plan:
            lines.append("plan:  (no planner adjustments yet)")
        for kind in sorted(plan):
            rec = plan[kind]
            age = ""
            ts = rec.get("ts")
            if ts:
                import time as _time

                age = f" ({max(_time.time() - ts, 0.0):.0f}s ago)"
            lines.append(
                f"plan:  {kind}: {rec.get('action', '?')} from "
                f"{rec.get('count_before', '?')} -- "
                f"{rec.get('reason', '')}{age}"
            )
    for link in summary.get("links", []):
        bw = link.get("bandwidth_bytes_per_s")
        setup = link.get("setup_ms")
        lines.append(
            f"link {link['src']}->{link['dst']}: "
            + ("fitting..." if bw is None
               else f"{bw / 1e6:.1f} MB/s + {setup or 0.0:.2f} ms setup")
            + f" ({link.get('samples', 0)} samples)"
        )
    return "\n".join(lines)


async def run_fleet(args) -> int:
    """fleet: subscribe to worker telemetry on the hub, print a live
    cluster table (the CLI face of GET /fleet)."""
    import json

    from .fleet import FleetObservatory
    from .runtime.component import DistributedRuntime
    from .runtime.metrics import MetricsRegistry

    runtime = await DistributedRuntime.detached(args.hub)
    # private registry: the CLI process has no scrape surface, and must
    # not pollute a colocated default registry with fleet families
    observatory = FleetObservatory(MetricsRegistry())
    await observatory.start(runtime.namespace(args.namespace))
    stop = asyncio.Event()
    if hasattr(runtime.hub, "on_connection_lost"):
        runtime.hub.on_connection_lost = stop.set
    try:
        while not stop.is_set():
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(stop.wait(), args.interval)
            if stop.is_set():
                break
            summary = observatory.summary()
            if args.json_out:
                print(json.dumps(summary, indent=2))
            else:
                print(format_fleet_table(
                    summary, show_plan=getattr(args, "plan", False)
                ))
                print()
            if args.once:
                break
    except KeyboardInterrupt:
        pass
    finally:
        await observatory.stop()
        await runtime.shutdown()
    return 0


def run_datagen(args) -> int:
    """datagen analyze|synthesize (reference benchmarks/data_generator/cli.py)."""
    import json

    from .datagen import PrefixAnalyzer, Synthesizer
    from .datagen.analyzer import load_trace

    if args.dgcmd == "analyze":
        stats = PrefixAnalyzer.from_file(
            args.input_file, block_size=args.block_size
        ).analyze()
        print(json.dumps(stats, indent=2))
        return 0
    syn = Synthesizer(
        load_trace(args.input_file),
        block_size=args.block_size,
        num_copies=args.num_copies,
        speedup_ratio=args.speedup_ratio,
        prefix_len_multiplier=args.prefix_len_multiplier,
        prompt_len_multiplier=args.prompt_len_multiplier,
        seed=args.seed,
    )
    records = syn.synthesize(args.num_requests)
    Synthesizer.dump(records, args.output_file)
    print(f"wrote {len(records)} requests to {args.output_file}")
    return 0


async def _wire_prefix_onboard(served, engine, ns, comp, comp_name):
    """Enable cross-worker prefix onboarding (G4) when the engine has a host
    offload tier to stage imports in: serve ``kv_export`` (donor side) and
    wrap the serving engine (importer side)."""
    if getattr(engine, "offload", None) is None:
        return served
    from .llm.prefix_onboard import (
        KV_EXPORT_ENDPOINT,
        PrefixOnboardEngine,
        kv_export_handler,
    )

    await comp.endpoint(KV_EXPORT_ENDPOINT).serve_raw(kv_export_handler(engine))
    return PrefixOnboardEngine(served, ns, comp_name, engine=engine)


def run_build(args) -> int:
    """Package a graph directory (tar.gz) and register it with api-store
    (reference `dynamo build`: containerize + push; here the artifact is the
    graph source + manifest, and the runtime image is container/Dockerfile's
    -- one image serves every graph, args select the role)."""
    import io
    import json as _json
    import tarfile
    import urllib.error
    import urllib.request

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        tar.add(args.path, arcname=args.name)
    blob = buf.getvalue()

    base = args.store.rstrip("/")

    def post(path, body):
        req = urllib.request.Request(
            base + path, data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(req) as r:
                return r.status, _json.load(r)
        except urllib.error.HTTPError as e:
            raw = e.read() or b"{}"
            try:
                return e.code, _json.loads(raw)
            except ValueError:  # non-JSON error page (proxy, wrong server)
                return e.code, {"error": raw[:200].decode("latin1")}
        except urllib.error.URLError as e:
            raise SystemExit(f"cannot reach api-store at {base}: {e.reason}")

    status, out = post("/api/v1/components", {"name": args.name})
    if status not in (201, 409):  # existing component is fine
        raise SystemExit(f"component create failed: {status} {out}")
    status, out = post(
        f"/api/v1/components/{args.name}/versions",
        {"version": args.version, "manifest": {"entry": args.name}},
    )
    if status != 201:
        raise SystemExit(f"version create failed: {status} {out}")
    req = urllib.request.Request(
        f"{base}/api/v1/components/{args.name}/versions/{args.version}/artifact",
        data=blob, method="PUT",
        headers={"Content-Type": "application/octet-stream"},
    )
    try:
        with urllib.request.urlopen(req) as r:
            out = _json.load(r)
    except urllib.error.HTTPError as e:
        raise SystemExit(
            f"artifact upload failed: HTTP {e.code} {e.read()[:200]!r}"
        )
    except urllib.error.URLError as e:
        raise SystemExit(f"cannot reach api-store at {base}: {e.reason}")
    print(
        f"built {args.name}:{args.version} "
        f"({out.get('artifact_bytes', len(blob))} bytes) -> {base}"
    )
    return 0


def run_deploy(args) -> int:
    """Fetch a built graph from api-store, unpack it, render its k8s
    manifests, and record the deployment (reference `dynamo deploy`)."""
    import io
    import json as _json
    import os
    import tarfile
    import urllib.error
    import urllib.request

    base = args.store.rstrip("/")
    url = (
        f"{base}/api/v1/components/{args.name}/versions/"
        f"{args.version}/artifact"
    )
    try:
        with urllib.request.urlopen(url) as r:
            blob = r.read()
    except urllib.error.HTTPError as e:
        raise SystemExit(
            f"{args.name}:{args.version} not fetchable from {base}: "
            f"HTTP {e.code} {e.read()[:200]!r}"
        )
    except urllib.error.URLError as e:
        raise SystemExit(f"cannot reach api-store at {base}: {e.reason}")
    os.makedirs(args.out_dir, exist_ok=True)
    with tarfile.open(fileobj=io.BytesIO(blob), mode="r:gz") as tar:
        try:
            tar.extractall(args.out_dir, filter="data")
        except TypeError:  # 3.10 < 3.10.12 lacks the filter kwarg
            tar.extractall(args.out_dir)  # noqa: S202 - own-store artifact

    from .deploy import DeploymentSpec, render_manifests

    spec = DeploymentSpec(
        name=args.name, model_path=args.model_path, image=args.image
    )
    mdir = os.path.join(args.out_dir, "manifests")
    os.makedirs(mdir, exist_ok=True)
    for fname, text in render_manifests(spec).items():
        with open(os.path.join(mdir, fname), "w") as f:
            f.write(text)
    req = urllib.request.Request(
        base + "/api/v1/deployments",
        data=_json.dumps(
            {"name": args.name,
             "spec": {"version": args.version, "image": args.image,
                      "model_path": args.model_path}}
        ).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req):
            pass
    except urllib.error.HTTPError as e:
        raise SystemExit(
            f"deployment record failed: HTTP {e.code} {e.read()[:200]!r}"
        )
    except urllib.error.URLError as e:
        raise SystemExit(f"cannot reach api-store at {base}: {e.reason}")
    print(
        f"deployed {args.name}:{args.version}: artifact + manifests under "
        f"{args.out_dir} (kubectl apply -f {mdir})"
    )
    return 0


async def run_api_store(args) -> int:
    """Serve the deployment-artifact registry over the hub."""
    from .api_store import ApiStoreService
    from .runtime.component import DistributedRuntime

    rt = await DistributedRuntime.detached(args.hub)
    svc = ApiStoreService(rt.hub, host=args.host, port=args.port)
    await svc.start()
    print(f"api-store at http://{args.host}:{svc.address[1]} (hub {args.hub})")
    stop = asyncio.Event()
    rt.hub.on_connection_lost = stop.set
    try:
        await stop.wait()
        print("hub connection lost; exiting", file=sys.stderr)
        return 1
    finally:
        await svc.stop()
        await rt.shutdown()


def run_eval(args) -> int:
    """Perplexity of a checkpoint on text: load weights exactly as serving
    would (incl. --quantize int8), score with llm/evaluate.py, print one
    JSON line."""
    import json as _json
    import os

    from .engine.config import ModelConfig
    from .engine.weights import load_safetensors_params
    from .llm.evaluate import evaluate_perplexity
    from .llm.tokenizer import Tokenizer

    if not args.text and not args.text_file:
        raise SystemExit("need --text or --text-file")
    text = args.text or open(args.text_file, encoding="utf-8").read()
    model_cfg = ModelConfig.from_pretrained(args.model_path)
    # load weights exactly as JaxEngine.from_pretrained would: safetensors
    # when present, else a GGUF checkpoint (dequantize-on-load)
    has_st = os.path.isdir(args.model_path) and any(
        f.endswith(".safetensors") for f in os.listdir(args.model_path)
    )
    if has_st:
        params = load_safetensors_params(args.model_path, model_cfg)
    else:
        from .llm.gguf import find_gguf_file, load_gguf_params

        gguf = find_gguf_file(args.model_path)
        if gguf is None:
            raise SystemExit(
                f"{args.model_path}: no .safetensors and no .gguf weights"
            )
        params = load_gguf_params(gguf, model_cfg)
    if args.quantize == "int8":
        from .engine.quant import quantize_params

        params = quantize_params(params, model_cfg)
    tok = Tokenizer.from_model_dir(args.model_path)
    ids = tok.encode(text)
    out = evaluate_perplexity(params, model_cfg, ids, window=args.window)
    out["model"] = args.model_path
    out["quantize"] = args.quantize
    print(_json.dumps({k: (round(v, 4) if isinstance(v, float) else v)
                       for k, v in out.items()}))
    return 0


async def run_operator(args) -> int:
    """Run the reconcile controller (reference operator equivalent)."""
    from .operator import KubectlBackend, Operator, OperatorConfig
    from .runtime.component import DistributedRuntime

    rt = await DistributedRuntime.detached(args.hub)
    op = Operator(
        rt.hub,
        KubectlBackend(kubectl=args.kubectl, namespace=args.namespace),
        OperatorConfig(
            interval_s=args.interval,
            image=args.image,
            namespace=args.namespace,
        ),
    )
    try:
        if args.once:
            actions = await op.reconcile_once()
            for a in actions:
                if a.action != "ok":
                    print(f"{a.deployment}: {a.action}")
            print(f"reconciled ({len(actions)} deployments checked)")
            return 0
        await op.start()
        print(f"operator reconciling every {args.interval}s (hub {args.hub})")
        stop = asyncio.Event()
        rt.hub.on_connection_lost = stop.set
        await stop.wait()
        print("hub connection lost; exiting", file=sys.stderr)
        return 1
    finally:
        await op.stop()
        await rt.shutdown()


async def run_trace(args) -> int:
    """Assemble one request's span timeline from every component on the hub.

    Discovery comes from the hub's ``instances/`` keyspace; each component's
    auto-served ``_trace`` endpoint returns its process's spans for the
    request id, and the merged set prints as one offset-ordered timeline
    (plus optional Chrome-trace JSON for chrome://tracing / Perfetto)."""
    import json as _json

    from .runtime import tracing
    from .runtime.component import (
        INSTANCE_ROOT_PATH,
        DistributedRuntime,
        Instance,
    )

    rt = await DistributedRuntime.detached(args.hub)
    try:
        prefix = f"{INSTANCE_ROOT_PATH}/{args.namespace}/"
        components = set()
        for _key, value in await rt.hub.kv_get_prefix(prefix):
            try:
                components.add(Instance.from_json(value).component)
            except Exception:
                logger.warning("skipping malformed instance record at %s", _key)
        if not components:
            print(f"no components registered under namespace {args.namespace}")
            return 1
        ns = rt.namespace(args.namespace)
        # scrape components concurrently: one wedged component costs one
        # timeout in total, not one per component
        results = await asyncio.gather(
            *(
                ns.component(comp).scrape_trace(
                    args.request_id, timeout_s=args.timeout
                )
                for comp in sorted(components)
            ),
            return_exceptions=True,
        )
        spans = []
        for comp, res in zip(sorted(components), results):
            if isinstance(res, Exception):
                logger.warning("trace scrape failed for %s: %s", comp, res)
            else:
                spans.extend(res)
        # colocated components share one process collector: the same span
        # comes back from every component scrape in that process
        seen_ids = set()
        deduped = []
        for s in spans:
            key = s.get("span_id")
            if key:
                if key in seen_ids:
                    continue
                seen_ids.add(key)
            deduped.append(s)
        spans = deduped
        if not spans:
            print(
                f"no spans for request {args.request_id} "
                f"(is DYN_TRACE=1 set on the serving processes?)"
            )
            return 1
        spans.sort(key=lambda s: s.get("start_s", 0.0))
        t0 = spans[0].get("start_s", 0.0)
        trace_ids = {s.get("trace_id") for s in spans if s.get("trace_id")}
        print(
            f"request {args.request_id}: {len(spans)} spans across "
            f"{len({s.get('component') or 'process' for s in spans})} "
            f"components (trace {', '.join(sorted(trace_ids)) or 'n/a'})"
        )
        print(f"{'offset_ms':>10}  {'dur_ms':>9}  {'component':<24} name")
        for s in spans:
            off = (s.get("start_s", 0.0) - t0) * 1e3
            print(
                f"{off:10.3f}  {s.get('duration_ms', 0.0):9.3f}  "
                f"{(s.get('component') or '-'):<24} {s.get('name', '')}"
            )
        if args.json_out:
            payload = _json.dumps(tracing.chrome_trace(spans), indent=2)
            await asyncio.to_thread(_write_text, args.json_out, payload)
            print(f"chrome trace written to {args.json_out}")
        return 0
    finally:
        await rt.shutdown()


def _write_text(path: str, payload: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(payload)


async def run_disagg_conf(args) -> int:
    """Write the live disagg routing policy to the hub; every decode worker
    watching the key reloads it (llm/disagg.py start_config_watch)."""
    import json as _json

    from .llm.disagg import disagg_conf_key
    from .runtime.component import DistributedRuntime

    conf = {}
    if args.max_local_prefill_length is not None:
        conf["max_local_prefill_length"] = args.max_local_prefill_length
    if args.max_prefill_queue_depth is not None:
        conf["max_prefill_queue_depth"] = args.max_prefill_queue_depth
    if not conf:
        print("nothing to update (pass --max-local-prefill-length and/or "
              "--max-prefill-queue-depth)")
        return 2
    rt = await DistributedRuntime.detached(args.hub)
    try:
        # read-modify-write: a partial update must not drop fields an
        # earlier update set -- workers that join later apply the snapshot
        key = disagg_conf_key(args.namespace)
        merged: dict = {}
        for _k, value in await rt.hub.kv_get_prefix(key):
            try:
                merged.update(_json.loads(value))
            except Exception:
                # malformed old value: overwrite it, but say so
                logger.warning("discarding malformed disagg conf at %s", _k)
        merged.update(conf)
        await rt.hub.kv_put(key, _json.dumps(merged).encode())
        print(f"disagg conf updated for namespace {args.namespace}: {merged}")
    finally:
        await rt.shutdown()
    return 0


def main(argv=None) -> int:
    from .runtime.utils import configure_logging

    configure_logging()  # DYN_LOG filter spec + DYN_LOG_JSONL mode
    args = build_parser().parse_args(argv)
    if args.cmd == "hub":
        from .runtime.transports.hub import HubServer

        try:
            asyncio.run(
                HubServer(
                    host=args.host, port=args.port, data_dir=args.data_dir
                ).serve_forever()
            )
        except KeyboardInterrupt:
            pass
        return 0
    if args.cmd == "llmctl":
        return asyncio.run(run_llmctl(args))
    if args.cmd == "metrics":
        return asyncio.run(run_metrics(args))
    if args.cmd == "fleet":
        return asyncio.run(run_fleet(args))
    if args.cmd == "datagen":
        return run_datagen(args)
    if args.cmd == "profile":
        return asyncio.run(run_profile(args))
    if args.cmd == "profile-sla":
        return asyncio.run(run_profile_sla(args))
    if args.cmd == "bench":
        return asyncio.run(run_bench(args))
    if args.cmd == "disagg-conf":
        return asyncio.run(run_disagg_conf(args))
    if args.cmd == "trace":
        return asyncio.run(run_trace(args))
    if args.cmd == "api-store":
        return asyncio.run(run_api_store(args))
    if args.cmd == "eval":
        return run_eval(args)
    if args.cmd == "operator":
        return asyncio.run(run_operator(args))
    if args.cmd == "build":
        return run_build(args)
    if args.cmd == "deploy":
        return run_deploy(args)
    args.inp, args.out = _parse_io(args.io)
    try:
        if args.inp == "http" and args.out in ("jax", "mocker", "echo"):
            asyncio.run(run_http_local(args))
        elif args.inp == "http" and args.out == "dyn":
            asyncio.run(run_http_frontend(args))
        elif args.inp == "dyn":
            asyncio.run(run_worker(args))
        elif args.inp == "text":
            asyncio.run(run_text(args))
        elif args.inp == "batch":
            asyncio.run(run_batch(args))
        else:
            raise SystemExit(f"unsupported combination in={args.inp} out={args.out}")
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
