"""``dyn://`` endpoint identifiers (reference lib/runtime protocols.rs:35).

An endpoint id names one served endpoint in the cluster:
``dyn://{namespace}.{component}.{endpoint}``, optionally suffixed with a
lease-scoped instance (``:{lease_hex}``) to address one worker directly --
the string form of the hub keyspace ``instances/{ns}/{comp}/{ep}:{hex}``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional, Tuple

SCHEME = "dyn://"
_RE = re.compile(
    r"^dyn://([A-Za-z0-9_-]+)\.([A-Za-z0-9_-]+)\.([A-Za-z0-9_-]+)"
    r"(?::([0-9a-fA-F]+))?$"
)


@dataclass(frozen=True)
class EndpointId:
    namespace: str
    component: str
    endpoint: str
    instance: Optional[int] = None  # lease id when addressing one worker

    @classmethod
    def parse(cls, s: str) -> "EndpointId":
        m = _RE.match(s)
        if not m:
            raise ValueError(
                f"invalid endpoint id {s!r}: expected "
                f"dyn://namespace.component.endpoint[:instance_hex]"
            )
        ns, comp, ep, inst = m.groups()
        return cls(ns, comp, ep, int(inst, 16) if inst else None)

    def __str__(self) -> str:
        base = f"{SCHEME}{self.namespace}.{self.component}.{self.endpoint}"
        if self.instance is not None:
            return f"{base}:{self.instance:x}"
        return base

    @property
    def subject(self) -> str:
        """The request-plane subject this id serves on."""
        return f"{self.namespace}.{self.component}.{self.endpoint}"

    def instance_key(self) -> str:
        """Hub keyspace entry for a concrete instance (requires one)."""
        if self.instance is None:
            raise ValueError(f"{self} has no instance id")
        return (
            f"instances/{self.namespace}/{self.component}/"
            f"{self.endpoint}:{self.instance:x}"
        )


def parse_endpoint_id(s: str) -> Tuple[str, str, str]:
    """Back-compat tuple form of :meth:`EndpointId.parse` (no instance)."""
    e = EndpointId.parse(s)
    return e.namespace, e.component, e.endpoint
