"""Common token-level protocols between preprocessor, router, and engine.

Reference parity: ``PreprocessedRequest`` / ``LLMEngineOutput`` /
``StopConditions`` / ``SamplingOptions`` in the reference LLM crate
(lib/llm/src/protocols/common/llm_backend.rs:27-90, protocols/common.rs).
These are plain dataclasses with dict (de)serialization so they travel over
the request plane as msgpack/JSON without a schema compiler.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional


class FinishReason(str, Enum):
    EOS = "eos"
    STOP = "stop"
    LENGTH = "length"
    CANCELLED = "cancelled"
    ERROR = "error"

    def to_openai(self) -> str:
        # OpenAI surface only knows stop/length/content_filter.
        if self in (FinishReason.EOS, FinishReason.STOP, FinishReason.CANCELLED):
            return "stop"
        if self is FinishReason.LENGTH:
            return "length"
        return "stop"


@dataclass
class StopConditions:
    """Reference: common.rs StopConditions."""

    max_tokens: Optional[int] = None
    stop: Optional[List[str]] = None
    stop_token_ids_hidden: Optional[List[int]] = None
    min_tokens: Optional[int] = None
    ignore_eos: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "StopConditions":
        return cls(**(d or {}))


@dataclass
class SamplingOptions:
    """Reference: common.rs SamplingOptions (subset that maps onto the engine)."""

    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    seed: Optional[int] = None
    # OpenAI logprobs: None = off; 0 = chosen-token logprob only; N > 0 =
    # chosen + top-N alternatives per position (reference protocol parity:
    # openai/completions/aggregator.rs:43,159)
    logprobs: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "SamplingOptions":
        return cls(**(d or {}))


@dataclass
class SpeculationOptions:
    """Per-request speculative-decoding knobs (spec/ subsystem).

    ``enabled`` arms draft-and-verify for the request; ``num_draft_tokens``
    is the per-verify draft length (engine-clamped to
    ``spec.MAX_DRAFT_TOKENS``); ``drafter`` names a registered drafter
    kind (``ngram``/``prompt_lookup`` today -- see spec/drafter.py).
    Output is always the target model's: greedy and seeded lanes are
    bit-identical with speculation on or off.
    """

    enabled: bool = False
    num_draft_tokens: int = 4
    drafter: str = "ngram"

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(
        cls, d: Optional[Dict[str, Any]]
    ) -> "Optional[SpeculationOptions]":
        if d is None:
            return None
        return cls(**{k: d[k] for k in cls().__dict__ if k in d})


@dataclass
class PreprocessedRequest:
    """Token-level request handed to the engine.

    Reference: llm_backend.rs:27-56 (token_ids, stop/sampling conditions,
    annotations, ``estimated_prefix_hit_num_blocks`` injected by the KV
    router).
    """

    token_ids: List[int]
    stop_conditions: StopConditions = field(default_factory=StopConditions)
    sampling_options: SamplingOptions = field(default_factory=SamplingOptions)
    eos_token_ids: List[int] = field(default_factory=list)
    annotations: List[str] = field(default_factory=list)
    mdc_sum: Optional[str] = None
    estimated_prefix_hit_num_blocks: Optional[int] = None
    # Multimodal soft prompt (llava-style): embedding rows occupying the
    # FIRST len(mm_embeds) prompt positions; the corresponding token_ids are
    # placeholders the embed lookup ignores.  [T_img][hidden] floats.
    mm_embeds: Optional[List[List[float]]] = None
    # speculative decoding knobs (None = off)
    speculation: Optional[SpeculationOptions] = None
    # prompt logprobs (completions echo+logprobs): None = off, 0 = chosen
    # only, N > 0 = with top-N alternatives per prompt position
    prompt_logprobs: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "token_ids": list(self.token_ids),
            "stop_conditions": self.stop_conditions.to_dict(),
            "sampling_options": self.sampling_options.to_dict(),
            "eos_token_ids": list(self.eos_token_ids),
            "annotations": list(self.annotations),
            "mdc_sum": self.mdc_sum,
            "estimated_prefix_hit_num_blocks": self.estimated_prefix_hit_num_blocks,
            "mm_embeds": self.mm_embeds,
            "speculation": (
                self.speculation.to_dict() if self.speculation else None
            ),
            "prompt_logprobs": self.prompt_logprobs,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PreprocessedRequest":
        return cls(
            token_ids=list(d.get("token_ids") or []),
            stop_conditions=StopConditions.from_dict(d.get("stop_conditions")),
            sampling_options=SamplingOptions.from_dict(d.get("sampling_options")),
            eos_token_ids=list(d.get("eos_token_ids") or []),
            annotations=list(d.get("annotations") or []),
            mdc_sum=d.get("mdc_sum"),
            estimated_prefix_hit_num_blocks=d.get("estimated_prefix_hit_num_blocks"),
            mm_embeds=d.get("mm_embeds"),
            speculation=SpeculationOptions.from_dict(d.get("speculation")),
            prompt_logprobs=d.get("prompt_logprobs"),
        )


@dataclass
class ForwardPassMetrics:
    """Worker load metrics published to the KV router
    (reference kv_router/protocols.rs:43-62; 'gpu_*' names kept for parity)."""

    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0
    request_active_slots: int = 0
    request_total_slots: int = 0
    # multi-tier KV offload plane (KVBM G2/G3): blocks parked per tier and
    # the fraction of tier lookups that hit -- the router's warmth signal
    # for preferring workers whose host tier holds reusable prefixes
    host_tier_blocks: int = 0
    disk_tier_blocks: int = 0
    tier_hit_rate: float = 0.0
    # live SLO attainment (runtime/slo.py, dynamo_slo_attainment{kind}):
    # rolling-window fraction of requests meeting the DYN_SLO targets.
    # 1.0 = met / not armed / no samples yet, so load-only consumers see
    # no spurious pressure when the SLO plane is off
    slo_ttft_attainment: float = 1.0
    slo_itl_attainment: float = 1.0
    slo_e2e_attainment: float = 1.0
    # cumulative TTFT-violation counts by attributed cause (runtime/slo.py
    # queue-vs-service first-token decomposition).  Cumulative, not rates:
    # the planner diffs consecutive rounds, so a lost scrape costs one
    # round of resolution, never drift -- same contract as telemetry
    # counters.  0 = SLO plane disarmed / no misses, which reads as "no
    # evidence" to cause-gated scaling rules
    slo_ttft_queue_violations: float = 0.0
    slo_ttft_service_violations: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return self.__dict__.copy()

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ForwardPassMetrics":
        d = d or {}
        return cls(**{k: d[k] for k in cls().__dict__ if k in d})


@dataclass
class LLMEngineOutput:
    """Per-step engine output (reference llm_backend.rs:58-90).

    ``token_ids`` usually holds one decoded token; the final chunk carries a
    ``finish_reason`` and empty tokens.  ``text`` stays None at the engine
    level -- detokenization happens in the Backend stage.
    """

    token_ids: List[int] = field(default_factory=list)
    tokens: Optional[List[str]] = None
    text: Optional[str] = None
    cum_log_probs: Optional[float] = None
    # per-token logprobs aligned with token_ids, and per-token top-N
    # alternatives as [[token_id, logprob], ...] lists (JSON-able)
    logprobs: Optional[List[float]] = None
    top_logprobs: Optional[List[List[List[float]]]] = None
    finish_reason: Optional[FinishReason] = None
    # completed KV blocks for this step (router/event feedback)
    completed_blocks: Optional[List[Dict[str, int]]] = None
    # prompt logprobs (first output item of an echo+logprobs completion):
    # one [token_id, logprob|None, top|None] entry per prompt position
    # (position 0 has no logprob, matching OpenAI prompt-logprobs shape)
    prompt_logprobs: Optional[List[Any]] = None
    # per-request speculation stats, attached to the finish item:
    # {drafted_tokens, accepted_tokens, acceptance_rate, drafter}
    spec: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"token_ids": list(self.token_ids)}
        if self.tokens is not None:
            out["tokens"] = self.tokens
        if self.text is not None:
            out["text"] = self.text
        if self.cum_log_probs is not None:
            out["cum_log_probs"] = self.cum_log_probs
        if self.logprobs is not None:
            out["logprobs"] = self.logprobs
        if self.top_logprobs is not None:
            out["top_logprobs"] = self.top_logprobs
        if self.finish_reason is not None:
            out["finish_reason"] = self.finish_reason.value
        if self.completed_blocks is not None:
            out["completed_blocks"] = self.completed_blocks
        if self.prompt_logprobs is not None:
            out["prompt_logprobs"] = self.prompt_logprobs
        if self.spec is not None:
            out["spec"] = self.spec
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LLMEngineOutput":
        fr = d.get("finish_reason")
        return cls(
            token_ids=list(d.get("token_ids") or []),
            tokens=d.get("tokens"),
            text=d.get("text"),
            cum_log_probs=d.get("cum_log_probs"),
            logprobs=d.get("logprobs"),
            top_logprobs=d.get("top_logprobs"),
            finish_reason=FinishReason(fr) if fr else None,
            completed_blocks=d.get("completed_blocks"),
            prompt_logprobs=d.get("prompt_logprobs"),
            spec=d.get("spec"),
        )

    @classmethod
    def finished(cls, reason: FinishReason) -> "LLMEngineOutput":
        return cls(token_ids=[], finish_reason=reason)
