"""OpenAI-compatible protocol types, SSE codec, and stream aggregators.

Reference parity: lib/llm/src/protocols/openai/* (request/response types,
SSE codec codec.rs, delta generators, stream->full aggregators) reduced to
the fields the serving path consumes.  Requests arrive as JSON dicts; these
dataclasses validate and normalize them, and the builders produce
wire-shaped dicts for both the streaming (chunk) and aggregated (full)
responses.

``nvext``-style extension fields are kept under the same names the reference
uses (ignore_eos, min_tokens, annotations) but accepted at the top level
too, matching common client behavior.
"""

from __future__ import annotations

import json
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union


# Wire marker distinguishing validation failures from other remote errors
# when an OpenAIError crosses the data plane as a flat message (the
# distributed embedding leg); stripped before anything user-facing.
INVALID_MARK = "[invalid_request] "


class OpenAIError(ValueError):
    """Invalid request -> HTTP 400 with an OpenAI-shaped error body."""

    def __init__(self, message: str, code: int = 400) -> None:
        super().__init__(message)
        self.code = code

    def to_body(self) -> Dict[str, Any]:
        msg = str(self)
        if msg.startswith(INVALID_MARK):  # wire marker is not user-facing
            msg = msg[len(INVALID_MARK):]
        return {
            "error": {
                "message": msg,
                "type": "invalid_request_error",
                "code": self.code,
            }
        }


def _as_stop_list(stop: Union[None, str, List[str]]) -> Optional[List[str]]:
    if stop is None:
        return None
    if isinstance(stop, str):
        return [stop]
    if isinstance(stop, list) and all(isinstance(s, str) for s in stop):
        return list(stop) or None
    raise OpenAIError("'stop' must be a string or a list of strings")


@dataclass
class SamplingFields:
    """Sampling/stop fields shared by chat and completion requests."""

    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    max_tokens: Optional[int] = None
    min_tokens: Optional[int] = None
    stop: Optional[List[str]] = None
    seed: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    ignore_eos: bool = False
    # HF-style repetition penalty (nvext field, reference SamplingOptions)
    repetition_penalty: Optional[float] = None
    # normalized logprobs request: None = off, 0 = chosen-token only,
    # N > 0 = chosen + top-N alternatives (clamped to 8, PARITY.md)
    logprobs: Optional[int] = None

    @classmethod
    def from_dict(
        cls, d: Dict[str, Any], chat: bool = False
    ) -> "SamplingFields":
        nvext = d.get("nvext") or {}
        max_tokens = d.get("max_completion_tokens", d.get("max_tokens"))
        out = cls(
            temperature=d.get("temperature"),
            top_p=d.get("top_p"),
            top_k=d.get("top_k", nvext.get("top_k")),
            max_tokens=max_tokens,
            min_tokens=d.get("min_tokens", nvext.get("min_tokens")),
            stop=_as_stop_list(d.get("stop")),
            seed=d.get("seed"),
            frequency_penalty=d.get("frequency_penalty"),
            presence_penalty=d.get("presence_penalty"),
            ignore_eos=bool(d.get("ignore_eos", nvext.get("ignore_eos", False))),
            repetition_penalty=d.get(
                "repetition_penalty", nvext.get("repetition_penalty")
            ),
            logprobs=_parse_logprobs(d, chat),
        )
        if out.temperature is not None and not 0.0 <= out.temperature <= 2.0:
            raise OpenAIError("'temperature' must be in [0, 2]")
        if out.top_p is not None and not 0.0 < out.top_p <= 1.0:
            raise OpenAIError("'top_p' must be in (0, 1]")
        if out.max_tokens is not None and out.max_tokens < 1:
            raise OpenAIError("'max_tokens' must be >= 1")
        for fname in ("frequency_penalty", "presence_penalty"):
            v = getattr(out, fname)
            if v is not None and not -2.0 <= v <= 2.0:
                raise OpenAIError(f"'{fname}' must be in [-2, 2]")
        if out.repetition_penalty is not None and out.repetition_penalty <= 0:
            raise OpenAIError("'repetition_penalty' must be > 0")
        return out


def _parse_speculation(d: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Per-request speculative-decoding knobs -> normalized dict (None =
    off).  Accepted at the top level or under ``nvext`` (matching the other
    extension fields): ``{"speculation": {"enabled": true,
    "num_draft_tokens": 4, "drafter": "ngram"}}``.  A bare ``{}`` block
    means "on with defaults".  Drafter-kind existence is validated by the
    engine (the registry is pluggable); the protocol checks shape only."""
    spec = d.get("speculation", (d.get("nvext") or {}).get("speculation"))
    if spec is None or spec is False:  # false = explicitly off, like absent
        return None
    if spec is True:
        spec = {}
    if not isinstance(spec, dict):
        raise OpenAIError("'speculation' must be an object or a boolean")
    enabled = spec.get("enabled", True)
    if not isinstance(enabled, bool):
        raise OpenAIError("'speculation.enabled' must be a boolean")
    n = spec.get("num_draft_tokens", 4)
    if not isinstance(n, int) or isinstance(n, bool) or n < 1:
        raise OpenAIError(
            "'speculation.num_draft_tokens' must be a positive integer"
        )
    drafter = spec.get("drafter", "ngram")
    if not isinstance(drafter, str) or not drafter:
        raise OpenAIError("'speculation.drafter' must be a non-empty string")
    return {"enabled": enabled, "num_draft_tokens": n, "drafter": drafter}


def _parse_logprobs(d: Dict[str, Any], chat: bool) -> Optional[int]:
    """OpenAI logprobs fields -> normalized top-N (None = off).

    Chat: ``logprobs: bool`` + ``top_logprobs: int``; completions:
    ``logprobs: int`` (N alternatives alongside the chosen token).
    Reference protocol parity: openai/completions/aggregator.rs:43."""
    lp = d.get("logprobs")
    if lp is None or lp is False:
        return None
    if chat:
        if not isinstance(lp, bool):
            raise OpenAIError("chat 'logprobs' must be a boolean")
        top = d.get("top_logprobs", 0)
        if not isinstance(top, int) or top < 0:
            raise OpenAIError("'top_logprobs' must be a non-negative integer")
        return min(top, 8)
    if isinstance(lp, bool):  # completions logprobs is numeric
        raise OpenAIError("'logprobs' must be an integer for completions")
    if not isinstance(lp, int) or lp < 0:
        raise OpenAIError("'logprobs' must be a non-negative integer")
    return min(lp, 8)


@dataclass
class ChatCompletionRequest:
    """POST /v1/chat/completions body (subset the engine consumes)."""

    model: str
    messages: List[Dict[str, Any]]
    sampling: SamplingFields
    stream: bool = False
    annotations: List[str] = field(default_factory=list)
    # normalized per-request speculative-decoding knobs (None = off)
    speculation: Optional[Dict[str, Any]] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ChatCompletionRequest":
        model = d.get("model")
        if not model or not isinstance(model, str):
            raise OpenAIError("'model' is required")
        messages = d.get("messages")
        if not isinstance(messages, list) or not messages:
            raise OpenAIError("'messages' must be a non-empty list")
        for m in messages:
            if not isinstance(m, dict) or "role" not in m:
                raise OpenAIError("each message needs a 'role'")
        if d.get("n") not in (None, 1):
            raise OpenAIError("only n=1 is supported")
        nvext = d.get("nvext") or {}
        return cls(
            model=model,
            messages=messages,
            sampling=SamplingFields.from_dict(d, chat=True),
            stream=bool(d.get("stream", False)),
            annotations=list(nvext.get("annotations") or []),
            speculation=_parse_speculation(d),
        )


@dataclass
class CompletionRequest:
    """POST /v1/completions body."""

    model: str
    prompt: Union[str, List[int]]
    sampling: SamplingFields
    stream: bool = False
    echo: bool = False
    # normalized per-request speculative-decoding knobs (None = off)
    speculation: Optional[Dict[str, Any]] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CompletionRequest":
        model = d.get("model")
        if not model or not isinstance(model, str):
            raise OpenAIError("'model' is required")
        prompt = d.get("prompt")
        if isinstance(prompt, list) and all(isinstance(t, int) for t in prompt):
            pass  # pre-tokenized prompt
        elif not isinstance(prompt, str):
            raise OpenAIError("'prompt' must be a string or a list of token ids")
        if not prompt:
            raise OpenAIError("'prompt' must not be empty")
        if d.get("n") not in (None, 1):
            raise OpenAIError("only n=1 is supported")
        # echo+logprobs (legacy OpenAI prompt logprobs) is served: the
        # preprocessor threads prompt_logprobs to the engine, whose
        # verify-scoring path computes logprobs at every prompt position
        return cls(
            model=model,
            prompt=prompt,
            sampling=SamplingFields.from_dict(d),
            stream=bool(d.get("stream", False)),
            echo=bool(d.get("echo", False)),
            speculation=_parse_speculation(d),
        )


@dataclass
class RequestTemplate:
    """Request defaults from a JSON file (reference request_template.rs:18:
    ``{model, temperature, max_completion_tokens}``).  Applied to the raw
    request body BEFORE validation; explicit client fields always win."""

    model: Optional[str] = None
    temperature: Optional[float] = None
    max_completion_tokens: Optional[int] = None

    @classmethod
    def load(cls, path: str) -> "RequestTemplate":
        with open(path) as f:
            d = json.load(f)
        return cls(
            model=d.get("model"),
            temperature=d.get("temperature"),
            max_completion_tokens=d.get("max_completion_tokens"),
        )

    def apply(self, body: Dict[str, Any]) -> Dict[str, Any]:
        if self.model is not None:
            body.setdefault("model", self.model)
        if self.temperature is not None:
            body.setdefault("temperature", self.temperature)
        if self.max_completion_tokens is not None and (
            "max_tokens" not in body and "max_completion_tokens" not in body
        ):
            body["max_tokens"] = self.max_completion_tokens
        return body


@dataclass
class EmbeddingRequest:
    """/v1/embeddings request (reference: protocols/openai/embeddings.rs).

    ``input`` accepts the OpenAI forms: one string, a list of strings, one
    token-id list, or a list of token-id lists; normalized here to
    ``texts`` (strings) or ``token_batches`` (pre-tokenized), exactly one
    of which is non-None.
    """

    model: str
    texts: Optional[List[str]] = None
    token_batches: Optional[List[List[int]]] = None
    encoding_format: str = "float"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EmbeddingRequest":
        model = d.get("model")
        if not isinstance(model, str) or not model:
            raise OpenAIError("'model' must be a non-empty string")
        fmt = d.get("encoding_format", "float")
        if fmt != "float":
            raise OpenAIError("only encoding_format='float' is supported")
        inp = d.get("input")
        texts: Optional[List[str]] = None
        batches: Optional[List[List[int]]] = None
        if isinstance(inp, str):
            texts = [inp]
        elif isinstance(inp, list) and inp:
            if all(isinstance(x, str) for x in inp):
                texts = list(inp)
            elif all(isinstance(x, int) and not isinstance(x, bool) for x in inp):
                batches = [list(inp)]
            elif all(
                isinstance(x, list)
                and x
                and all(isinstance(t, int) and not isinstance(t, bool) for t in x)
                for x in inp
            ):
                batches = [list(x) for x in inp]
        if texts is None and batches is None:
            raise OpenAIError(
                "'input' must be a string, list of strings, token-id list,"
                " or list of token-id lists (non-empty)"
            )
        return cls(model=model, texts=texts, token_batches=batches,
                   encoding_format=fmt)

    @property
    def n_inputs(self) -> int:
        return len(self.texts if self.texts is not None else self.token_batches)


def embedding_response(
    model: str, vectors: List[List[float]], prompt_tokens: int
) -> Dict[str, Any]:
    return {
        "object": "list",
        "model": model,
        "data": [
            {"object": "embedding", "index": i, "embedding": v}
            for i, v in enumerate(vectors)
        ],
        "usage": {"prompt_tokens": prompt_tokens, "total_tokens": prompt_tokens},
    }


# -- response builders -------------------------------------------------------


def new_response_id(kind: str = "chatcmpl") -> str:
    return f"{kind}-{uuid.uuid4().hex}"


def chat_chunk(
    response_id: str,
    model: str,
    created: int,
    *,
    content: Optional[str] = None,
    role: Optional[str] = None,
    finish_reason: Optional[str] = None,
    logprobs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    delta: Dict[str, Any] = {}
    if role is not None:
        delta["role"] = role
    if content is not None:
        delta["content"] = content
    choice: Dict[str, Any] = {
        "index": 0, "delta": delta, "finish_reason": finish_reason
    }
    if logprobs is not None:
        choice["logprobs"] = logprobs
    return {
        "id": response_id,
        "object": "chat.completion.chunk",
        "created": created,
        "model": model,
        "choices": [choice],
    }


def completion_chunk(
    response_id: str,
    model: str,
    created: int,
    *,
    text: str = "",
    finish_reason: Optional[str] = None,
    logprobs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    choice: Dict[str, Any] = {
        "index": 0, "text": text, "finish_reason": finish_reason
    }
    if logprobs is not None:
        choice["logprobs"] = logprobs
    return {
        "id": response_id,
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": [choice],
    }


def usage_block(prompt_tokens: int, completion_tokens: int) -> Dict[str, Any]:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def aggregate_chat(chunks: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold a chunk stream into one chat.completion response (reference
    aggregator, protocols/openai/chat_completions/aggregator.rs)."""
    content: List[str] = []
    lp_content: List[Dict[str, Any]] = []
    finish = None
    rid, model, created, usage = "", "", int(time.time()), None
    for ch in chunks:
        rid = ch.get("id") or rid
        model = ch.get("model") or model
        created = ch.get("created") or created
        usage = ch.get("usage") or usage
        for choice in ch.get("choices") or []:
            delta = choice.get("delta") or {}
            if delta.get("content"):
                content.append(delta["content"])
            lp = choice.get("logprobs")
            if lp and lp.get("content"):
                lp_content.extend(lp["content"])
            if choice.get("finish_reason"):
                finish = choice["finish_reason"]
    out = {
        "id": rid,
        "object": "chat.completion",
        "created": created,
        "model": model,
        "choices": [
            {
                "index": 0,
                "message": {"role": "assistant", "content": "".join(content)},
                "finish_reason": finish or "stop",
            }
        ],
    }
    if lp_content:
        out["choices"][0]["logprobs"] = {"content": lp_content}
    if usage:
        out["usage"] = usage
    return out


def aggregate_completion(chunks: List[Dict[str, Any]]) -> Dict[str, Any]:
    text: List[str] = []
    finish = None
    rid, model, created, usage = "", "", int(time.time()), None
    lp: Optional[Dict[str, List[Any]]] = None
    for ch in chunks:
        rid = ch.get("id") or rid
        model = ch.get("model") or model
        created = ch.get("created") or created
        usage = ch.get("usage") or usage
        for choice in ch.get("choices") or []:
            if choice.get("text"):
                text.append(choice["text"])
            clp = choice.get("logprobs")
            if clp:
                if lp is None:
                    lp = {
                        "tokens": [], "token_logprobs": [],
                        "top_logprobs": [], "text_offset": [],
                    }
                lp["tokens"].extend(clp.get("tokens") or [])
                lp["token_logprobs"].extend(clp.get("token_logprobs") or [])
                tops = clp.get("top_logprobs")
                lp["top_logprobs"].extend(
                    tops if tops is not None
                    else [None] * len(clp.get("tokens") or [])
                )
                lp["text_offset"].extend(clp.get("text_offset") or [])
            if choice.get("finish_reason"):
                finish = choice["finish_reason"]
    out = {
        "id": rid,
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": [
            {"index": 0, "text": "".join(text), "finish_reason": finish or "stop"}
        ],
    }
    if lp is not None:
        out["choices"][0]["logprobs"] = lp
    if usage:
        out["usage"] = usage
    return out


# -- SSE codec ---------------------------------------------------------------

SSE_DONE = b"data: [DONE]\n\n"


def sse_encode(obj: Dict[str, Any]) -> bytes:
    return b"data: " + json.dumps(obj, separators=(",", ":")).encode() + b"\n\n"


def sse_error(message: str) -> bytes:
    return sse_encode({"error": {"message": message, "type": "server_error"}})
