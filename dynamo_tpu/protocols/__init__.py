"""Wire/pipeline protocol types shared across the framework."""

from .common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

__all__ = [
    "FinishReason",
    "LLMEngineOutput",
    "PreprocessedRequest",
    "SamplingOptions",
    "StopConditions",
]
