"""OpenAI-compatible HTTP service: routes + per-model engine registry.

Reference parity: lib/llm/src/http/service/service_v2.rs:51-133 (HttpService
+ state), openai.rs:123,277 (completions / chat completions handlers with
SSE streaming), discovery/model_manager.rs (ModelManager: engines keyed by
model name, added/removed dynamically by the discovery watcher).

An entry's engine is an AsyncEngine taking Context[ChatCompletionRequest]
(or CompletionRequest) and yielding Annotated[openai-chunk-dict] -- usually
``link(OpenAIPreprocessor, Backend, push_router_or_engine)``.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import AsyncIterator, Dict, Optional

from ..protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    EmbeddingRequest,
    OpenAIError,
    SSE_DONE,
    aggregate_chat,
    aggregate_completion,
    embedding_response,
    sse_encode,
    sse_error,
)
from ..runtime import metrics as rtmetrics
from ..runtime import profiling, slo, tracing
from ..runtime.engine import (
    DEADLINE_EXCEEDED_MSG,
    Annotated,
    AsyncEngine,
    Context,
    DeadlineExceededError,
    as_response_stream,
)
from .metrics import ServiceMetrics
from .server import HttpServer, Request, Response

logger = logging.getLogger("dynamo.http.service")


def _bears_token(data: dict) -> bool:
    """True when an OpenAI chunk carries generated text (TTFT/ITL must not
    count the synthetic role-priming chat chunk)."""
    for c in data.get("choices") or []:
        if (c.get("delta") or {}).get("content"):
            return True
        if c.get("text"):
            return True
    return False


def sse_annotation(name: str, comment) -> bytes:
    """Named SSE event for Annotated annotation envelopes."""
    import json as _json

    payload = _json.dumps({"comment": comment or []}, separators=(",", ":"))
    return f"event: {name}\ndata: {payload}\n\n".encode()


class ModelNotFound(OpenAIError):
    def __init__(self, model: str) -> None:
        super().__init__(f"model '{model}' not found", code=404)


class AdmissionControl:
    """Frontend load shedding: bound concurrently-admitted requests.

    Past ``max_inflight`` (0 = unbounded; env ``DYN_HTTP_MAX_INFLIGHT``)
    new requests are rejected with 503 + ``Retry-After`` (env
    ``DYN_HTTP_RETRY_AFTER_S``) *before* any parsing or engine work --
    overload sheds at the cheapest possible point instead of growing an
    unbounded queue whose every entry will miss its SLO anyway."""

    def __init__(
        self,
        max_inflight: Optional[int] = None,
        retry_after_s: Optional[float] = None,
    ) -> None:
        if max_inflight is None:
            max_inflight = int(os.environ.get("DYN_HTTP_MAX_INFLIGHT", "0"))
        if retry_after_s is None:
            retry_after_s = float(os.environ.get("DYN_HTTP_RETRY_AFTER_S", "1"))
        self.max_inflight = max_inflight
        self.retry_after_s = retry_after_s
        self.inflight = 0

    def try_acquire(self) -> bool:
        if 0 < self.max_inflight <= self.inflight:
            return False
        self.inflight += 1
        return True

    def release(self) -> None:
        self.inflight = max(0, self.inflight - 1)


class ModelManager:
    """Engines per model name, per endpoint type (chat / completion)."""

    def __init__(self) -> None:
        self._chat: Dict[str, AsyncEngine] = {}
        self._completion: Dict[str, AsyncEngine] = {}
        self._embedding: Dict[str, AsyncEngine] = {}

    def add_chat_model(self, name: str, engine: AsyncEngine) -> None:
        self._chat[name] = engine

    def add_completion_model(self, name: str, engine: AsyncEngine) -> None:
        self._completion[name] = engine

    def add_embedding_model(self, name: str, engine: AsyncEngine) -> None:
        self._embedding[name] = engine

    def remove_model(self, name: str) -> None:
        self._chat.pop(name, None)
        self._completion.pop(name, None)
        self._embedding.pop(name, None)

    def chat_engine(self, name: str) -> AsyncEngine:
        try:
            return self._chat[name]
        except KeyError:
            raise ModelNotFound(name) from None

    def completion_engine(self, name: str) -> AsyncEngine:
        try:
            return self._completion[name]
        except KeyError:
            raise ModelNotFound(name) from None

    def embedding_engine(self, name: str) -> AsyncEngine:
        try:
            return self._embedding[name]
        except KeyError:
            raise ModelNotFound(name) from None

    def list_models(self) -> list:
        names = sorted(set(self._chat) | set(self._completion) | set(self._embedding))
        return [
            {"id": n, "object": "model", "owned_by": "dynamo-tpu"} for n in names
        ]

    @property
    def is_empty(self) -> bool:
        return not self._chat and not self._completion and not self._embedding


class HttpService:
    """The OpenAI frontend: /v1/chat/completions, /v1/completions,
    /v1/embeddings, /v1/models, /health, /live, /metrics."""

    def __init__(
        self,
        manager: Optional[ModelManager] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics_prefix: str = "dynamo",
        template=None,  # Optional[RequestTemplate]: body defaults
        max_inflight: Optional[int] = None,  # admission bound (None = env)
        default_deadline_s: Optional[float] = None,  # None = env / no deadline
        observatory=None,  # Optional[FleetObservatory]: /fleet surface
    ) -> None:
        self.manager = manager or ModelManager()
        self.observatory = observatory
        self.template = template
        self.admission = AdmissionControl(max_inflight)
        if default_deadline_s is None:
            env_dl = float(os.environ.get("DYN_DEADLINE_S", "0"))
            default_deadline_s = env_dl if env_dl > 0 else None
        self.default_deadline_s = default_deadline_s
        self.metrics = ServiceMetrics(prefix=metrics_prefix)
        self.server = HttpServer(host, port)
        self.server.route("POST", "/v1/chat/completions", self._chat)
        self.server.route("POST", "/v1/completions", self._completions)
        self.server.route("POST", "/v1/embeddings", self._embeddings)
        self.server.route("GET", "/v1/models", self._models)
        self.server.route("GET", "/health", self._health)
        self.server.route("GET", "/live", self._health)
        self.server.route("GET", "/metrics", self._metrics)
        self.server.route_prefix("GET", "/trace/", self._trace)
        # performance-observability plane (runtime/profiling.py): the tick
        # ring + live enable, a bounded jax.profiler device capture, and
        # flight-recorder snapshots for chaos postmortems
        self.server.route("GET", "/profile/ticks", self._profile_ticks)
        self.server.route("POST", "/profile/ticks", self._profile_ticks_post)
        self.server.route("POST", "/profile/device", self._profile_device)
        self.server.route("GET", "/debug/flightrec", self._flightrec_list)
        self.server.route_prefix("GET", "/debug/flightrec/", self._flightrec_get)
        # fleet observatory surface (fleet/observatory.py): cluster summary
        # + the dynamo_fleet_* exposition, 503 until an observatory is wired
        self.server.route("GET", "/fleet", self._fleet)
        self.server.route("GET", "/fleet/metrics", self._fleet_metrics)

    @property
    def address(self) -> tuple:
        return self.server.address

    @property
    def url(self) -> str:
        host, port = self.server.address
        return f"http://{host}:{port}"

    async def start(self) -> None:
        await self.server.start()
        logger.info("http service listening on %s", self.url)

    async def stop(self) -> None:
        await self.server.stop()

    # -- handlers ------------------------------------------------------------

    async def _health(self, req: Request) -> Response:
        return Response.json(
            {"status": "healthy", "models": [m["id"] for m in self.manager.list_models()]}
        )

    async def _models(self, req: Request) -> Response:
        return Response.json({"object": "list", "data": self.manager.list_models()})

    async def _metrics(self, req: Request) -> Response:
        # one scrape surface: the service's private HTTP-layer families plus
        # the process-wide runtime registry (engine, scheduler, KV, disagg,
        # router series) -- two exposition payloads concatenate cleanly as
        # long as family names are disjoint, which the naming scheme
        # guarantees ({prefix}_http_service_* vs dynamo_engine_*/_disagg_*)
        # age stale SLO windows out of the attainment gauges before the
        # scrape (a drained instance must not export incident-era values)
        slo.tracker.refresh_gauges()
        body, content_type = self.metrics.render()
        runtime_body, _ = rtmetrics.render_default()
        return Response(200, {"Content-Type": content_type}, body + runtime_body)

    async def _fleet(self, req: Request) -> Response:
        """GET /fleet: the observatory's cluster summary -- per-worker
        rows, role-aggregated totals, the learned link table, stragglers."""
        if self.observatory is None:
            return Response.json(
                {"error": {"message": "no fleet observatory attached"}}, 503
            )
        return Response.json(self.observatory.summary())

    async def _fleet_metrics(self, req: Request) -> Response:
        """GET /fleet/metrics: only the ``dynamo_fleet_*`` families, for
        scrapers that want cluster rollups without per-process series."""
        if self.observatory is None:
            return Response.json(
                {"error": {"message": "no fleet observatory attached"}}, 503
            )
        body, content_type = self.observatory.render()
        return Response(200, {"Content-Type": content_type}, body)

    async def _trace(self, req: Request) -> Response:
        """GET /trace/{request_id}: this process's spans for one request,
        plus the Chrome-trace export (debug surface; the cross-process
        timeline is the ``dynamo-tpu trace`` CLI's job)."""
        rid = req.path[len("/trace/"):].strip("/")
        if not rid:
            return Response.json(
                {"error": {"message": "usage: /trace/{request_id}"}}, 400
            )
        spans = [s.to_dict() for s in tracing.collector.get(rid)]
        if not spans:
            return Response.json(
                {"error": {"message": f"no spans for request {rid!r}"}}, 404
            )
        return Response.json(
            {
                "request_id": rid,
                "spans": spans,
                "chrome_trace": tracing.chrome_trace(spans),
            }
        )

    async def _profile_ticks(self, req: Request) -> Response:
        """GET /profile/ticks: the tick-phase profiler's ring + aggregate
        summary + a Chrome-trace export merged with this process's request
        spans (one timeline: tick phases next to the span tree)."""
        prof = profiling.profiler
        spans = tracing.collector.dump() if tracing.collector.enabled else []
        return Response.json(
            {
                "enabled": prof.enabled,
                "summary": prof.summary(),
                "ticks": [r.to_dict() for r in prof.records()],
                "chrome_trace": prof.chrome_trace(spans),
            }
        )

    async def _profile_ticks_post(self, req: Request) -> Response:
        """POST /profile/ticks {"enabled": true|false, "clear": bool}:
        arm/disarm tick profiling on a live server (no restart, no env)."""
        body = req.json() or {}
        if not isinstance(body, dict):
            return Response.json(
                {"error": {"message": "body must be a JSON object"}}, 400
            )
        prof = profiling.profiler
        if body.get("clear"):
            prof.clear()
        if "enabled" in body:
            if body["enabled"]:
                prof.enable()
            else:
                prof.disable()
        return Response.json({"enabled": prof.enabled})

    async def _profile_device(self, req: Request) -> Response:
        """POST /profile/device {"duration_s": 1.0, "log_dir": "..."}: a
        bounded-duration ``jax.profiler`` device-trace capture.  Degrades
        gracefully (ok=false + reason) on CPU-only stacks."""
        body = req.json() or {}
        if not isinstance(body, dict):
            return Response.json(
                {"error": {"message": "body must be a JSON object"}}, 400
            )
        try:
            duration = float(body.get("duration_s", 1.0))
        except (TypeError, ValueError):
            return Response.json(
                {"error": {"message": "duration_s must be a number"}}, 400
            )
        result = await profiling.capture_device_trace(
            duration, body.get("log_dir")
        )
        return Response.json(result, 200 if result.get("ok") else 503)

    async def _flightrec_list(self, req: Request) -> Response:
        return Response.json(
            {"snapshots": profiling.flight_recorder.list()}
        )

    async def _flightrec_get(self, req: Request) -> Response:
        snap_id = req.path[len("/debug/flightrec/"):].strip("/")
        snap = profiling.flight_recorder.get(snap_id)
        if snap is None:
            return Response.json(
                {"error": {"message": f"no flight-recorder snapshot {snap_id!r}"}},
                404,
            )
        return Response.json(snap)

    def _shed(self, endpoint: str) -> Response:
        """Admission-control rejection: 503 + Retry-After, counted."""
        self.metrics.sheds.labels(endpoint).inc()
        if slo.tracker.enabled:
            slo.tracker.record_shed()
        resp = Response.json(
            {
                "error": {
                    "message": "server overloaded, retry later",
                    "type": "overloaded_error",
                }
            },
            503,
        )
        resp.headers["Retry-After"] = (
            f"{self.admission.retry_after_s:g}"
        )
        return resp

    def _deadline_expired(self, request: Context, rsp=None) -> str:
        """One deadline-expiry bookkeeping site for every 504 path: SLO
        violation with cause=deadline, a flight-recorder snapshot, and the
        snapshot id stamped onto the request span.  Returns the id the
        error frame/body carries (postmortems start from it)."""
        # record the violation BEFORE snapshotting: the dump must carry
        # its own trigger in slo_violations
        if slo.tracker.enabled:
            slo.tracker.record_deadline(request.id)
        fid = profiling.flight_recorder.snapshot(
            "deadline_expired", request_id=request.id
        )
        if rsp is not None:
            rsp.set(deadline_expired=True, flightrec_id=fid)
        return fid

    @staticmethod
    def _deadline_body(fid: str) -> dict:
        return {
            "error": {
                "message": DEADLINE_EXCEEDED_MSG,
                "type": "timeout_error",
                "flightrec": fid,
            }
        }

    def _request_deadline(self, req: Request) -> Optional[float]:
        """Per-request deadline budget in seconds: the
        ``X-Request-Deadline-S`` header, else the service default
        (``DYN_DEADLINE_S``), else None (no deadline)."""
        raw = req.headers.get("x-request-deadline-s")
        if raw:
            try:
                return float(raw)
            except ValueError:
                logger.warning("ignoring bad X-Request-Deadline-S %r", raw)
        return self.default_deadline_s

    def _count_rejected(self, body: Optional[dict], endpoint: str) -> None:
        """Count a rejected request, labelling with the model name only when
        it is actually served: client-supplied junk names must not mint
        unbounded label series."""
        raw = body.get("model") if body else None
        known = {m["id"] for m in self.manager.list_models()}
        self.metrics.requests_total.labels(
            raw if raw in known else "unknown", endpoint, "rejected"
        ).inc()

    async def _chat(self, req: Request) -> Response:
        return await self._serve(req, chat=True)

    async def _completions(self, req: Request) -> Response:
        return await self._serve(req, chat=False)

    async def _embeddings(self, req: Request) -> Response:
        """/v1/embeddings: single aggregated response, no streaming
        (reference openai.rs:212)."""
        endpoint = "embeddings"
        if not self.admission.try_acquire():
            return self._shed(endpoint)
        try:
            body = req.json()
            if not isinstance(body, dict):
                raise OpenAIError("request body must be a JSON object")
            if self.template is not None and self.template.model is not None:
                body.setdefault("model", self.template.model)
            parsed = EmbeddingRequest.from_dict(body)
            engine = self.manager.embedding_engine(parsed.model)
        except OpenAIError as e:
            self.admission.release()
            self._count_rejected(body if isinstance(body, dict) else None, endpoint)
            return Response.json(e.to_body(), e.code)
        except BaseException:
            self.admission.release()
            raise

        request = Context.new(parsed)
        guard = self.metrics.guard(parsed.model, endpoint, request.id)
        guard.on_finish = self.admission.release
        try:
            with guard, tracing.span(
                "http.request", request.id, component="http",
                bind=True, endpoint=endpoint, model=parsed.model,
            ):
                stream = await as_response_stream(engine, request)
                vectors, prompt_tokens = None, 0
                async for item in stream:
                    if not isinstance(item, Annotated):
                        item = Annotated.from_data(item)
                    if item.is_error():
                        raise RuntimeError(
                            item.error_message() or "engine error"
                        )
                    data = item.data or {}
                    if "embeddings" in data:
                        vectors = data["embeddings"]
                        prompt_tokens = int(data.get("prompt_tokens", 0))
                if vectors is None:
                    raise RuntimeError("embedding engine returned no vectors")
                guard.mark_ok()
                resp = Response.json(
                    embedding_response(parsed.model, vectors, prompt_tokens)
                )
                resp.headers.setdefault("X-Request-Id", request.id)
                return resp
        except OpenAIError as e:
            # the guard's __exit__ already finished it with status=error
            return Response.json(e.to_body(), e.code)
        except Exception as e:
            logger.exception("embedding request failed")
            return Response.json(
                {"error": {"message": str(e), "type": "server_error"}}, 500
            )

    async def _serve(self, req: Request, chat: bool) -> Response:
        endpoint = "chat_completions" if chat else "completions"
        # shed BEFORE parsing: overload rejection must stay O(1)
        if not self.admission.try_acquire():
            return self._shed(endpoint)
        try:
            body = req.json()
            if not isinstance(body, dict):
                raise OpenAIError("request body must be a JSON object")
            if self.template is not None:
                body = self.template.apply(body)
            parsed = (
                ChatCompletionRequest.from_dict(body)
                if chat
                else CompletionRequest.from_dict(body)
            )
            engine = (
                self.manager.chat_engine(parsed.model)
                if chat
                else self.manager.completion_engine(parsed.model)
            )
        except OpenAIError as e:
            self.admission.release()
            self._count_rejected(body if isinstance(body, dict) else None, endpoint)
            return Response.json(e.to_body(), e.code)
        except BaseException:
            self.admission.release()
            raise

        request = Context.new(parsed)
        guard = self.metrics.guard(parsed.model, endpoint, request.id)
        # Deadline budget: armed here at the edge, it rides the codec
        # headers hop by hop; the local watchdog kills the request context
        # at expiry so even an engine that never checks terminates.
        deadline_s = self._request_deadline(req)
        watchdog = None
        if deadline_s is not None:
            request.ctx.set_deadline(deadline_s)
            watchdog = asyncio.get_running_loop().call_later(
                max(deadline_s, 0.0), request.ctx.kill
            )

        def on_finish() -> None:
            self.admission.release()
            if watchdog is not None:
                watchdog.cancel()

        guard.on_finish = on_finish
        # Root span of the request's trace, bound to the request id so the
        # egress hop (and, through the propagated context, every remote
        # component's spans) links under it.  Manually paired: it closes
        # when the response body completes, covering the full stream.
        rsp = tracing.span(
            "http.request",
            request.id,
            component="http",
            bind=True,
            endpoint=endpoint,
            model=parsed.model,
        )
        rsp.__enter__()
        try:
            stream = await as_response_stream(engine, request)
        except DeadlineExceededError as e:
            guard.mark_error()
            guard.finish()
            fid = self._deadline_expired(request, rsp)
            rsp.__exit__(type(e), e, e.__traceback__)
            return Response.json(self._deadline_body(fid), 504)
        except Exception as e:
            logger.exception("engine dispatch failed")
            guard.mark_error()
            guard.finish()
            rsp.__exit__(type(e), e, e.__traceback__)
            return Response.json(
                {"error": {"message": f"engine error: {e}", "type": "server_error"}},
                503,
            )

        if parsed.stream:
            started = [False]
            resp = Response.sse(
                self._sse_body(stream, request, guard, rsp, started)
            )

            def on_close() -> None:
                # the server calls this once the connection is done with the
                # response; a body generator that was never started (the
                # client vanished before the first header byte) never runs
                # its cleanup, so this is the only path that can kill the
                # engine-side request and release the inflight gauge
                if not started[0]:
                    request.ctx.kill()
                    guard.mark_error()
                    guard.finish()
                    rsp.set(abandoned=True)
                    rsp.__exit__(None, None, None)

            resp.on_close = on_close
        else:
            resp = await self._aggregate_body(stream, request, guard, chat, rsp)
        # the trace handle: clients retrieve the span tree via
        # GET /trace/{request_id} or the dynamo-tpu trace CLI
        resp.headers.setdefault("X-Request-Id", request.id)
        return resp

    async def _sse_body(
        self, stream, request: Context, guard, rsp=None, started=None
    ) -> AsyncIterator[bytes]:
        if started is not None:
            started[0] = True
        try:
            with guard:
                async for item in stream:
                    if not isinstance(item, Annotated):
                        item = Annotated.from_data(item)
                    if item.is_error():
                        guard.mark_error()
                        if rsp is not None:
                            rsp.set(error=True)
                        yield sse_error(item.error_message() or "engine error")
                        return
                    if item.data is not None:
                        if _bears_token(item.data):
                            guard.token()
                        yield sse_encode(item.data)
                    elif item.event is not None:
                        # annotation envelope (formatted_prompt / token_ids
                        # ...): surface as a named SSE event, reference
                        # openai.rs shape
                        yield sse_annotation(item.event, item.comment)
                if request.ctx.deadline_expired():
                    # the watchdog killed the request: the stream ended
                    # because the budget ran out, not because it finished
                    guard.mark_error()
                    fid = self._deadline_expired(request, rsp)
                    yield sse_error(
                        f"{DEADLINE_EXCEEDED_MSG} [flightrec:{fid}]"
                    )
                    return
                guard.mark_ok()
                yield SSE_DONE
        except (asyncio.CancelledError, GeneratorExit):
            # client went away mid-stream (handler cancelled, or the writer
            # failed and the generator was aclosed): kill the engine-side
            # request instead of decoding for a dead connection
            request.ctx.kill()
            if rsp is not None:
                rsp.set(abandoned=True)
            raise
        except Exception as e:
            # the guard's __exit__ already finished it with status=error
            logger.exception("stream failed")
            if rsp is not None:
                rsp.set(error=True)
            yield sse_error(str(e))
        finally:
            if rsp is not None:
                rsp.__exit__(None, None, None)

    async def _aggregate_body(
        self, stream, request: Context, guard, chat: bool, rsp=None
    ) -> Response:
        chunks = []

        def timeout_response() -> Response:
            guard.mark_error()
            fid = self._deadline_expired(request, rsp)
            return Response.json(self._deadline_body(fid), 504)

        try:
            with guard:
                async for item in stream:
                    if not isinstance(item, Annotated):
                        item = Annotated.from_data(item)
                    if item.is_error():
                        msg = item.error_message() or ""
                        if msg.startswith(DEADLINE_EXCEEDED_MSG):
                            return timeout_response()
                        guard.mark_error()
                        if rsp is not None:
                            rsp.set(error=True)
                        return Response.json(
                            {
                                "error": {
                                    "message": item.error_message(),
                                    "type": "server_error",
                                }
                            },
                            500,
                        )
                    if item.data is not None:
                        if _bears_token(item.data):
                            guard.token()
                        chunks.append(item.data)
                if request.ctx.deadline_expired():
                    # watchdog-killed: the stream ended on budget expiry
                    return timeout_response()
                guard.mark_ok()
                agg = (
                    aggregate_chat(chunks) if chat
                    else aggregate_completion(chunks)
                )
                return Response.json(agg)
        except DeadlineExceededError:
            return timeout_response()
        except Exception as e:
            # the guard's __exit__ already finished it with status=error
            logger.exception("aggregation failed")
            if rsp is not None:
                rsp.set(error=True)
            return Response.json(
                {"error": {"message": str(e), "type": "server_error"}}, 500
            )
        finally:
            if rsp is not None:
                rsp.__exit__(None, None, None)
