"""OpenAI-compatible HTTP service: routes + per-model engine registry.

Reference parity: lib/llm/src/http/service/service_v2.rs:51-133 (HttpService
+ state), openai.rs:123,277 (completions / chat completions handlers with
SSE streaming), discovery/model_manager.rs (ModelManager: engines keyed by
model name, added/removed dynamically by the discovery watcher).

An entry's engine is an AsyncEngine taking Context[ChatCompletionRequest]
(or CompletionRequest) and yielding Annotated[openai-chunk-dict] -- usually
``link(OpenAIPreprocessor, Backend, push_router_or_engine)``.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, Dict, Optional

from ..protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    EmbeddingRequest,
    OpenAIError,
    SSE_DONE,
    aggregate_chat,
    aggregate_completion,
    embedding_response,
    sse_encode,
    sse_error,
)
from ..runtime.engine import Annotated, AsyncEngine, Context, as_response_stream
from .metrics import ServiceMetrics
from .server import HttpServer, Request, Response

logger = logging.getLogger("dynamo.http.service")


def _bears_token(data: dict) -> bool:
    """True when an OpenAI chunk carries generated text (TTFT/ITL must not
    count the synthetic role-priming chat chunk)."""
    for c in data.get("choices") or []:
        if (c.get("delta") or {}).get("content"):
            return True
        if c.get("text"):
            return True
    return False


def sse_annotation(name: str, comment) -> bytes:
    """Named SSE event for Annotated annotation envelopes."""
    import json as _json

    payload = _json.dumps({"comment": comment or []}, separators=(",", ":"))
    return f"event: {name}\ndata: {payload}\n\n".encode()


class ModelNotFound(OpenAIError):
    def __init__(self, model: str) -> None:
        super().__init__(f"model '{model}' not found", code=404)


class ModelManager:
    """Engines per model name, per endpoint type (chat / completion)."""

    def __init__(self) -> None:
        self._chat: Dict[str, AsyncEngine] = {}
        self._completion: Dict[str, AsyncEngine] = {}
        self._embedding: Dict[str, AsyncEngine] = {}

    def add_chat_model(self, name: str, engine: AsyncEngine) -> None:
        self._chat[name] = engine

    def add_completion_model(self, name: str, engine: AsyncEngine) -> None:
        self._completion[name] = engine

    def add_embedding_model(self, name: str, engine: AsyncEngine) -> None:
        self._embedding[name] = engine

    def remove_model(self, name: str) -> None:
        self._chat.pop(name, None)
        self._completion.pop(name, None)
        self._embedding.pop(name, None)

    def chat_engine(self, name: str) -> AsyncEngine:
        try:
            return self._chat[name]
        except KeyError:
            raise ModelNotFound(name) from None

    def completion_engine(self, name: str) -> AsyncEngine:
        try:
            return self._completion[name]
        except KeyError:
            raise ModelNotFound(name) from None

    def embedding_engine(self, name: str) -> AsyncEngine:
        try:
            return self._embedding[name]
        except KeyError:
            raise ModelNotFound(name) from None

    def list_models(self) -> list:
        names = sorted(set(self._chat) | set(self._completion) | set(self._embedding))
        return [
            {"id": n, "object": "model", "owned_by": "dynamo-tpu"} for n in names
        ]

    @property
    def is_empty(self) -> bool:
        return not self._chat and not self._completion and not self._embedding


class HttpService:
    """The OpenAI frontend: /v1/chat/completions, /v1/completions,
    /v1/embeddings, /v1/models, /health, /live, /metrics."""

    def __init__(
        self,
        manager: Optional[ModelManager] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics_prefix: str = "dynamo",
        template=None,  # Optional[RequestTemplate]: body defaults
    ) -> None:
        self.manager = manager or ModelManager()
        self.template = template
        self.metrics = ServiceMetrics(prefix=metrics_prefix)
        self.server = HttpServer(host, port)
        self.server.route("POST", "/v1/chat/completions", self._chat)
        self.server.route("POST", "/v1/completions", self._completions)
        self.server.route("POST", "/v1/embeddings", self._embeddings)
        self.server.route("GET", "/v1/models", self._models)
        self.server.route("GET", "/health", self._health)
        self.server.route("GET", "/live", self._health)
        self.server.route("GET", "/metrics", self._metrics)

    @property
    def address(self) -> tuple:
        return self.server.address

    @property
    def url(self) -> str:
        host, port = self.server.address
        return f"http://{host}:{port}"

    async def start(self) -> None:
        await self.server.start()
        logger.info("http service listening on %s", self.url)

    async def stop(self) -> None:
        await self.server.stop()

    # -- handlers ------------------------------------------------------------

    async def _health(self, req: Request) -> Response:
        return Response.json(
            {"status": "healthy", "models": [m["id"] for m in self.manager.list_models()]}
        )

    async def _models(self, req: Request) -> Response:
        return Response.json({"object": "list", "data": self.manager.list_models()})

    async def _metrics(self, req: Request) -> Response:
        body, content_type = self.metrics.render()
        return Response(200, {"Content-Type": content_type}, body)

    def _count_rejected(self, body: Optional[dict], endpoint: str) -> None:
        """Count a rejected request, labelling with the model name only when
        it is actually served: client-supplied junk names must not mint
        unbounded label series."""
        raw = body.get("model") if body else None
        known = {m["id"] for m in self.manager.list_models()}
        self.metrics.requests_total.labels(
            raw if raw in known else "unknown", endpoint, "rejected"
        ).inc()

    async def _chat(self, req: Request) -> Response:
        return await self._serve(req, chat=True)

    async def _completions(self, req: Request) -> Response:
        return await self._serve(req, chat=False)

    async def _embeddings(self, req: Request) -> Response:
        """/v1/embeddings: single aggregated response, no streaming
        (reference openai.rs:212)."""
        endpoint = "embeddings"
        try:
            body = req.json()
            if not isinstance(body, dict):
                raise OpenAIError("request body must be a JSON object")
            if self.template is not None and self.template.model is not None:
                body.setdefault("model", self.template.model)
            parsed = EmbeddingRequest.from_dict(body)
            engine = self.manager.embedding_engine(parsed.model)
        except OpenAIError as e:
            self._count_rejected(body if isinstance(body, dict) else None, endpoint)
            return Response.json(e.to_body(), e.code)

        guard = self.metrics.guard(parsed.model, endpoint)
        request = Context.new(parsed)
        try:
            stream = await as_response_stream(engine, request)
            vectors, prompt_tokens = None, 0
            async for item in stream:
                if not isinstance(item, Annotated):
                    item = Annotated.from_data(item)
                if item.is_error():
                    raise RuntimeError(item.error_message() or "engine error")
                data = item.data or {}
                if "embeddings" in data:
                    vectors = data["embeddings"]
                    prompt_tokens = int(data.get("prompt_tokens", 0))
            if vectors is None:
                raise RuntimeError("embedding engine returned no vectors")
            guard.mark_ok()
            return Response.json(
                embedding_response(parsed.model, vectors, prompt_tokens)
            )
        except OpenAIError as e:
            guard.mark_error()
            return Response.json(e.to_body(), e.code)
        except Exception as e:
            logger.exception("embedding request failed")
            guard.mark_error()
            return Response.json(
                {"error": {"message": str(e), "type": "server_error"}}, 500
            )
        finally:
            guard.finish()

    async def _serve(self, req: Request, chat: bool) -> Response:
        endpoint = "chat_completions" if chat else "completions"
        try:
            body = req.json()
            if not isinstance(body, dict):
                raise OpenAIError("request body must be a JSON object")
            if self.template is not None:
                body = self.template.apply(body)
            parsed = (
                ChatCompletionRequest.from_dict(body)
                if chat
                else CompletionRequest.from_dict(body)
            )
            engine = (
                self.manager.chat_engine(parsed.model)
                if chat
                else self.manager.completion_engine(parsed.model)
            )
        except OpenAIError as e:
            self._count_rejected(body if isinstance(body, dict) else None, endpoint)
            return Response.json(e.to_body(), e.code)

        guard = self.metrics.guard(parsed.model, endpoint)
        request = Context.new(parsed)
        try:
            stream = await as_response_stream(engine, request)
        except Exception as e:
            logger.exception("engine dispatch failed")
            guard.mark_error()
            guard.finish()
            return Response.json(
                {"error": {"message": f"engine error: {e}", "type": "server_error"}},
                503,
            )

        if parsed.stream:
            return Response.sse(self._sse_body(stream, request, guard))
        return await self._aggregate_body(stream, guard, chat)

    async def _sse_body(
        self, stream, request: Context, guard
    ) -> AsyncIterator[bytes]:
        try:
            async for item in stream:
                if not isinstance(item, Annotated):
                    item = Annotated.from_data(item)
                if item.is_error():
                    guard.mark_error()
                    yield sse_error(item.error_message() or "engine error")
                    return
                if item.data is not None:
                    if _bears_token(item.data):
                        guard.token()
                    yield sse_encode(item.data)
                elif item.event is not None:
                    # annotation envelope (formatted_prompt / token_ids ...):
                    # surface as a named SSE event, reference openai.rs shape
                    yield sse_annotation(item.event, item.comment)
            guard.mark_ok()
            yield SSE_DONE
        except (asyncio.CancelledError, GeneratorExit):
            # client went away mid-stream (handler cancelled, or the writer
            # failed and the generator was aclosed): kill the engine-side
            # request instead of decoding for a dead connection
            request.ctx.kill()
            raise
        except Exception as e:
            logger.exception("stream failed")
            guard.mark_error()
            yield sse_error(str(e))
        finally:
            guard.finish()

    async def _aggregate_body(self, stream, guard, chat: bool) -> Response:
        chunks = []
        try:
            async for item in stream:
                if not isinstance(item, Annotated):
                    item = Annotated.from_data(item)
                if item.is_error():
                    guard.mark_error()
                    guard.finish()
                    return Response.json(
                        {
                            "error": {
                                "message": item.error_message(),
                                "type": "server_error",
                            }
                        },
                        500,
                    )
                if item.data is not None:
                    if _bears_token(item.data):
                        guard.token()
                    chunks.append(item.data)
            guard.mark_ok()
            agg = aggregate_chat(chunks) if chat else aggregate_completion(chunks)
            return Response.json(agg)
        except Exception as e:
            logger.exception("aggregation failed")
            guard.mark_error()
            return Response.json(
                {"error": {"message": str(e), "type": "server_error"}}, 500
            )
        finally:
            guard.finish()
