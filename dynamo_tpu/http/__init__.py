from .server import HttpServer, Request, Response
from .service import HttpService, ModelManager

__all__ = ["HttpServer", "HttpService", "ModelManager", "Request", "Response"]
