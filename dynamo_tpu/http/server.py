"""Minimal asyncio HTTP/1.1 server: the transport under the OpenAI service.

The reference rides axum/hyper (lib/llm/src/http/service/service_v2.rs);
here the service speaks HTTP directly over asyncio streams -- no web
framework is available in the image, and the surface is small: JSON request
bodies, JSON responses, and SSE streaming with chunked transfer encoding.

Supports keep-alive, Content-Length bodies, and per-route async handlers
returning either a full :class:`Response` or a streaming one (async
iterator body -> ``Transfer-Encoding: chunked``).
"""

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import dataclass, field
from typing import AsyncIterator, Awaitable, Callable, Dict, Optional, Tuple, Union

logger = logging.getLogger("dynamo.http")

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 64 * 1024 * 1024

_STATUS_TEXT = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    413: "Payload Too Large",
    422: "Unprocessable Entity",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}


@dataclass
class Request:
    method: str
    path: str
    headers: Dict[str, str]
    body: bytes

    def json(self):
        try:
            return json.loads(self.body) if self.body else None
        except json.JSONDecodeError as e:
            raise BadRequest(f"invalid JSON body: {e}") from e


@dataclass
class Response:
    status: int = 200
    headers: Dict[str, str] = field(default_factory=dict)
    body: Union[bytes, AsyncIterator[bytes]] = b""
    # invoked exactly once when the connection is done with this response,
    # even when a streaming body was NEVER started (header write failed
    # because the client vanished): finalizing a never-started async
    # generator does not run its body (PEP 525), so cleanup that lives in
    # the generator needs this out-of-band hook
    on_close: Optional[Callable[[], None]] = None

    @classmethod
    def json(cls, obj, status: int = 200) -> "Response":
        return cls(
            status=status,
            headers={"Content-Type": "application/json"},
            body=json.dumps(obj).encode(),
        )

    @classmethod
    def sse(cls, gen: AsyncIterator[bytes]) -> "Response":
        return cls(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            },
            body=gen,
        )


class BadRequest(ValueError):
    pass


Handler = Callable[[Request], Awaitable[Response]]


class HttpServer:
    """Route-table HTTP server.  Routes are ``(METHOD, path) -> handler``;
    a fallback handler (if set) sees everything unmatched."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self.routes: Dict[Tuple[str, str], Handler] = {}
        # (METHOD, path_prefix, handler): matched after exact routes, for
        # path-parameter endpoints like /trace/{request_id}
        self.prefix_routes: list = []
        self.fallback: Optional[Handler] = None
        self._server: Optional[asyncio.base_events.Server] = None
        # live connections; stop() force-closes them -- Python 3.12+
        # wait_closed() otherwise blocks until every handler returns
        self._writers: set = set()

    def route(self, method: str, path: str, handler: Handler) -> None:
        self.routes[(method.upper(), path)] = handler

    def route_prefix(self, method: str, prefix: str, handler: Handler) -> None:
        """Route every path under ``prefix`` (the trailing path segment is
        the handler's to parse from ``Request.path``)."""
        self.prefix_routes.append((method.upper(), prefix, handler))

    @property
    def address(self) -> Tuple[str, int]:
        assert self._server is not None, "server not started"
        sock = self._server.sockets[0]
        host, port = sock.getsockname()[:2]
        return host, port

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, limit=MAX_HEADER_BYTES
        )
        self.port = self.address[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for w in list(self._writers):
                try:
                    w.close()
                except Exception:
                    logger.debug("closing live connection failed", exc_info=True)
            await self._server.wait_closed()
            self._server = None

    # -- connection handling -------------------------------------------------

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._writers.add(writer)
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except BadRequest as e:
                    # malformed framing: answer 400 and drop the connection
                    # (the stream position is no longer trustworthy)
                    await self._write_response(
                        writer,
                        Response.json({"error": {"message": str(e)}}, 400),
                        keep_alive=False,
                    )
                    break
                if req is None:
                    break
                keep_alive = (
                    req.headers.get("connection", "keep-alive").lower()
                    != "close"
                )
                try:
                    resp = await self._dispatch(req)
                except BadRequest as e:
                    resp = Response.json({"error": {"message": str(e)}}, 400)
                except Exception:
                    logger.exception("handler failed for %s %s", req.method, req.path)
                    resp = Response.json(
                        {"error": {"message": "internal server error"}}, 500
                    )
                await self._write_response(writer, resp, keep_alive)
                if not keep_alive:
                    break
        except (
            asyncio.IncompleteReadError,
            ConnectionResetError,
            BrokenPipeError,
            asyncio.LimitOverrunError,
        ):
            pass
        except Exception:
            logger.exception("connection handler error")
        finally:
            self._writers.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                # peer vanished mid-teardown: routine, but keep a trace
                logger.debug("connection teardown failed", exc_info=True)

    async def _read_request(
        self, reader: asyncio.StreamReader
    ) -> Optional[Request]:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except asyncio.IncompleteReadError:
            return None
        except asyncio.LimitOverrunError:
            # the StreamReader limit (== MAX_HEADER_BYTES) tripped first
            raise BadRequest("headers too large") from None
        lines = head.decode("latin-1").split("\r\n")
        parts = lines[0].split(" ")
        if len(parts) != 3:
            raise BadRequest(f"malformed request line: {lines[0]!r}")
        method, target, _version = parts
        path = target.split("?", 1)[0]
        headers: Dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            k, _, v = line.partition(":")
            headers[k.strip().lower()] = v.strip()
        try:
            length = int(headers.get("content-length", "0") or 0)
        except ValueError:
            raise BadRequest("invalid Content-Length") from None
        if length > MAX_BODY_BYTES:
            raise BadRequest("body too large")
        body = await reader.readexactly(length) if length else b""
        return Request(method=method.upper(), path=path, headers=headers, body=body)

    async def _dispatch(self, req: Request) -> Response:
        handler = self.routes.get((req.method, req.path))
        if handler is None:
            for method, prefix, h in self.prefix_routes:
                if method == req.method and req.path.startswith(prefix):
                    handler = h
                    break
        if handler is None and self.fallback is not None:
            handler = self.fallback
        if handler is None:
            if any(p == req.path for (_m, p) in self.routes):
                return Response.json(
                    {"error": {"message": "method not allowed"}}, 405
                )
            return Response.json({"error": {"message": "not found"}}, 404)
        return await handler(req)

    async def _write_response(
        self, writer: asyncio.StreamWriter, resp: Response, keep_alive: bool
    ) -> None:
        try:
            await self._write_response_inner(writer, resp, keep_alive)
        finally:
            if resp.on_close is not None:
                try:
                    resp.on_close()
                except Exception:
                    logger.debug("response on_close failed", exc_info=True)

    async def _write_response_inner(
        self, writer: asyncio.StreamWriter, resp: Response, keep_alive: bool
    ) -> None:
        status_line = (
            f"HTTP/1.1 {resp.status} {_STATUS_TEXT.get(resp.status, 'Unknown')}\r\n"
        )
        headers = dict(resp.headers)
        headers.setdefault("Connection", "keep-alive" if keep_alive else "close")
        if isinstance(resp.body, bytes):
            headers["Content-Length"] = str(len(resp.body))
            head = status_line + "".join(
                f"{k}: {v}\r\n" for k, v in headers.items()
            )
            writer.write(head.encode("latin-1") + b"\r\n" + resp.body)
            await writer.drain()
            return
        # streaming body -> chunked transfer encoding
        headers["Transfer-Encoding"] = "chunked"
        head = status_line + "".join(f"{k}: {v}\r\n" for k, v in headers.items())
        writer.write(head.encode("latin-1") + b"\r\n")
        await writer.drain()
        try:
            async for chunk in resp.body:
                if not chunk:
                    continue
                writer.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                await writer.drain()
        finally:
            aclose = getattr(resp.body, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:
                    # the stream generator's cleanup failed AFTER its last
                    # chunk; the response is intact but leaks deserve a trace
                    logger.debug("stream body aclose() failed", exc_info=True)
        writer.write(b"0\r\n\r\n")
        await writer.drain()
