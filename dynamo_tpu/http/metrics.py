"""HTTP service Prometheus metrics.

Reference parity: lib/llm/src/http/service/metrics.rs:27-188,402-460 -- same
metric family names (``{prefix}_http_service_requests_total``,
``_inflight_requests``, ``_request_duration_seconds``,
``_time_to_first_token_seconds``, ``_inter_token_latency_seconds``) so
existing dashboards translate directly.  Each service owns a private
registry (tests run many services per process); families are minted
through :class:`~dynamo_tpu.runtime.metrics.MetricsRegistry` (dynalint
DT007 keeps inline prometheus_client construction out of the codebase).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ..runtime import slo
from ..runtime.metrics import MetricsRegistry

_DURATION_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
_TTFT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
_ITL_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
)


class ServiceMetrics:
    def __init__(
        self,
        prefix: str = "dynamo",
        registry: Optional[MetricsRegistry] = None,
    ) -> None:
        self._metrics = registry or MetricsRegistry()
        self.registry = self._metrics.registry
        # the service's private registry carries the same worker identity
        # the runtime registry renders with (satellite: multi-worker
        # Prometheus scrapes must not collide on identical series)
        from ..runtime import metrics as rtm

        identity = rtm.worker_identity()
        if identity and not self._metrics.default_labels:
            self._metrics.set_default_labels(**identity)
        self.requests_total = self._metrics.counter(
            f"{prefix}_http_service_requests",
            "Total HTTP service requests",
            ["model", "endpoint", "status"],
        )
        self.inflight = self._metrics.gauge(
            f"{prefix}_http_service_inflight_requests",
            "Requests currently being processed",
            ["model", "endpoint"],
        )
        self.duration = self._metrics.histogram(
            f"{prefix}_http_service_request_duration_seconds",
            "End-to-end request duration",
            ["model", "endpoint"],
            buckets=_DURATION_BUCKETS,
        )
        self.ttft = self._metrics.histogram(
            f"{prefix}_http_service_time_to_first_token_seconds",
            "Time to first generated token",
            ["model"],
            buckets=_TTFT_BUCKETS,
        )
        self.itl = self._metrics.histogram(
            f"{prefix}_http_service_inter_token_latency_seconds",
            "Latency between consecutive tokens",
            ["model"],
            buckets=_ITL_BUCKETS,
        )
        self.sheds = self._metrics.counter(
            f"{prefix}_http_service_sheds",
            "Requests rejected 503 by admission control (inflight bound)",
            ["endpoint"],
        )

    def guard(
        self, model: str, endpoint: str, request_id: str = ""
    ) -> "InflightGuard":
        return InflightGuard(self, model, endpoint, request_id)

    def render(self) -> tuple[bytes, str]:
        return self._metrics.render()


class InflightGuard:
    """Tracks one request: inflight gauge, duration, TTFT, ITL, final status.

    Reference: metrics.rs InflightGuard -- created at admission, marked
    ok/error at completion; finishing without a mark counts as error.

    Use as a context manager: ``__exit__`` always calls :meth:`finish`
    (marking error when an exception escaped), so an abandoned stream --
    the consumer's generator torn down by cancel/GeneratorExit -- can no
    longer leak the inflight gauge.  ``finish`` is idempotent: belt-and-
    suspenders call sites cannot double-decrement.

    With the SLO plane armed (``DYN_SLO``), the same stamps feed the
    attainment tracker: TTFT at the first token, ITL per subsequent
    token, E2E at finish -- one recording site instead of parallel
    plumbing (``request_id`` links a TTFT miss to the engine's
    queue-vs-service decomposition).
    """

    def __init__(
        self,
        metrics: ServiceMetrics,
        model: str,
        endpoint: str,
        request_id: str = "",
    ) -> None:
        self.m = metrics
        self.model = model
        self.endpoint = endpoint
        self.request_id = request_id
        self.start = time.monotonic()
        self._last_token: Optional[float] = None
        self._status: Optional[str] = None
        self._finished = False
        # invoked exactly once from finish(): the admission controller's
        # release (and the deadline watchdog's cancel) piggyback on the one
        # completion point every request path already hits
        self.on_finish: Optional[callable] = None
        metrics.inflight.labels(model, endpoint).inc()

    def __enter__(self) -> "InflightGuard":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None and self._status is None:
            self._status = "error"
        self.finish()
        return False

    def token(self) -> None:
        now = time.monotonic()
        if self._last_token is None:
            self.m.ttft.labels(self.model).observe(now - self.start)
            if slo.tracker.enabled:
                slo.tracker.record_ttft(self.request_id, now - self.start)
        else:
            self.m.itl.labels(self.model).observe(now - self._last_token)
            if slo.tracker.enabled:
                slo.tracker.record_itl(now - self._last_token)
        self._last_token = now

    def mark_ok(self) -> None:
        self._status = "success"

    def mark_error(self) -> None:
        self._status = "error"

    def finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        self.m.inflight.labels(self.model, self.endpoint).dec()
        elapsed = time.monotonic() - self.start
        self.m.duration.labels(self.model, self.endpoint).observe(elapsed)
        if slo.tracker.enabled and self._status == "success":
            # errored/deadline requests record their violation at the
            # classifying site (cause=deadline/shed), not as a plain miss
            slo.tracker.record_e2e(self.request_id, elapsed)
        self.m.requests_total.labels(
            self.model, self.endpoint, self._status or "error"
        ).inc()
        if self.on_finish is not None:
            try:
                self.on_finish()
            except Exception:
                logging.getLogger("dynamo.http.metrics").exception(
                    "guard on_finish callback failed"
                )
