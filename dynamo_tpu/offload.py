"""Multi-tier KV offload plane: G2 (host RAM), G3 (disk), and G4 (the
fleet-shared remote store) behind the G1 page pool, coordinated by
:class:`KVOffloadEngine`.

Reference parity: lib/llm/src/block_manager offload (offload.rs:76-80 --
eviction cascades G1 -> G2 -> G3, lookups promote back up) plus the
offload/onboard engines that move blocks between tiers asynchronously.
The TPU build keeps the same cascade but moves data on XLA's terms (see
engine/engine.py): an evicted block's pages are *sliced on device* before
the free-list reclaims them (device program order guarantees the slice
reads pre-reuse contents), the transfer rides ``copy_to_host_async``, and
the blocking materialize + every tier put/get runs on the offload
engine's dedicated thread -- never the event loop, never the engine
executor that drives device ticks.

A block is stored as ``(blob, meta)``: blob is the raw page content
``[L, 2, pages_per_block, page, Hkv, D]``, meta carries the router-facing
identity (block_hash, parent_sequence_hash, position) so an onboarded
block re-registers and re-publishes exactly as it first did.

Beyond block offload, the engine parks whole preempted sequences here:
swap-based preemption snapshots the victim lane's KV into a request-keyed
swap record and restores it through the chunked scatter path on resume,
instead of burning prefill FLOPs recomputing KV that already existed
(FlowKV, arXiv:2504.03775).  ``DYN_KV_OFFLOAD`` arms the whole plane from
the environment; unset and unconfigured, no engine is built and no
offload thread ever starts.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .runtime import thread_sentry

logger = logging.getLogger("dynamo.offload")

# The designated sync-transfer helpers (dynalint DT009): every synchronous
# device<->host materialization in this module must happen inside one of
# these functions -- bare names cover module functions, dotted qualnames
# pin single methods -- so an accidental blocking transfer on a tier hot
# path is a lint error, not a latent stall.  ``pack_kv_blob_frame`` is
# the G4 remote tier's materialize point; ``RemoteTier._put``/``_get``
# are the store round-trips themselves: all three run only on the
# kv-remote thread (thread_sentry asserts the role at runtime).
COPY_HELPERS = (
    "to_host",
    "pack_kv_blob_frame",
    "RemoteTier._put",
    "RemoteTier._get",
)

# Pseudo worker id of the hub-backed G4 store in every per-link table
# (telemetry TransferLog rows, the observatory's LinkModel, the global
# holdings index): store<->worker edges fit and predict like any
# worker<->worker link.
G4_STORE_ID = -4


def to_host(arr: Any) -> np.ndarray:
    """THE designated device->host materialize point for the offload plane.

    Runs only on the offload engine's thread: by the time it is called the
    async DMA (``copy_to_host_async``, started at dispatch) has usually
    landed, so this is a wait, not a transfer -- and if it is a transfer,
    it blocks a thread nobody's tick latency depends on.  Quantized pool
    snapshots (kv_cache.QuantKV) materialize data and scales together --
    the pair is the blob."""
    thread_sentry.assert_role("kv-offload", what="offload.to_host")
    from .engine.kv_cache import QuantKV

    if isinstance(arr, QuantKV):
        return QuantKV(q=np.asarray(arr.q), s=np.asarray(arr.s))
    return np.asarray(arr)


@dataclass
class BlockMeta:
    block_hash: int = 0
    parent_sequence_hash: int = 0
    position: int = 0
    # shard geometry of the pool the blob was exported from ({"axis": i,
    # "parts": n}, parallel.sharding.kv_shard_geometry) -- None for an
    # unsharded pool.  Tier blobs are always full-width (per-shard slices
    # reassemble on export), so this is provenance for restore-site
    # validation, not a layout switch.
    shards: Optional[Dict[str, int]] = None
    # dtype of the pool the blob was sliced from ("int8" = quantized
    # kv_cache.QuantKV pair -- its per-row scales travel inside the blob).
    # Restore sites use this to route cross-geometry deliveries through
    # the shared conversion rule; None = pre-ISSUE-13 full-width blob.
    kv_dtype: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "block_hash": self.block_hash,
            "parent_sequence_hash": self.parent_sequence_hash,
            "position": self.position,
        }
        if self.shards is not None:
            out["shards"] = dict(self.shards)
        if self.kv_dtype is not None:
            out["kv_dtype"] = str(self.kv_dtype)
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "BlockMeta":
        shards = d.get("shards")
        kv_dtype = d.get("kv_dtype")
        return cls(
            int(d.get("block_hash", 0)),
            int(d.get("parent_sequence_hash", 0)),
            int(d.get("position", 0)),
            dict(shards) if shards else None,
            str(kv_dtype) if kv_dtype else None,
        )


class KVStagingBuffer:
    """Host-RAM landing zone for an incoming chunked KV transfer.

    The decode side of disaggregation (and the prefix-onboard importer)
    assembles wire chunks here before the device scatter; this class owns
    the geometry arithmetic -- the preallocated ndarray, its flat byte
    view, and each chunk's [start, end) byte range -- so sender and
    receiver derive identical bounds from the same metadata.  Layer spans
    map to byte ranges because layer slabs are contiguous in the C-order
    blob ``[L, 2, pages, page, Hkv, D]``."""

    def __init__(self, shape, dtype, bounds, quant: bool = False) -> None:
        shape = tuple(int(s) for s in shape)
        self.quant = quant
        self.shape = shape
        if quant:
            # quantized wire layout (kv_cache.pack_quant_blob_bytes): each
            # layer slab is its int8 data followed by its f32 row scales,
            # so the landing zone is a flat byte buffer and layer_slice
            # unpacks the (data, scales) pair per span
            from .engine.kv_cache import quant_blob_nbytes

            self.array = np.empty((quant_blob_nbytes(shape),), np.uint8)
        else:
            self.array = np.empty(shape, dtype)
        self.flat = self.array.view(np.uint8).reshape(-1)
        self.bounds = [(int(s), int(e)) for s, e in bounds]
        if self.bounds and self.bounds[-1][1] != self.flat.size:
            raise ValueError(
                f"chunk bounds end at {self.bounds[-1][1]}, blob holds "
                f"{self.flat.size} bytes"
            )

    @classmethod
    def for_layer_spans(cls, shape, dtype, spans) -> "KVStagingBuffer":
        """One chunk per layer-group span [lo, hi) over axis 0.  An int8
        ``dtype`` selects the quantized wire layout (data + row scales per
        layer slab)."""
        shape = tuple(int(s) for s in shape)
        if np.dtype("int8") == np.dtype(str(dtype)):
            from .engine.kv_cache import quant_blob_nbytes

            bpl = quant_blob_nbytes(shape) // max(shape[0], 1)
            return cls(
                shape, dtype, [(lo * bpl, hi * bpl) for lo, hi in spans],
                quant=True,
            )
        total = int(np.prod(shape)) * np.dtype(dtype).itemsize
        bpl = total // max(shape[0], 1)
        return cls(shape, dtype, [(lo * bpl, hi * bpl) for lo, hi in spans])

    @classmethod
    def for_byte_chunks(cls, shape, dtype, chunk_bytes: int) -> "KVStagingBuffer":
        """Fixed-size byte chunks (the block-blob transfer framing).  An
        int8 ``dtype`` selects the quantized wire layout."""
        shape = tuple(int(s) for s in shape)
        quant = np.dtype("int8") == np.dtype(str(dtype))
        if quant:
            from .engine.kv_cache import quant_blob_nbytes

            total = quant_blob_nbytes(shape)
        else:
            total = int(np.prod(shape)) * np.dtype(dtype).itemsize
        if total == 0:
            return cls(shape, dtype, [(0, 0)], quant=quant)
        bounds = [
            (off, min(off + chunk_bytes, total))
            for off in range(0, total, chunk_bytes)
        ]
        return cls(shape, dtype, bounds, quant=quant)

    def payload(self):
        """The assembled blob in its engine-facing form: the ndarray for
        dense pools, the unpacked (data, scales) pair for quantized wire
        bytes.  Valid for whole-blob staging (``for_byte_chunks``) only --
        the layer-span layout packs (data | scales) PER SPAN, so those
        consumers unpack via :meth:`layer_slice` instead."""
        if self.quant:
            from .engine.kv_cache import unpack_quant_blob_bytes

            # zero-copy: the pair aliases the staging buffer's bytes
            return unpack_quant_blob_bytes(self.flat, self.shape)
        return self.array

    @property
    def memoryview(self) -> memoryview:
        return memoryview(self.flat)

    def layer_slice(self, lo: int, hi: int) -> np.ndarray:
        """View of layers [lo, hi) -- stable once their bytes landed.  For
        the quantized layout this unpacks the span's (data, scales) pair;
        like the dense path it ALIASES the staging buffer (zero-copy), so
        it is valid only while the buffer's bytes stay untouched."""
        if self.quant:
            from .engine.kv_cache import (
                quant_blob_nbytes,
                unpack_quant_blob_bytes,
            )

            bpl = quant_blob_nbytes(self.shape) // max(self.shape[0], 1)
            span_shape = (hi - lo,) + self.shape[1:]
            # zero-copy: the pair aliases the staging buffer's bytes
            return unpack_quant_blob_bytes(
                self.flat[lo * bpl : hi * bpl], span_shape
            )
        return self.array[lo:hi]


class DiskTier:
    """G3: one ``.npz`` file per block under ``root``, LRU-capped.

    ``put``/``get`` do blocking file I/O and therefore must only be
    called from the :class:`KVOffloadEngine`'s dedicated thread (the same
    single-writer-thread pattern as the hub WAL) -- the event loop and
    the engine's device executor never touch this class directly.  The
    residency index (``__contains__``) is in-RAM and safe from any
    thread."""

    def __init__(self, root: str, capacity_blocks: int) -> None:
        self.root = root
        self.capacity = capacity_blocks
        os.makedirs(root, exist_ok=True)
        self._lru: "collections.OrderedDict[int, None]" = collections.OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def _path(self, seq_hash: int) -> str:
        return os.path.join(self.root, f"{seq_hash & (2**64 - 1):016x}.npz")

    def __len__(self) -> int:
        return len(self._lru)

    def __contains__(self, seq_hash: int) -> bool:
        with self._lock:
            return seq_hash in self._lru

    def put(
        self, seq_hash: int, blob: np.ndarray, meta: BlockMeta
    ) -> List[Tuple[int, Optional[str], int]]:
        """Offload-thread only.  File I/O runs OUTSIDE the lock (write to
        a temp file, rename into place): the lock guards only the in-RAM
        index, so ``__contains__`` probes from the admission path never
        wait behind a multi-MB compressed write.

        Returns the holdings delta this put caused -- ``(hash, "disk",
        nbytes)`` for the stored block (``(hash, None, 0)`` when capacity
        or a write error dropped it) plus ``(victim, None, 0)`` for every
        LRU eviction -- so the publisher never advertises a tier the
        worker already dropped."""
        thread_sentry.assert_role("kv-offload", what="DiskTier.put")
        from .engine.kv_cache import QuantKV

        if self.capacity <= 0:
            return [(seq_hash, None, 0)]
        path = self._path(seq_hash)
        tmp = path + ".tmp.npz"  # .npz suffix so np.savez appends nothing
        try:
            meta_d = {
                k: v for k, v in meta.to_dict().items() if k != "shards"
            }
            if isinstance(blob, QuantKV):
                # quantized pair: scales are part of the block's bytes
                np.savez(tmp, blob=blob.q, blob_scales=blob.s, **meta_d)
            else:
                np.savez(tmp, blob=blob, **meta_d)
            os.replace(tmp, path)
        except OSError:
            logger.exception("disk tier write failed for %x", seq_hash)
            with_suppress_remove(tmp)
            return [(seq_hash, None, 0)]
        victims: List[int] = []
        with self._lock:
            self._lru[seq_hash] = None
            self._lru.move_to_end(seq_hash)
            while len(self._lru) > self.capacity:
                victim, _ = self._lru.popitem(last=False)
                victims.append(victim)
        for victim in victims:
            with_suppress_remove(self._path(victim))
        delta: List[Tuple[int, Optional[str], int]] = [
            (seq_hash, "disk", int(blob.nbytes))
        ]
        delta.extend((v, None, 0) for v in victims)
        return delta

    def get(self, seq_hash: int) -> Optional[Tuple[np.ndarray, BlockMeta]]:
        """Offload-thread only (single reader; puts rename atomically, so
        a file listed in the index is always complete).  The lock again
        covers only the index."""
        thread_sentry.assert_role("kv-offload", what="DiskTier.get")
        with self._lock:
            if seq_hash not in self._lru:
                self.misses += 1
                return None
        from .engine.kv_cache import QuantKV

        try:
            with np.load(self._path(seq_hash)) as z:
                blob = z["blob"]
                if "blob_scales" in z.files:
                    blob = QuantKV(q=blob, s=z["blob_scales"])
                meta = BlockMeta(
                    int(z["block_hash"]),
                    int(z["parent_sequence_hash"]),
                    int(z["position"]),
                    kv_dtype=(
                        str(z["kv_dtype"]) if "kv_dtype" in z.files else None
                    ),
                )
        except OSError:
            with self._lock:
                self._lru.pop(seq_hash, None)
                self.misses += 1
            return None
        with self._lock:
            if seq_hash in self._lru:
                self._lru.move_to_end(seq_hash)
            self.hits += 1
        return blob, meta


def with_suppress_remove(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass


class HostTier:
    """G2: preallocated host-RAM ring of block blobs; overflow demotes to
    the G3 parent.

    The ring is ONE contiguous ndarray of ``capacity_blocks`` slots,
    allocated lazily from the first block's geometry (the pinned-buffer
    analog on a platform without a user pin API: a single stable
    allocation the allocator never fragments or re-touches).  ``put``
    copies into a free slot with ``np.copyto`` -- zero allocations on the
    eviction path -- and ``get`` copies out, so a returned blob stays
    valid after its slot is recycled.  Blocks whose geometry does not
    match the ring (foreign-engine donors) fall back to a per-entry side
    table, counted against the same LRU capacity."""

    def __init__(
        self, capacity_blocks: int, parent: Optional[DiskTier] = None
    ) -> None:
        self.capacity = capacity_blocks
        self.parent = parent
        # LRU order over every resident hash; value = ring slot or None
        # (None = side-table entry)
        self._slots: "collections.OrderedDict[int, Optional[int]]" = (
            collections.OrderedDict()
        )
        self._misc: Dict[int, Tuple[np.ndarray, BlockMeta]] = {}
        self._meta: Dict[int, BlockMeta] = {}
        self._ring: Optional[np.ndarray] = None
        # scale ring of a quantized pool's blocks (kv_cache.QuantKV): the
        # pair occupies one LRU slot -- scales are part of the block
        self._ring_s: Optional[np.ndarray] = None
        self._ring_failed = False
        self._free_slots: List[int] = []
        # prefetch pins: hash -> refcount.  A pinned block is skipped by
        # LRU demotion, so a chain promoted for a queued request cannot
        # be churned back to disk before its admission consumes it.  Pins
        # come only from bounded prefetch windows and are released at
        # admission or cancel (the leak the ISSUE 10 satellite closes).
        self._pins: Dict[int, int] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        # holdings sink (KVOffloadEngine._on_holdings): fired -- outside
        # the lock, on the offload thread -- with the per-put residency
        # delta, so every promote/demote/evict reaches the cluster-global
        # prefix index the moment it happens
        self.holdings_cb: Optional[Any] = None

    def __len__(self) -> int:
        return len(self._slots)

    @property
    def ring_nbytes(self) -> int:
        n = self._ring.nbytes if self._ring is not None else 0
        if self._ring_s is not None:
            n += self._ring_s.nbytes
        return n

    def _ensure_ring_locked(self, blob: Any) -> None:
        from .engine.kv_cache import QuantKV

        if self._ring is not None or self._ring_failed or self.capacity <= 0:
            return
        try:
            if isinstance(blob, QuantKV):
                self._ring = np.empty(
                    (self.capacity,) + tuple(blob.q.shape), blob.q.dtype
                )
                self._ring_s = np.empty(
                    (self.capacity,) + tuple(blob.s.shape), blob.s.dtype
                )
            else:
                self._ring = np.empty(
                    (self.capacity,) + tuple(blob.shape), blob.dtype
                )
        except MemoryError:
            # remember the failure: retrying a multi-GB allocation on
            # every eviction would hammer the allocator on the one thread
            # all offload work queues behind
            logger.exception(
                "host tier ring allocation failed (%d blocks); falling "
                "back to per-entry storage", self.capacity,
            )
            self._ring = None
            self._ring_s = None
            self._ring_failed = True
            return
        self._free_slots = list(range(self.capacity - 1, -1, -1))

    def _ring_fits_locked(self, blob: Any) -> bool:
        from .engine.kv_cache import QuantKV

        if self._ring is None:
            return False
        if isinstance(blob, QuantKV):
            return (
                self._ring_s is not None
                and tuple(blob.q.shape) == self._ring.shape[1:]
                and blob.q.dtype == self._ring.dtype
                and tuple(blob.s.shape) == self._ring_s.shape[1:]
            )
        return (
            self._ring_s is None
            and tuple(blob.shape) == self._ring.shape[1:]
            and blob.dtype == self._ring.dtype
        )

    def _ring_read_locked(self, slot: int):
        from .engine.kv_cache import QuantKV

        if self._ring_s is not None:
            return QuantKV(
                q=self._ring[slot].copy(), s=self._ring_s[slot].copy()
            )
        return self._ring[slot].copy()

    def put(self, seq_hash: int, blob: np.ndarray, meta: BlockMeta) -> None:
        delta: List[Tuple[int, Optional[str], int]] = []
        if self.capacity <= 0:
            if self.parent is not None:
                delta = self.parent.put(seq_hash, blob, meta)
            else:
                delta = [(seq_hash, None, 0)]
            self._emit_holdings(delta)
            return
        from .engine.kv_cache import QuantKV

        demote: List[Tuple[int, np.ndarray, BlockMeta]] = []
        with self._lock:
            self._evict_locked(seq_hash)  # overwrite: recycle the old slot
            self._ensure_ring_locked(blob)
            slot: Optional[int] = None
            if self._ring_fits_locked(blob):
                if not self._free_slots:
                    self._demote_lru_locked(demote)
                if self._free_slots:
                    slot = self._free_slots.pop()
                    if isinstance(blob, QuantKV):
                        np.copyto(self._ring[slot], blob.q)
                        np.copyto(self._ring_s[slot], blob.s)
                    else:
                        np.copyto(self._ring[slot], blob)
            if slot is None:
                # geometry mismatch (or ring unavailable): side table
                self._misc[seq_hash] = (blob.copy(), meta)
            self._slots[seq_hash] = slot
            self._slots.move_to_end(seq_hash)
            self._meta[seq_hash] = meta
            while len(self._slots) > self.capacity:
                if not self._demote_lru_locked(demote):
                    break  # everything resident is pinned; overshoot
        delta.append((seq_hash, "host", int(blob.nbytes)))
        for victim, vb, vm in demote:
            if self.parent is not None:
                delta.extend(self.parent.put(victim, vb, vm))
            else:
                delta.append((victim, None, 0))
        self._emit_holdings(delta)

    def _emit_holdings(
        self, delta: List[Tuple[int, Optional[str], int]]
    ) -> None:
        """Forward a residency delta to the holdings sink.  Disk-LRU
        victims that are still RAM-resident (a promote leaves the disk
        copy behind; the disk ring may later churn it out) are filtered
        -- the worker still holds them, just in a warmer tier."""
        cb = self.holdings_cb
        if cb is None or not delta:
            return
        out = []
        for h, tier, nbytes in delta:
            if tier is None:
                with self._lock:
                    if h in self._slots:
                        continue
            out.append((h, tier, nbytes))
        if out:
            try:
                cb(out)
            except Exception:
                logger.debug("holdings callback failed", exc_info=True)

    def _demote_lru_locked(
        self, demote: List[Tuple[int, np.ndarray, BlockMeta]]
    ) -> bool:
        """Demote the least-recent UNPINNED resident; returns False when
        every resident is pinned (caller stops demoting -- the ring may
        transiently exceed capacity rather than evict a block a queued
        request is about to consume)."""
        victim = next(
            (h for h in self._slots if not self._pins.get(h)), None
        )
        if victim is None:
            return False
        slot = self._slots.pop(victim)
        meta = self._meta.pop(victim)
        if slot is None:
            vb, meta = self._misc.pop(victim)
        else:
            vb = self._ring_read_locked(slot)
            self._free_slots.append(slot)
        demote.append((victim, vb, meta))
        return True

    def pin(self, seq_hash: int) -> bool:
        """Pin a RAM-resident block against demotion (prefetch holds);
        returns False when the hash is not resident."""
        with self._lock:
            if seq_hash not in self._slots:
                return False
            self._pins[seq_hash] = self._pins.get(seq_hash, 0) + 1
            return True

    def unpin(self, seq_hash: int) -> None:
        with self._lock:
            n = self._pins.get(seq_hash, 0) - 1
            if n > 0:
                self._pins[seq_hash] = n
            else:
                self._pins.pop(seq_hash, None)

    @property
    def pinned_blocks(self) -> int:
        with self._lock:
            return len(self._pins)

    @property
    def block_nbytes(self) -> int:
        """Bytes of one resident block blob (0 until the first put)."""
        if self._ring is not None:
            n = int(self._ring[0].nbytes)
            if self._ring_s is not None:
                n += int(self._ring_s[0].nbytes)
            return n
        with self._lock:
            for blob, _meta in self._misc.values():
                return int(blob.nbytes)
        return 0

    def _evict_locked(self, seq_hash: int) -> None:
        slot = self._slots.pop(seq_hash, "absent")
        if slot == "absent":
            return
        self._meta.pop(seq_hash, None)
        if slot is None:
            self._misc.pop(seq_hash, None)
        else:
            self._free_slots.append(slot)

    def get_ram(self, seq_hash: int) -> Optional[Tuple[np.ndarray, BlockMeta]]:
        """RAM-resident hit only: never consults the disk parent, so it is
        safe to call from latency-sensitive threads (the admission path)."""
        with self._lock:
            if seq_hash not in self._slots:
                return None
            slot = self._slots[seq_hash]
            self._slots.move_to_end(seq_hash)
            self.hits += 1
            if slot is None:
                blob, meta = self._misc[seq_hash]
                return blob.copy(), meta
            return self._ring_read_locked(slot), self._meta[seq_hash]

    def get(self, seq_hash: int) -> Optional[Tuple[np.ndarray, BlockMeta]]:
        """Tiered get: RAM first, then the disk parent (promoting the hit
        back into G2).  May do file I/O -- offload-thread only."""
        hit = self.get_ram(seq_hash)
        if hit is not None:
            return hit
        if self.parent is not None:
            promoted = self.parent.get(seq_hash)
            if promoted is not None:
                # promote back into G2 (and let LRU demote something else)
                self.put(seq_hash, *promoted)
                return promoted
        self.misses += 1
        return None

    def contains(self, seq_hash: int) -> bool:
        with self._lock:
            if seq_hash in self._slots:
                return True
        return self.parent is not None and seq_hash in self.parent

    def stats(self) -> Dict[str, Any]:
        out = {
            "g2_blocks": len(self),
            "g2_hits": self.hits,
            "g2_misses": self.misses,
            "g2_ring_bytes": self.ring_nbytes,
        }
        if self.parent is not None:
            out.update(
                g3_blocks=len(self.parent),
                g3_hits=self.parent.hits,
                g3_misses=self.parent.misses,
            )
        return out


# ---------------------------------------------------------------------------
# the G4 remote tier: fleet-shared blob store behind the hub
# ---------------------------------------------------------------------------


def pack_kv_blob_frame(blob: Any, meta: BlockMeta) -> bytes:
    """Self-describing G4 wire frame for one block blob.

    ``u32-LE header length | JSON header | payload``: quantized blobs
    (kv_cache.QuantKV) pack through the shared
    ``pack_quant_blob_bytes`` rule -- int8 pools ship half the bytes --
    and dense blobs ship C-order raw.  A COPY_HELPERS member: this is the
    remote tier's one sync materialize point and runs only on the
    kv-remote thread."""
    from .engine.kv_cache import QuantKV, pack_quant_blob_bytes

    if isinstance(blob, QuantKV):
        payload = pack_quant_blob_bytes(blob)
        kind, dtype = "quant", "int8"
        shape = tuple(int(s) for s in blob.q.shape)
    else:
        arr = np.ascontiguousarray(blob)
        payload = arr.tobytes()
        kind, dtype = "dense", str(arr.dtype)
        shape = tuple(int(s) for s in arr.shape)
    hdr = json.dumps(
        {
            "v": 1,
            "kind": kind,
            "dtype": dtype,
            "shape": list(shape),
            "meta": meta.to_dict(),
            "payload_nbytes": len(payload),
        }
    ).encode("utf-8")
    return struct.pack("<I", len(hdr)) + hdr + payload


def unpack_kv_blob_frame(buf: Any) -> Tuple[Any, BlockMeta]:
    """Inverse of :func:`pack_kv_blob_frame`; raises ``ValueError`` on any
    framing violation (truncation, garbage header, payload/shape size
    mismatch) so a corrupt store entry surfaces as a fetch miss -- the
    gate falls back to recompute -- never as a malformed scatter.

    The returned blob ALIASES ``buf`` (zero-copy unpack); the host-tier
    put that follows copies into the ring."""
    from .engine.kv_cache import quant_blob_nbytes, unpack_quant_blob_bytes

    view = memoryview(buf)
    if len(view) < 4:
        raise ValueError("G4 frame shorter than its header-length word")
    (hlen,) = struct.unpack_from("<I", view, 0)
    if hlen <= 0 or 4 + hlen > len(view):
        raise ValueError(f"G4 frame header length {hlen} exceeds frame")
    try:
        hdr = json.loads(bytes(view[4 : 4 + hlen]).decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise ValueError("G4 frame header is not valid JSON") from e
    if not isinstance(hdr, dict) or "shape" not in hdr:
        raise ValueError("G4 frame header missing blob geometry")
    shape = tuple(int(s) for s in hdr["shape"])
    payload = view[4 + hlen :]
    try:
        if hdr.get("kind") == "quant":
            expect = quant_blob_nbytes(shape)
        else:
            expect = int(np.prod(shape)) * np.dtype(str(hdr.get("dtype"))).itemsize
    except TypeError as e:
        raise ValueError("G4 frame header names an unknown dtype") from e
    if len(payload) != expect or expect != int(hdr.get("payload_nbytes", -1)):
        raise ValueError(
            f"G4 frame payload holds {len(payload)} bytes, geometry "
            f"expects {expect}"
        )
    meta = BlockMeta.from_dict(hdr.get("meta") or {})
    if hdr.get("kind") == "quant":
        return unpack_quant_blob_bytes(payload, shape), meta
    return np.frombuffer(payload, str(hdr["dtype"])).reshape(shape), meta


class InMemoryBlobStore:
    """Process-local G4 store (tests, single-process bench legs): the hub
    blob verbs' semantics -- byte-capacity LRU over named blobs -- behind
    the same sync ``put``/``get``/``delete`` protocol :class:`RemoteTier`
    speaks, without a hub in the loop.  Thread-safe: every worker's
    kv-remote thread may hit the shared instance concurrently."""

    def __init__(self, cap_bytes: int = 1 << 30) -> None:
        self.cap_bytes = int(cap_bytes)
        self._blobs: "collections.OrderedDict[str, bytes]" = (
            collections.OrderedDict()
        )
        self._total = 0
        self._lock = threading.Lock()

    def put(self, name: str, data: bytes) -> None:
        data = bytes(data)
        with self._lock:
            old = self._blobs.pop(name, None)
            if old is not None:
                self._total -= len(old)
            self._blobs[name] = data
            self._total += len(data)
            while self._total > self.cap_bytes and len(self._blobs) > 1:
                _, dropped = self._blobs.popitem(last=False)
                self._total -= len(dropped)

    def get(self, name: str) -> Optional[bytes]:
        with self._lock:
            data = self._blobs.get(name)
            if data is not None:
                self._blobs.move_to_end(name)
            return data

    def delete(self, name: str) -> bool:
        with self._lock:
            old = self._blobs.pop(name, None)
            if old is not None:
                self._total -= len(old)
            return old is not None

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"blobs": len(self._blobs), "bytes": self._total}


class RemoteTier:
    """G4: block blobs in a fleet-shared object store (the hub's blob
    verbs, or any sync ``put``/``get`` duck type).

    All store I/O runs on ONE private thread (``kv-remote``) -- the same
    isolation contract as the kv-offload thread, so a slow or wedged
    store RPC can never stall an eviction cascade, a tick, or the event
    loop.  ``submit_put``/``fetch`` enqueue and return futures;
    ``fetch_blocking`` is for worker threads that may wait (the offload
    thread's tiered ``get_blocking`` chain, the onboard path's executor
    hop).  Every store/fetch feeds the shared telemetry
    :class:`~dynamo_tpu.runtime.telemetry.TransferLog` with the
    :data:`G4_STORE_ID` pseudo endpoint, so the fleet observatory fits a
    store link and ``predict_transfer_ms`` covers the G4 edge like any
    worker<->worker hop."""

    def __init__(
        self,
        store: Any,
        *,
        worker_id: int = 0,
        namespace: str = "dynamo",
        registry: Any = None,
    ) -> None:
        self.store = store
        self.worker_id = int(worker_id)
        self.namespace = namespace
        self._ex = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="kv-remote"
        )
        self._lock = threading.Lock()
        # hash -> frame nbytes known to be in the store (our own puts +
        # adverts merged back from the cluster-global holdings index)
        self._known: Dict[int, int] = {}
        from .runtime.metrics import RemoteKVMetrics

        self.metrics = RemoteKVMetrics(registry)
        # holdings sink (KVOffloadEngine._on_holdings): a successful put
        # advertises (hash, "remote", nbytes) to the global index
        self.holdings_cb: Optional[Any] = None
        # plain mirrors for bench/tests (no registry scrape needed)
        self.puts = 0
        self.fetches = 0
        self.store_bytes = 0
        self.store_seconds = 0.0
        self.fetch_bytes = 0
        self.fetch_seconds = 0.0
        self.fetch_fails: Dict[str, int] = {}

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._ex.shutdown(wait=True)

    def drain(self) -> None:
        self._ex.submit(lambda: None).result()

    def _name(self, seq_hash: int) -> str:
        return f"kv/{self.namespace}/{seq_hash & (2**64 - 1):016x}"

    # -- residency index ---------------------------------------------------

    def contains(self, seq_hash: int) -> bool:
        with self._lock:
            return seq_hash in self._known

    def note_remote(self, seq_hash: int, nbytes: int) -> None:
        """Merge a G4 advert from the cluster-global index (another
        worker published this block) into the local residency view."""
        with self._lock:
            self._known[seq_hash] = int(nbytes)

    def known_blocks(self) -> int:
        with self._lock:
            return len(self._known)

    # -- async surface -----------------------------------------------------

    def submit_put(self, seq_hash: int, blob: Any, meta: BlockMeta):
        """Queue a store upload; returns the future (True on success)."""
        return self._ex.submit(self._put, seq_hash, blob, meta)

    def fetch(self, seq_hash: int):
        """Queue a store fetch; the future resolves to ``(blob, meta)``
        or None (missing / failed / corrupt -- the caller recomputes)."""
        return self._ex.submit(self._get, seq_hash)

    def fetch_blocking(
        self, seq_hash: int
    ) -> Optional[Tuple[Any, BlockMeta]]:
        """Worker-thread fetch (never the event loop): waits on the
        kv-remote thread's result."""
        return self.fetch(seq_hash).result()

    # -- kv-remote thread side ---------------------------------------------

    def _put(self, seq_hash: int, blob: Any, meta: BlockMeta) -> bool:
        thread_sentry.assert_role("kv-remote", what="RemoteTier._put")
        try:
            frame = pack_kv_blob_frame(blob, meta)
            t0 = time.perf_counter()
            self.store.put(self._name(seq_hash), frame)
            dt = time.perf_counter() - t0
        except Exception:
            logger.debug("G4 store put failed for %x", seq_hash, exc_info=True)
            return False
        with self._lock:
            self._known[seq_hash] = len(frame)
            self.puts += 1
            self.store_bytes += len(frame)
            self.store_seconds += dt
            known = len(self._known)
        self.metrics.record_store(len(frame), dt)
        self.metrics.blocks.set(known)
        from .runtime.telemetry import note_transfer

        note_transfer(self.worker_id, G4_STORE_ID, len(frame), dt)
        cb = self.holdings_cb
        if cb is not None:
            try:
                cb([(seq_hash, "remote", len(frame))])
            except Exception:
                logger.debug("G4 holdings callback failed", exc_info=True)
        return True

    def _get(self, seq_hash: int) -> Optional[Tuple[Any, BlockMeta]]:
        thread_sentry.assert_role("kv-remote", what="RemoteTier._get")
        from .runtime import faults

        if faults.injector.enabled and faults.injector.should_fire(
            "remote.fetch_fail", f"g4/{seq_hash:x}"
        ):
            self._count_fail("fetch_fail")
            return None
        t0 = time.perf_counter()
        try:
            frame = self.store.get(self._name(seq_hash))
        except Exception:
            logger.debug(
                "G4 store get failed for %x", seq_hash, exc_info=True
            )
            self._count_fail("fetch_fail")
            return None
        if frame is None:
            # the store LRU'd it out from under the index: forget it
            with self._lock:
                self._known.pop(seq_hash, None)
            self._count_fail("missing")
            return None
        dt = time.perf_counter() - t0
        if faults.injector.enabled and faults.injector.should_fire(
            "remote.blob_corrupt", f"g4/{seq_hash:x}"
        ):
            # truncate mid-payload: the frame validator must catch it
            frame = bytes(frame)[: max(len(frame) // 2, 4)]
        try:
            blob, meta = unpack_kv_blob_frame(frame)
        except ValueError:
            logger.warning(
                "G4 blob for %x failed frame validation; treating as miss",
                seq_hash,
            )
            self._count_fail("blob_corrupt")
            return None
        with self._lock:
            self.fetches += 1
            self.fetch_bytes += len(frame)
            self.fetch_seconds += dt
        self.metrics.record_fetch(len(frame), dt)
        from .runtime.telemetry import note_transfer

        note_transfer(G4_STORE_ID, self.worker_id, len(frame), dt)
        return blob, meta

    def _count_fail(self, cause: str) -> None:
        with self._lock:
            self.fetch_fails[cause] = self.fetch_fails.get(cause, 0) + 1
        self.metrics.fetch_failures.labels(cause).inc()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "g4_known_blocks": len(self._known),
                "g4_puts": self.puts,
                "g4_fetches": self.fetches,
                "g4_store_bytes": self.store_bytes,
                "g4_fetch_bytes": self.fetch_bytes,
                "g4_fetch_fails": dict(self.fetch_fails),
            }
            seconds = self.store_seconds + self.fetch_seconds
            if seconds > 0:
                out["kv_g4_gbps"] = round(
                    (self.store_bytes + self.fetch_bytes) / seconds / 1e9, 3
                )
        return out


def parse_kv_remote_spec(spec: str) -> Optional[Dict[str, Any]]:
    """Parse a ``--kv-remote`` / ``DYN_KV_REMOTE`` value into G4 settings,
    or None when empty/off (no remote tier, no kv-remote thread).

    Grammar: ``1``/``on`` arms the tier with defaults, or a
    comma-separated ``k=v`` list::

        DYN_KV_REMOTE=mirror=1,fetch=1,prefill_tok_s=4000,gbps=1.0,namespace=prod

    ``mirror`` re-publishes host-tier eviction stores into the fleet
    store; ``fetch`` lets the router gate choose G4 as a prefix source;
    ``prefill_tok_s`` is the per-worker prefill-rate estimate and
    ``gbps`` the unfitted-link bandwidth prior, both feeding the
    fetch-vs-recompute gate until the observatory has real
    observations."""
    spec = (spec or "").strip()
    if not spec or spec.lower() in ("0", "off", "false", "no"):
        return None
    out: Dict[str, Any] = {
        "mirror": True,
        "fetch": True,
        "prefill_tok_s": 4000.0,
        "gbps": 1.0,
        "namespace": "dynamo",
    }
    if spec.lower() in ("1", "on", "true", "yes"):
        return out
    for clause in filter(None, (c.strip() for c in spec.split(","))):
        k, sep, v = clause.partition("=")
        k = k.strip().lower()
        if not sep:
            raise ValueError(f"malformed DYN_KV_REMOTE clause {clause!r}")
        try:
            if k in ("mirror", "fetch"):
                out[k] = v.strip().lower() not in ("0", "off", "false", "no")
            elif k in ("prefill_tok_s", "gbps"):
                out[k] = float(v)
                if out[k] <= 0:
                    raise ValueError(f"{k} must be positive")
            elif k == "namespace":
                out[k] = v.strip()
            else:
                raise ValueError(f"unknown DYN_KV_REMOTE key {k!r}")
        except ValueError as e:
            raise ValueError(f"bad DYN_KV_REMOTE value {clause!r}") from e
    return out


def env_remote_spec(
    environ: Optional[Dict[str, str]] = None,
) -> Optional[Dict[str, Any]]:
    """``DYN_KV_REMOTE`` from the environment, parsed; None when unset."""
    env = environ if environ is not None else os.environ
    return parse_kv_remote_spec(env.get("DYN_KV_REMOTE", ""))


# ---------------------------------------------------------------------------
# the offload engine: dedicated thread + swap records + env arming
# ---------------------------------------------------------------------------


SWAP_PENDING = "pending"
SWAP_READY = "ready"
SWAP_FAILED = "failed"


@dataclass
class PrefetchState:
    """One queued request's prefetch walk (queue-side prefix promotion
    with completion tracking, ISSUE 10).

    ``done`` collects the hashes the walk found (or made) RAM-resident
    -- each is pinned in the host ring until the request admits or
    cancels.  ``completed_at`` stamps the walk's end; together with
    ``issued_at`` and the admission stamp it yields the *overlap ratio*:
    the fraction of the disk->host walk that ran during queue wait
    instead of on the TTFT critical path (1.0 = fully hidden)."""

    hashes: List[int]
    issued_at: float = field(default_factory=time.perf_counter)
    done: set = field(default_factory=set)
    completed_at: Optional[float] = None
    # stamped by finish_prefetch when admission lands before the walk
    # finishes; the walk's tail then computes the partial overlap
    admitted_at: Optional[float] = None
    consumed: Optional[set] = None


@dataclass
class SwapRecord:
    """One preempted sequence's parked KV, staged across two homes:

    ``dev`` is the gathered device-side snapshot -- retained (budgeted)
    so a short park restores with a device-to-device scatter and never
    round-trips the host link (FlowKV's low-latency staged transfer; on a
    tunneled chip the host link can be 100x slower than HBM).  ``blob``
    is the host materialization the offload thread produces -- the spill
    that survives once the device copy is dropped for budget.  A record
    is restorable the moment either exists."""

    cache_len: int
    n_blocks: int  # block-equivalents charged against the swap budget
    # shard geometry of the source pool at snapshot time (provenance for
    # the restore-side compatibility check; blobs are full-width)
    shards: Optional[Dict[str, int]] = None
    state: str = SWAP_PENDING
    dev: Any = None  # device-resident staging copy (fast-path restore)
    blob: Optional[np.ndarray] = None
    nbytes: int = 0
    started_at: float = field(default_factory=time.perf_counter)


def env_offload_spec(environ: Optional[Dict[str, str]] = None) -> Optional[Dict[str, Any]]:
    """Parse ``DYN_KV_OFFLOAD`` into offload-plane settings, or None when
    unset (the plane stays a no-op: no tiers, no thread, no swap).

    Grammar: ``1``/``on`` arms the host tier with defaults, or a
    comma-separated ``k=v`` list::

        DYN_KV_OFFLOAD=host=256,disk=1024,dir=/var/kv,swap=1

    with ``host``/``disk`` in blocks, ``dir`` the G3 root, and ``swap``
    enabling/disabling swap-based preemption (default on)."""
    env = environ if environ is not None else os.environ
    spec = env.get("DYN_KV_OFFLOAD", "").strip()
    if not spec or spec.lower() in ("0", "off", "false", "no"):
        return None
    out: Dict[str, Any] = {"host": 256, "disk": 0, "dir": None, "swap": True}
    if spec.lower() in ("1", "on", "true", "yes"):
        return out
    for clause in filter(None, (c.strip() for c in spec.split(","))):
        k, sep, v = clause.partition("=")
        k = k.strip().lower()
        if not sep:
            raise ValueError(f"malformed DYN_KV_OFFLOAD clause {clause!r}")
        try:
            if k == "host":
                out["host"] = int(v)
            elif k == "disk":
                out["disk"] = int(v)
            elif k == "dir":
                out["dir"] = v
            elif k == "swap":
                out["swap"] = v.strip().lower() not in ("0", "off", "false", "no")
            else:
                raise ValueError(f"unknown DYN_KV_OFFLOAD key {k!r}")
        except ValueError as e:
            raise ValueError(f"bad DYN_KV_OFFLOAD value {clause!r}") from e
    return out


class KVOffloadEngine:
    """The G2/G3 coordinator: owns the tiers, the dedicated offload
    thread, the swap records, and the plane's metrics.

    Every blocking step -- the device->host materialize of an eviction
    snapshot, disk writes, disk reads, host-ring copies -- runs on ONE
    private thread (``kv-offload``), the same isolation pattern as the
    hub WAL's writer thread: the asyncio event loop and the engine's
    device executor only ever enqueue work here or probe RAM-resident
    indexes.  Capacity and occupancy are deterministic: the host ring is
    one preallocated buffer, swap records are budgeted in
    block-equivalents against ``swap_blocks``."""

    def __init__(
        self,
        host_blocks: int,
        disk_blocks: int = 0,
        disk_dir: Optional[str] = None,
        *,
        swap_enabled: bool = True,
        swap_blocks: Optional[int] = None,
        registry: Any = None,
    ) -> None:
        disk = None
        if disk_blocks > 0:
            if not disk_dir:
                raise ValueError("disk_blocks > 0 requires disk_dir")
            disk = DiskTier(disk_dir, disk_blocks)
        self.disk = disk
        self.host = HostTier(host_blocks, parent=disk)
        self.swap_enabled = swap_enabled
        self.swap_blocks = (
            swap_blocks if swap_blocks is not None else max(host_blocks, 8)
        )
        # device-side staging budget (block-equivalents of retained device
        # snapshots, HBM *outside* the page pool -- the same scratch class
        # as the disagg export gathers); 0 = host-blob restores only.
        # Half the swap budget: short parks ride the device fast path,
        # but once parked KV piles up the overflow spills to host blobs
        # instead of holding HBM scratch for the whole park.
        self.swap_device_blocks = max(self.swap_blocks // 2, 1)
        self._swaps: Dict[str, SwapRecord] = {}
        self._swap_used = 0
        self._swap_dev_used = 0
        self._promoting: set = set()
        self._lock = threading.Lock()
        self._ex = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="kv-offload"
        )
        # lazy import keeps this module importable without prometheus
        from .runtime.metrics import OffloadMetrics

        self.metrics = OffloadMetrics(registry)
        self._registry = registry
        # the G4 remote tier (attach_remote): host-tier eviction stores
        # mirror into the fleet store, and the tiered get_blocking chain
        # extends host -> disk -> remote
        self.remote: Optional[RemoteTier] = None
        self._remote_mirror = True
        # holdings sink (engine._emit_kv_holdings): receives every tier
        # residency delta [(hash, tier|None, nbytes)] for the
        # cluster-global prefix index
        self.holdings_cb: Optional[Any] = None
        self.host.holdings_cb = self._on_holdings
        # called (from the offload thread) when a swap blob becomes ready,
        # so a sleeping tick loop wakes to apply it
        self.wake_cb: Optional[Any] = None
        # plain-int mirrors for bench/tests (no registry scrape needed)
        self.offload_bytes = 0
        self.offload_seconds = 0.0
        self.onboard_bytes = 0
        self.onboard_seconds = 0.0
        # per-tier [bytes, seconds] so bench can separate swap restores
        # from prefix onboards when deriving recovery rates
        self.onboard_detail: Dict[str, List[float]] = {}
        self.tier_hits: Dict[str, int] = {"host": 0, "disk": 0, "swap": 0}
        self.tier_lookups = 0
        # disk->host promotions (prefetch or lookup-triggered); kept OUT
        # of tier_hits so tier_hit_rate only counts lookups actually
        # served -- a warmed-but-unused worker must not read as warm
        self.disk_promotes = 0
        self.copy_fails = 0
        self.swap_outs = 0
        self.swap_ins = 0
        self.swap_fallbacks = 0
        self.onboard_fallbacks = 0
        # queue-side prefetch tracking (ISSUE 10): request-keyed walk
        # states (pins + stamps) and the aggregate counters behind
        # dynamo_kv_prefetch_* / the bench overlap ratio
        self._prefetch_states: Dict[str, PrefetchState] = {}
        self.prefetch_issued = 0  # blocks requested by tracked walks
        self.prefetch_hits = 0  # staged blocks consumed at admission
        self.prefetch_wasted_bytes = 0  # staged but never consumed
        self.prefetch_overlap_sum = 0.0
        self.prefetch_overlap_n = 0

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        self._ex.shutdown(wait=True)
        if self.remote is not None:
            self.remote.close()

    def drain(self) -> None:
        """Barrier: returns once every queued offload/prefetch/swap task
        has run (tests and shutdown; never called on a hot path)."""
        self._ex.submit(lambda: None).result()
        if self.remote is not None:
            self.remote.drain()

    def attach_remote(
        self,
        store: Any,
        *,
        worker_id: int = 0,
        namespace: str = "dynamo",
        mirror: bool = True,
    ) -> RemoteTier:
        """Arm the G4 tier over ``store`` (the hub blob verbs or any sync
        put/get duck type).  ``mirror=True`` re-publishes every host-tier
        eviction store into the fleet store so peers (and cold restarts)
        can fetch instead of recompute."""
        remote = RemoteTier(
            store,
            worker_id=worker_id,
            namespace=namespace,
            registry=self._registry,
        )
        remote.holdings_cb = self._on_holdings
        self._remote_mirror = bool(mirror)
        self.remote = remote
        return remote

    def _on_holdings(self, delta: List[Tuple[int, Optional[str], int]]) -> None:
        """Tier-side residency deltas (host/disk/remote puts, demotions,
        evictions, promotes) fan into the engine-facing sink."""
        cb = self.holdings_cb
        if cb is None:
            return
        try:
            cb(delta)
        except Exception:
            logger.debug("holdings sink failed", exc_info=True)

    def _wake(self) -> None:
        cb = self.wake_cb
        if cb is not None:
            try:
                cb()
            except Exception:
                logger.debug("offload wake callback failed", exc_info=True)

    # -- eviction path (G1 -> G2 -> G3) --------------------------------------

    def submit_evict(self, seq_hash: int, snap: Any, meta: BlockMeta) -> None:
        """Queue an eviction snapshot for materialize + tier store.  The
        caller has already dispatched the device slice and started the
        async host copy; nothing here blocks."""
        self._ex.submit(self._store_evict, seq_hash, snap, meta)

    def _store_evict(self, seq_hash: int, snap: Any, meta: BlockMeta) -> None:
        from .runtime import faults

        try:
            if faults.injector.enabled and faults.injector.should_fire(
                "offload.copy_fail", f"evict/{seq_hash:x}"
            ):
                # copy_fails is also bumped by swap_out on the engine
                # executor: both increments go through the lock (DT014)
                with self._lock:
                    self.copy_fails += 1
                self.metrics.copy_fails.inc()
                return  # lost offload = a cache miss later, never an error
            t0 = time.perf_counter()
            blob = to_host(snap)
            self.host.put(seq_hash, blob, meta)
            dt = time.perf_counter() - t0
            with self._lock:
                self.offload_bytes += blob.nbytes
                self.offload_seconds += dt
            self.metrics.record_offload("host", blob.nbytes, dt)
            remote = self.remote
            if (
                remote is not None
                and self._remote_mirror
                and not remote.contains(seq_hash)
            ):
                # fleet publication rides the kv-remote thread; the host
                # blob is already materialized, so this enqueue is free
                remote.submit_put(seq_hash, blob, meta)
            self._observe_occupancy()
        except Exception:
            logger.debug("offload store failed for %x", seq_hash, exc_info=True)

    def submit_put(self, seq_hash: int, blob: np.ndarray, meta: BlockMeta) -> None:
        """Store an externally-sourced block (prefix-onboard donor fetch)
        without touching the calling thread: the put -- and any disk
        demotion it cascades into -- runs on the offload thread."""
        self._ex.submit(self._store_put, seq_hash, blob, meta)

    def _store_put(self, seq_hash: int, blob: np.ndarray, meta: BlockMeta) -> None:
        try:
            self.host.put(seq_hash, blob, meta)
            self._observe_occupancy()
        except Exception:
            logger.debug("tier put failed for %x", seq_hash, exc_info=True)

    # -- lookup path (tiered prefix reuse) -----------------------------------

    def lookup(self, seq_hash: int) -> Optional[Tuple[np.ndarray, BlockMeta, str]]:
        """Admission-time probe: returns ``(blob, meta, tier)`` for a
        RAM-resident hit.  A disk-only hit schedules an asynchronous
        promote (so a later admission -- or the retry after prefetch --
        hits in RAM) and returns None: this path runs on the event loop
        and must never wait on file I/O."""
        self.tier_lookups += 1
        hit = self.host.get_ram(seq_hash)
        if hit is not None:
            self.tier_hits["host"] += 1
            self.metrics.tier_hits.labels("host").inc()
            return hit[0], hit[1], "host"
        if self.disk is not None and seq_hash in self.disk:
            with self._lock:
                schedule = seq_hash not in self._promoting
                if schedule:
                    self._promoting.add(seq_hash)
            if schedule:
                self._ex.submit(self._promote, seq_hash)
        return None

    def _promote(self, seq_hash: int) -> None:
        try:
            hit = self.host.get(seq_hash)  # promotes disk -> ring
            if hit is not None:
                self.disk_promotes += 1
                self.metrics.tier_promotes.labels("disk").inc()
                self._observe_occupancy()
        except Exception:
            logger.debug("disk promote failed for %x", seq_hash, exc_info=True)
        finally:
            with self._lock:
                self._promoting.discard(seq_hash)
            self._wake()

    def prefetch(
        self, seq_hashes: List[int], request_id: Optional[str] = None
    ) -> None:
        """Queue-side prefetch: while the request waits for admission,
        promote its offloaded prefix chain into the host ring so the
        admission-time ``lookup`` is a RAM hit and the onboard's H2D
        scatter can be dispatched with the admitting tick (overlapping
        the copy with that tick's compute) instead of stalling on a disk
        read.  Stops at the first tier miss -- prefix chains are only
        usable contiguously.

        With a ``request_id`` the walk is *tracked*: every block it
        stages is pinned against ring demotion until the request admits
        (:meth:`finish_prefetch`) or cancels (:meth:`cancel_prefetch`),
        and the issue/complete/admit stamps feed the
        ``dynamo_kv_prefetch_*`` series and the bench overlap ratio."""
        if not seq_hashes:
            return
        state = None
        if request_id is not None:
            state = PrefetchState(hashes=list(seq_hashes))
            with self._lock:
                old = self._prefetch_states.pop(request_id, None)
                self._prefetch_states[request_id] = state
                self.prefetch_issued += len(seq_hashes)
            if old is not None:
                self._release_prefetch(old, wasted=True)
            self.metrics.prefetch_issued.inc(len(seq_hashes))
        self._ex.submit(self._prefetch, list(seq_hashes), request_id, state)

    def _prefetch(
        self,
        seq_hashes: List[int],
        request_id: Optional[str] = None,
        state: Optional[PrefetchState] = None,
    ) -> None:
        for h in seq_hashes:
            try:
                resident = self.host.get_ram(h) is not None
                if not resident:
                    if self.host.get(h) is None:
                        break
                    # a promote is NOT a hit: only lookups actually
                    # served count toward tier_hit_rate (the router
                    # warmth signal)
                    self.disk_promotes += 1
                    self.metrics.tier_promotes.labels("disk").inc()
                if state is not None:
                    # pin-and-record under the engine lock so a
                    # concurrent cancel (which pops the state under the
                    # same lock and unpins ``done``) cannot miss a pin
                    with self._lock:
                        if self._prefetch_states.get(
                            request_id
                        ) is state and self.host.pin(h):
                            state.done.add(h)
            except Exception:
                logger.debug("prefetch failed at %x", h, exc_info=True)
                break
        if state is not None:
            settle = False
            with self._lock:
                state.completed_at = time.perf_counter()
                if (
                    self._prefetch_states.get(request_id) is state
                    and state.admitted_at is not None
                ):
                    # admission landed mid-walk: settle the partial
                    # overlap now that the walk's end is known
                    self._prefetch_states.pop(request_id, None)
                    settle = True
            if settle:
                self._settle_prefetch(state)
        self._observe_occupancy()

    def finish_prefetch(
        self, request_id: str, consumed_hashes: List[int]
    ) -> int:
        """Admission landed: release the request's prefetch pins, count
        hits (staged blocks the admission actually onboarded) vs wasted
        bytes, and record the overlap ratio.  Returns the hit count (the
        admission-path span attr).  Safe to call for untracked ids."""
        with self._lock:
            state = self._prefetch_states.get(request_id)
            if state is None:
                return 0
            state.admitted_at = time.perf_counter()
            state.consumed = set(consumed_hashes)
            if state.completed_at is None:
                # walk still running: it settles the state at its end
                # (pins it takes after this point release there too)
                return len(state.done & state.consumed)
            self._prefetch_states.pop(request_id, None)
        return self._settle_prefetch(state)

    def cancel_prefetch(self, request_id: str) -> None:
        """A queued request left before admission (cancel / error): free
        its host-staged prefetch state -- unpin every staged block and
        charge the bytes as wasted.  Without this, pins from abandoned
        requests accumulate and the ring degenerates to unevictable.  A
        still-running walk stops pinning the moment the state is popped
        (it re-checks registration under the lock before every pin)."""
        with self._lock:
            state = self._prefetch_states.pop(request_id, None)
        if state is None:
            return
        self._release_prefetch(state, wasted=True)

    def _settle_prefetch(self, state: PrefetchState) -> int:
        """Settle one tracked walk's accounting and release its pins.
        Called from the offload thread (walk end) or the engine executor
        (admission) -- never while holding ``self._lock``; the plain-int
        aggregates update under it so concurrent settles cannot lose
        increments."""
        consumed = state.consumed or set()
        hits = len(state.done & consumed)
        wasted = len(state.done - consumed) * self.host.block_nbytes
        walk = (state.completed_at or state.issued_at) - state.issued_at
        ratio = None
        if walk > 0 and state.admitted_at is not None:
            ratio = min(
                max((state.admitted_at - state.issued_at) / walk, 0.0), 1.0
            )
        with self._lock:
            self.prefetch_hits += hits
            self.prefetch_wasted_bytes += wasted
            if ratio is not None:
                self.prefetch_overlap_sum += ratio
                self.prefetch_overlap_n += 1
        if hits:
            self.metrics.prefetch_hits.inc(hits)
        if wasted:
            self.metrics.prefetch_wasted.inc(wasted)
        if ratio is not None:
            self.metrics.prefetch_overlap.observe(ratio)
        for h in state.done:
            self.host.unpin(h)
        return hits

    def _release_prefetch(self, state: PrefetchState, wasted: bool) -> None:
        if wasted and state.done:
            nbytes = len(state.done) * self.host.block_nbytes
            with self._lock:
                self.prefetch_wasted_bytes += nbytes
            self.metrics.prefetch_wasted.inc(nbytes)
        for h in state.done:
            self.host.unpin(h)

    def contains(self, seq_hash: int) -> bool:
        return self.host.contains(seq_hash)

    def get_blocking(self, seq_hash: int) -> Optional[Tuple[np.ndarray, Any]]:
        """Tiered get from a worker thread (block export / donor paths):
        routes the possibly-disk read through the offload thread and
        waits for it, falling through to the G4 store when the local
        tiers miss (the fetch waits on the kv-remote thread -- a
        different executor, so no deadlock).  A G4 hit promotes into the
        host ring so the next lookup is a RAM hit.  Never call on the
        event loop."""
        hit = self._ex.submit(self.host.get, seq_hash).result()
        if (
            hit is None
            and self.remote is not None
            and self.remote.contains(seq_hash)
        ):
            fetched = self.remote.fetch_blocking(seq_hash)
            if fetched is not None:
                self.submit_put(seq_hash, fetched[0], fetched[1])
                hit = fetched
        return hit

    # -- swap records (preempted-sequence KV) --------------------------------

    def swap_out(
        self, request_id: str, snap: Any, cache_len: int, n_blocks: int,
        shards: Optional[Dict[str, int]] = None,
    ) -> bool:
        """Reserve budget and park a preemption snapshot.  The device copy
        is retained (within ``swap_device_blocks``) so a short park can
        restore without ever crossing the host link; the host materialize
        is queued as the spill.  Returns False (caller falls back to
        recompute) when swap is disabled, the budget is exhausted, or the
        ``offload.copy_fail`` chaos site fires -- tiers-full is a
        fallback, never an error."""
        from .runtime import faults

        if not self.swap_enabled:
            return False
        if faults.injector.enabled and faults.injector.should_fire(
            "offload.copy_fail", f"swap/{request_id}"
        ):
            # runs on the engine executor while the offload thread may be
            # bumping the same counters: lock-guard the increments (DT014)
            with self._lock:
                self.copy_fails += 1
                self.swap_fallbacks += 1
            self.metrics.copy_fails.inc()
            self.metrics.swap_fallbacks.labels("copy_fail").inc()
            return False
        keep_dev = self.swap_device_blocks > 0
        with self._lock:
            if request_id in self._swaps:
                return False  # defensive: one parked record per request
            if self._swap_used + n_blocks > self.swap_blocks:
                self.swap_fallbacks += 1
                self.metrics.swap_fallbacks.labels("budget").inc()
                return False
            self._swap_used += n_blocks
            if keep_dev:
                self._swap_dev_used += n_blocks
            self._swaps[request_id] = SwapRecord(
                cache_len=cache_len,
                n_blocks=n_blocks,
                shards=dict(shards) if shards else None,
                dev=snap if keep_dev else None,
            )
            self.swap_outs += 1
        self.metrics.swap_events.labels("out").inc()
        self._ex.submit(self._store_swap, request_id, snap)
        return True

    def _store_swap(self, request_id: str, snap: Any) -> None:
        with self._lock:  # racing drop_swap pops under the same lock
            rec = self._swaps.get(request_id)
        if rec is None:
            return  # dropped (cancel / already restored from the device copy)
        try:
            t0 = time.perf_counter()
            rec.blob = to_host(snap)
            rec.nbytes = rec.blob.nbytes
            dt = time.perf_counter() - t0
            rec.state = SWAP_READY
            with self._lock:
                self.offload_bytes += rec.nbytes
                self.offload_seconds += dt
            self.metrics.record_offload("swap", rec.nbytes, dt)
            # host spill landed: drop the device copy if the staging
            # budget is oversubscribed (long parks ride the host blob)
            with self._lock:
                if (
                    rec.dev is not None
                    and self._swap_dev_used > self.swap_device_blocks
                ):
                    rec.dev = None
                    self._swap_dev_used -= rec.n_blocks
        except Exception:
            logger.debug("swap store failed for %s", request_id, exc_info=True)
            rec.state = SWAP_FAILED
        finally:
            self._observe_occupancy()
            self._wake()

    def poll_swap(self, request_id: str) -> Optional[SwapRecord]:
        return self._swaps.get(request_id)

    def drop_swap(self, request_id: str) -> None:
        with self._lock:
            rec = self._swaps.pop(request_id, None)
            if rec is not None:
                self._swap_used -= rec.n_blocks
                if rec.dev is not None:
                    rec.dev = None
                    self._swap_dev_used -= rec.n_blocks
        if rec is not None:
            self._observe_occupancy()

    def record_onboard(self, tier: str, nbytes: int, seconds: float) -> None:
        """Called by the engine after an onboard scatter lands on device;
        feeds the ``kv_onboard_gbps`` accounting."""
        self.onboard_bytes += nbytes
        self.onboard_seconds += seconds
        d = self.onboard_detail.setdefault(tier, [0.0, 0.0])
        d[0] += nbytes
        d[1] += seconds
        if tier == "swap":
            self.swap_ins += 1
            self.metrics.swap_events.labels("in").inc()
        self.metrics.record_onboard(tier, nbytes, seconds)

    # -- observability -------------------------------------------------------

    def _observe_occupancy(self) -> None:
        with self._lock:  # _swap_used mutates under the lock on two roles
            swap_used = self._swap_used
        self.metrics.tier_blocks.labels("host").set(len(self.host))
        if self.disk is not None:
            self.metrics.tier_blocks.labels("disk").set(len(self.disk))
        self.metrics.tier_blocks.labels("swap").set(swap_used)

    @property
    def tier_hit_rate(self) -> float:
        """Fraction of tier lookups served from G2/G3 -- the router-facing
        warmth signal (a worker whose tiers keep hitting is a better home
        for repeat prefixes than a cold one)."""
        if not self.tier_lookups:
            return 0.0
        return min(
            (self.tier_hits["host"] + self.tier_hits["disk"])
            / self.tier_lookups,
            1.0,
        )

    def stats(self) -> Dict[str, Any]:
        out = dict(self.host.stats())
        out.update(
            offload_bytes=self.offload_bytes,
            offload_seconds=round(self.offload_seconds, 6),
            onboard_bytes=self.onboard_bytes,
            onboard_seconds=round(self.onboard_seconds, 6),
            onboard_detail={
                t: {"bytes": int(b), "seconds": round(s, 6)}
                for t, (b, s) in self.onboard_detail.items()
            },
            tier_hits=dict(self.tier_hits),
            tier_lookups=self.tier_lookups,
            disk_promotes=self.disk_promotes,
            swap_outs=self.swap_outs,
            swap_ins=self.swap_ins,
            swap_fallbacks=self.swap_fallbacks,
            onboard_fallbacks=self.onboard_fallbacks,
            swap_used_blocks=self._swap_used,
            copy_fails=self.copy_fails,
            prefetch_issued=self.prefetch_issued,
            prefetch_hits=self.prefetch_hits,
            prefetch_wasted_bytes=self.prefetch_wasted_bytes,
            prefetch_pinned_blocks=self.host.pinned_blocks,
        )
        if self.prefetch_overlap_n:
            out["prefetch_overlap_ratio"] = round(
                self.prefetch_overlap_sum / self.prefetch_overlap_n, 4
            )
        if self.onboard_seconds > 0:
            out["onboard_gbps"] = round(
                self.onboard_bytes / self.onboard_seconds / 1e9, 3
            )
        if self.remote is not None:
            out.update(self.remote.stats())
        return out
